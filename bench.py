"""Benchmark: greedy decode throughput on the local TPU chip.

Prints ONE JSON line:
    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

The metric mirrors BASELINE.json ("Llama-3 decode tokens/sec/chip"); the
baseline denominator is its v5p target of 50 tok/s/chip for 70B.  The
reference publishes no numbers of its own (BASELINE.md), so vs_baseline is
measured against that target.

The bench model is a ~1B-param Llama-3-architecture config (GQA 2:1, SwiGLU,
bf16) — the largest that comfortably fits a single v5e-lite chip with its KV
cache.  Decode throughput is measured over full-length generations with no
stop tokens, steady-state (after one compile warmup), batch 8.  The headline
value is the bf16-weight path (parity-honest vs the reference's fp32/bf16
serving); the int8 weight-only serving path is reported in `detail`.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Public TPU v5e (v5 lite) single-chip peaks; denominators for the
# utilization figures reported in `detail` (emitted as null when the
# device is not a v5 lite chip).
V5E_HBM_BYTES_PER_S = 819e9     # HBM bandwidth
V5E_BF16_FLOPS = 197e12         # MXU bf16 peak


# ---------------------------------------------------------------------------
# Regression gate: diff headline keys between two trajectory records
# ---------------------------------------------------------------------------

# Key-name direction classes for the --compare gate.  Throughput-ish
# keys regress DOWN, latency-ish keys regress UP; keys matching
# neither are reported but never gate (a mis-guessed direction must
# not fail CI).
_HIGHER_BETTER = (
    "per_s", "tok", "tflops", "gbps", "rate", "util", "goodput",
    "ceiling", "attain", "hit", "value", "vs_baseline",
)
_LOWER_BETTER = ("ms", "latency", "stall", "wait_", "overhead", "_s")


def _headline_keys(record: dict) -> dict:
    """Numeric headline keys of a BENCH_*/MULTICHIP_* record.

    Covers both record styles: proper numeric leaves of the JSON
    (dotted paths), and the older records whose bench stdout lives as
    a TRUNCATED string under "tail" — there, every '"key": number'
    fragment is recovered by regex (last occurrence wins).  Driver
    bookkeeping (rc / n / n_devices) never gates."""
    import re as _re

    skip = {"rc", "n", "n_devices", "devices"}
    out: dict = {}

    def walk(d, prefix=""):
        if isinstance(d, dict):
            for k, v in d.items():
                walk(v, f"{prefix}.{k}" if prefix else str(k))
        elif isinstance(d, str):
            for m in _re.finditer(
                r'"([A-Za-z0-9_]+)":\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)',
                d,
            ):
                if m.group(1) not in skip:
                    out[m.group(1)] = float(m.group(2))
        elif isinstance(d, (int, float)) and not isinstance(d, bool):
            if prefix.split(".")[-1] not in skip:
                out[prefix] = float(d)

    walk(record)
    return out


def compare_records(
    old: dict, new: dict, tolerance_pct: float = 5.0,
) -> dict:
    """Diff shared headline keys; a REGRESSION is a classified key
    moving in its worse direction by more than ``tolerance_pct``."""
    a, b = _headline_keys(old), _headline_keys(new)
    shared = sorted(set(a) & set(b))
    regressions, improvements, unclassified = [], [], []
    for k in shared:
        if a[k] == 0:
            continue
        rel = (b[k] - a[k]) / abs(a[k]) * 100.0
        low = k.lower()
        higher_better = any(t in low for t in _HIGHER_BETTER)
        lower_better = (
            not higher_better
            and any(t in low for t in _LOWER_BETTER)
        )
        entry = {
            "key": k, "old": a[k], "new": b[k],
            "delta_pct": round(rel, 2),
        }
        if higher_better and rel < -tolerance_pct:
            regressions.append(entry)
        elif lower_better and rel > tolerance_pct:
            regressions.append(entry)
        elif (higher_better or lower_better) and abs(rel) > tolerance_pct:
            improvements.append(entry)
        elif not (higher_better or lower_better) and abs(rel) > tolerance_pct:
            unclassified.append(entry)
    return {
        "shared_keys": len(shared),
        "tolerance_pct": tolerance_pct,
        "regressions": regressions,
        "improvements": improvements,
        "unclassified_changes": unclassified,
        "ok": not regressions,
    }


def compare_main() -> None:
    """``python bench.py --compare OLD.json [NEW.json]
    [--tolerance PCT]``: machine-check the bench trajectory — exits
    non-zero when a shared headline key regressed past tolerance.
    With NEW omitted, the newest record of OLD's family
    (BENCH_*/MULTICHIP_*) in OLD's directory stands in."""
    import glob as _glob
    import os as _os
    import sys as _sys

    argv = argv_rest = _sys.argv[1:]
    tol = 5.0
    if "--tolerance" in argv:
        i = argv.index("--tolerance")
        tol = float(argv[i + 1])
        # Drop the flag AND its value before the positional scan — a
        # bare "10" must not be mistaken for NEW.json.
        argv_rest = argv[:i] + argv[i + 2:]
    files = [
        a for a in argv_rest[argv_rest.index("--compare") + 1:]
        if not a.startswith("--")
    ][:2]
    if not files:
        raise SystemExit("--compare needs OLD.json [NEW.json]")
    old_path = files[0]
    if len(files) == 2:
        new_path = files[1]
    else:
        base = _os.path.basename(old_path)
        fam = base.split("_r")[0]
        cands = sorted(
            p for p in _glob.glob(_os.path.join(
                _os.path.dirname(old_path) or ".", f"{fam}_r*.json"
            )) if _os.path.abspath(p) != _os.path.abspath(old_path)
        )
        if not cands:
            raise SystemExit(f"no other {fam}_r*.json next to {old_path}")
        new_path = cands[-1]
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    result = compare_records(old, new, tolerance_pct=tol)
    result["old"], result["new"] = old_path, new_path
    print(json.dumps(result, indent=1))
    if result["shared_keys"] == 0:
        # Heterogeneous rounds (a CPU controller round vs a chip
        # round) share nothing — say so loudly but do not fail: the
        # gate is for same-shaped rounds.
        print("bench-compare: WARNING: no shared headline keys",
              file=_sys.stderr)
    if not result["ok"]:
        raise SystemExit(3)


def load_harness(params, config, *, n_slots=8, max_len=1024,
                 block_size=128, duration_s=6.0, max_requests=400,
                 interactive_frac=0.5, seed=0,
                 i_prompt=64, i_new=8, b_prompt=512, b_new=32):
    """Open-loop (Poisson-arrival) load sweep over the HTTP server —
    the closed loop for the overload controller (overload.py): offered
    request rate vs goodput and per-class TTFT/ITL SLO attainment.

    Three phases:
      1. CALIBRATE: a closed-loop drain measures the sustainable
         request rate, and a low-rate flood sets the TTFT SLO at
         8x its median TTFT (attainment ~1.0 when healthy, degrading
         under overload — the sweep's y-axis).
      2. SWEEP (``serving_goodput_vs_rate``): floods at {0.5, 1, 2, 4}x
         the sustainable rate, mixed interactive/batch traffic, ladder
         + priority classes ON.  Each point reports per-class served/
         refused/hung counts, TTFT percentiles, SLO attainment over
         served requests, and goodput tokens/s.
      3. A/B at 4x (``serving_overload_ladder_vs_static``): the same
         flood against priority_classes=off (the pre-PR-9 static
         max_queue 503) vs on — the record that the ladder holds
         interactive attainment where the static config collapses,
         with zero hung clients either way.  (4x, not 2x: the
         sustainable anchor reads conservative — see phase 3.)

    Pure host/HTTP-side measurement: the device work is the same
    serving stack every other bench drives."""
    from jax_llama_tpu.obs import Observability
    from jax_llama_tpu.overload import (
        open_loop_flood, poisson_schedule, summarize_flood,
    )
    from jax_llama_tpu.serving import ContinuousBatcher
    from jax_llama_tpu.server import LLMServer

    rng = np.random.RandomState(9000 + seed)
    V = config.vocab_size
    # Interactive: short chat-turn shape.  Batch: long-prompt bulk
    # shape — the cost asymmetry the static depth count cannot see.
    I_PROMPT, I_NEW = i_prompt, i_new
    B_PROMPT, B_NEW = b_prompt, b_new

    def payload_fn(i):
        # Golden-ratio stride: a deterministic, well-interleaved mix
        # at any fraction (blocks of one class would skew the short
        # floods below).
        interactive = (i * 0.6180339887) % 1.0 < interactive_frac
        if interactive:
            toks = rng.randint(1, V, I_PROMPT).tolist()
            return {"prompt": toks, "max_new_tokens": I_NEW,
                    "priority": "interactive", "stream": True,
                    "timeout_s": 30.0}
        toks = rng.randint(1, V, B_PROMPT).tolist()
        return {"prompt": toks, "max_new_tokens": B_NEW,
                "priority": "batch", "stream": True,
                "timeout_s": 30.0}

    def make_server(priority_on, slo_ttft_ms=None, slo_itl_ms=None):
        obs = Observability(slo_ttft_ms=slo_ttft_ms,
                            slo_itl_ms=slo_itl_ms)
        cb = ContinuousBatcher(
            params, config, n_slots=n_slots, max_len=max_len,
            block_size=block_size, decode_chunk=16, prefill_budget=512,
            obs=obs,
        )
        return LLMServer(
            cb, max_queue=64, priority_classes=priority_on,
            # React within the flood window: these are drill-scale
            # dwell/cooldown, not the production defaults.
            brownout_dwell_s=0.5, brownout_cooldown_s=2.0,
            watchdog_deadline_s=None,
        )

    # -- phases 0/1: warmup + calibrate -------------------------------------
    # The warmup burst compiles every program the floods will hit
    # (multi-row inserts, the K ramp, fused prefill chunks); the SAME
    # burst is then re-run timed for the sustainable rate, and a few
    # SEQUENTIAL interactive requests (no queueing) set the TTFT SLO
    # at 8x their median — attainment ~1.0 when healthy, degrading
    # under overload.  Controller OFF here: the drill-scale dwell
    # would let the ladder escalate (even shed) during the
    # compile-stalled warmup, leaving batch-shape programs uncompiled
    # and inflating the sustainable-rate anchor the whole sweep keys
    # off.
    n_cal = 2 * n_slots
    with make_server(False) as srv:
        open_loop_flood(
            srv.address, [0.0] * n_cal, payload_fn,
            timeout_s=600.0, join_timeout_s=900.0,
        )
        t0 = time.time()
        open_loop_flood(
            srv.address, [0.0] * n_cal, payload_fn,
            timeout_s=120.0, join_timeout_s=300.0,
        )
        cal_wall = time.time() - t0
        base_ttfts = []
        for j in range(4):
            r = open_loop_flood(
                srv.address, [0.0], lambda i: payload_fn(0),
                timeout_s=120.0, join_timeout_s=300.0,
            )[0]
            if r["ttft_ms"] is not None:
                base_ttfts.append(r["ttft_ms"])
    sustainable = n_cal / cal_wall
    base_ttfts.sort()
    base_ttft = (
        base_ttfts[len(base_ttfts) // 2] if base_ttfts else 100.0
    )
    # 8x the UNLOADED median: an SLO that is attainable (~1.0) at the
    # sustainable rate — normal queueing behind a few concurrent
    # requests costs several unloaded-TTFTs — so the sweep measures
    # overload degradation, not a bar nobody could hold (3x was
    # already missed at 1x offered load).
    slo_ttft_ms = max(50.0, round(8.0 * base_ttft, 1))

    # -- phase 2: rate sweep, ladder on -------------------------------------
    def flood(rate, priority_on):
        # Adaptive window: at least ~24 expected arrivals per point
        # (a 6 s window at a slow backend's sustainable rate would
        # sample almost nothing), capped so the sweep stays bounded.
        dur = min(60.0, max(duration_s, 24.0 / max(rate, 1e-6)))
        sched = poisson_schedule(rate, dur, seed=seed + 1)
        if len(sched) > max_requests:
            # Truncation shortens the real flood window: goodput and
            # the point's effective offered rate must be computed over
            # the span actually flooded, not the nominal one.
            sched = sched[:max_requests]
            dur = sched[-1]
        with make_server(priority_on, slo_ttft_ms=slo_ttft_ms) as srv:
            recs = open_loop_flood(
                srv.address, sched, payload_fn,
                timeout_s=60.0, join_timeout_s=240.0,
            )
            summary = summarize_flood(
                recs, slo_ttft_ms=slo_ttft_ms, duration_s=dur
            )
            h = srv.overload.health()
            summary["rung_final"] = h["rung"]
            summary["sheds"] = h["sheds_total"]
            summary["refused"] = dict(h["refused"])
        return summary

    sweep = {}
    for mult in (0.5, 1.0, 2.0, 4.0):
        sweep[f"x{mult:g}"] = flood(sustainable * mult, True)

    # -- phase 3: ladder vs static at 4x ------------------------------------
    # 4x, not 2x: the sustainable estimate comes from a closed-loop
    # burst drain and reads conservative, so 2x of it may not saturate
    # a fast backend at all — 4x reliably lands in the regime the
    # drill is about (the ISSUE criterion is ">= 2x").
    def _ab_view(s):
        return {
            "interactive_attainment": s["interactive"]["slo_attainment"],
            "interactive_ttft_ms_p99": s["interactive"]["ttft_ms_p99"],
            "interactive_served": s["interactive"]["served"],
            "batch_served": s["batch"]["served"],
            "batch_refused_503": s["batch"]["refused_503"],
            "timeouts_504": (
                s["interactive"]["timeout_504"] + s["batch"]["timeout_504"]
            ),
            "hung_total": s["hung_total"],
            "goodput_tokens_per_s": s.get("goodput_tokens_per_s"),
            "rung_final": s.get("rung_final"),
            "sheds": s.get("sheds"),
        }

    static = flood(sustainable * 4.0, False)
    return {
        "sustainable_req_per_s": round(sustainable, 2),
        "slo_ttft_ms": slo_ttft_ms,
        "mix": {
            "interactive": {"prompt": I_PROMPT, "max_new": I_NEW},
            "batch": {"prompt": B_PROMPT, "max_new": B_NEW},
            "interactive_frac": interactive_frac,
        },
        "duration_s": duration_s,
        "serving_goodput_vs_rate": sweep,
        "serving_overload_ladder_vs_static": {
            "offered_x_sustainable": 4.0,
            "ladder": _ab_view(sweep["x4"]),
            "static_max_queue": _ab_view(static),
        },
    }


def load_harness_main() -> None:
    """Standalone entry (``python bench.py --load-harness``): the
    open-loop overload sweep on a small model, printed as one JSON
    line.  CPU-safe — the harness measures controller behavior
    (attainment held, sheds clean, zero hangs), not chip throughput;
    the full-size TPU round embeds the same keys via main()."""
    import jax
    import jax_llama_tpu as jlt

    config = jlt.get_config(
        "llama3-8b",
        dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
        multiple_of=128, vocab_size=4096, max_seq_len=1024,
        param_dtype="float32" if jax.default_backend() == "cpu"
        else "bfloat16",
    )
    params = jlt.init_params(jax.random.PRNGKey(0), config)
    result = {
        "metric": "open-loop overload sweep (goodput + per-class SLO "
                  "attainment vs offered rate), small-model harness",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "params": jlt.param_count(params),
        # Lighter request shapes than the TPU round: the small-model
        # harness proves controller BEHAVIOR, and a CPU backend's
        # sustainable rate would make the full shapes crawl.
        "detail": load_harness(
            params, config, n_slots=4, b_prompt=256, b_new=16
        ),
    }
    print(json.dumps(result))


def multichip_serving_main(record_path=None) -> None:
    """``python bench.py --multichip-serving [--record PATH]``: the
    scale-out serving dryrun round (MULTICHIP_r06) on the forced
    8-host-device CPU mesh — no TPU pod required.  Three certs:

      1. **Sharded-chunk parity**: the mesh-placed batcher
         (``--serve-mesh 2,2`` geometry: KV pool head-sharded over
         tensor, state rows over data) serves a chunked + fused-
         admission mix TOKEN-IDENTICALLY to single-chip.
      2. **Sharded lowering contracts**: the analysis mesh pass
         (donated-leaf donor attributes + sharding stability) is clean
         for every registered mesh variant.
      3. **Routed-replica serving**: 2 LLMServer replicas behind a
         ReplicaRouter serve a concurrent burst token-identically to
         one replica, with the wall tokens/s recorded.

    CPU numbers measure BEHAVIOR, not chips — the throughput keys roll
    forward at the next TPU-attached round, like BENCH_r06 did for the
    overload controller."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

    import json as _json
    import threading
    import urllib.request

    import jax_llama_tpu as jlt
    from jax_llama_tpu.parallel.partition import shard_params
    from jax_llama_tpu.parallel.serve_mesh import (
        ServeMeshSpec, build_serve_mesh, mesh_shape,
    )
    from jax_llama_tpu.router import ReplicaRouter
    from jax_llama_tpu.server import LLMServer
    from jax_llama_tpu.serving import ContinuousBatcher

    n_devices = len(jax.devices())
    tail: list = []

    config = jlt.get_config(
        "tiny", vocab_size=512, dim=128, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=256,
        dtype="float32", param_dtype="float32",
    )
    params = jlt.init_params(jax.random.PRNGKey(0), config)

    # -- 1. sharded-chunk parity on the 2x2 serving mesh -------------------
    mesh = build_serve_mesh(
        ServeMeshSpec(data=2, tensor=2), devices=jax.devices()[:4]
    )
    sp = shard_params(params, mesh, config)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 512, size=n).tolist()
               for n in (12, 30, 48)]

    def serve(p, m):
        cb = ContinuousBatcher(
            p, config, n_slots=4, max_len=256, mesh=m,
            decode_chunk=8, prefill_budget=32,
        )
        rids = [cb.submit(pr, max_new_tokens=8, seed=7 + i)
                for i, pr in enumerate(prompts)]
        t0 = time.time()
        done = cb.run_to_completion()
        wall = time.time() - t0
        return [done[r] for r in rids], wall, cb

    base, _, _ = serve(params, None)
    sharded, _, cb = serve(sp, mesh)
    parity_ok = sharded == base and cb._mesh_placed
    tail.append(
        f"dryrun_multichip_serving ok: sharded chunk programs on "
        f"data=2 tensor=2 mesh token-identical={parity_ok} "
        f"({sum(map(len, sharded))} tokens)"
    )

    # -- 2. sharded lowering contracts (analysis mesh pass) -----------------
    from jax_llama_tpu.analysis.lowering import check_mesh_traces

    findings = check_mesh_traces()
    lowering_ok = not findings
    mesh_contracts = sorted(
        name for name, c in __import__(
            "jax_llama_tpu.analysis.contracts", fromlist=["REGISTRY"]
        ).REGISTRY.items() if c.mesh_build is not None
    )
    tail.append(
        f"dryrun_multichip_serving ok: mesh lowering contracts clean="
        f"{lowering_ok} ({len(mesh_contracts)} sharded programs: "
        f"{', '.join(mesh_contracts)})"
    )

    # -- 3. routed 2-replica serving vs 1 replica ---------------------------
    def mk_server(i):
        return LLMServer(
            ContinuousBatcher(
                params, config, n_slots=2, max_len=256, decode_chunk=8,
            ),
            replica_id=i,
        ).start()

    def post(url, payload):
        req = urllib.request.Request(
            url + "/generate", data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=600) as r:
            return _json.loads(r.read())

    burst = [
        {"prompt": prompts[i % len(prompts)], "max_new_tokens": 8,
         "seed": 100 + i}
        for i in range(6)
    ]

    def flood(url):
        out = [None] * len(burst)

        def one(i):
            out[i] = post(url, burst[i])["tokens"]

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(burst))]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out, time.time() - t0

    solo = mk_server(0)
    try:
        want, _ = flood(solo.address)
    finally:
        solo.stop()
    servers = [mk_server(i) for i in range(2)]
    router = ReplicaRouter(servers, policy="least-loaded").start()
    try:
        got, wall = flood(router.address)
        routed_ok = got == want
        toks = sum(len(t) for t in got if t)
        routed_tps = round(toks / max(wall, 1e-9), 2)
        h = router.health()
        both_served = all(
            r["routed_total"] > 0 for r in h["replicas"]
        )
        # -- fleet cache baseline (PR 13 telemetry): publish the SAME
        # chain on both replicas (direct posts — deterministic), then
        # scrape the router's fleet cache view.  The duplicate-chain
        # bytes are the number that justifies the cache-aware
        # disaggregation scheduler (ROADMAP item 2); the scrape cost
        # bounds what a scheduler tick would pay.
        shared = {"prompt": prompts[2], "max_new_tokens": 4, "seed": 3}
        for s in servers:
            post(s.address, shared)
        t0 = time.time()
        with urllib.request.urlopen(
            router.address + "/debug/kv/fleet", timeout=60
        ) as r:
            fleet_doc = _json.loads(r.read())
        fleet_scrape_ms = round((time.time() - t0) * 1000.0, 2)
        fl = fleet_doc["fleet"]
        fleet_ok = fl["duplicate_kv_bytes"] > 0 and (
            sorted(fl["replicas_scraped"]) == [0, 1]
        )
        per_replica_hit = {
            str(p["replica"]): p["hit_ratio"]
            for p in fleet_doc["replicas"]
        }
    finally:
        router.stop()
        for s in servers:
            s.stop()
    tail.append(
        f"dryrun_multichip_serving ok: routed 2-replica serving "
        f"token-identical={routed_ok}, both replicas served="
        f"{both_served}, {routed_tps} tok/s wall (CPU behavior round)"
    )
    tail.append(
        f"dryrun_multichip_serving ok: fleet cache view duplicate-"
        f"chain bytes={fl['duplicate_kv_bytes']} "
        f"({fl['duplicate_chains']} chains on both replicas), fleet "
        f"hit ratio={fl['prefix_hit_ratio']}, scrape="
        f"{fleet_scrape_ms} ms"
    )

    # -- 4. fleet-TTFT A/B: cache-aware vs least-loaded (r08) ---------------
    # Deterministic revisit-heavy workload: 4 sessions, each visited
    # 3 times with a growing prompt (the chat shape), posted
    # SEQUENTIALLY so routing policy is the only variable.  Under
    # least-loaded the revisits alternate replicas (half the turns
    # re-prefill cold); cache-aware routes each turn to the replica
    # holding the session's chain — the fleet prefix-hit-tokens ratio
    # is the headline, per-request wall time the TTFT proxy (CPU
    # behavior round; max_new=2 keeps the measurement
    # prefill-dominated).
    rng2 = np.random.RandomState(7)
    bases = [rng2.randint(1, 512, size=48).tolist() for _ in range(4)]
    turns = [
        [b + rng2.randint(1, 512, size=16 * k).tolist()
         for k in range(3)]
        for b in bases
    ]

    def fleet_ab(policy):
        servers = [
            LLMServer(
                ContinuousBatcher(
                    params, config, n_slots=2, max_len=256,
                    decode_chunk=8,
                ),
                replica_id=i,
            ).start()
            for i in range(2)
        ]
        router = ReplicaRouter(
            servers, policy=policy, health_interval_s=0,
            block_size=servers[0].batcher.block_size,
        ).start()
        lat: list = []
        try:
            # Warmup (compile paths) off the clock.
            post(router.address,
                 {"prompt": bases[0][:20], "max_new_tokens": 2})
            router.check_health_now()
            for round_i in range(3):
                for s, session_turns in enumerate(turns):
                    t0 = time.time()
                    post(router.address, {
                        "prompt": session_turns[round_i],
                        "max_new_tokens": 2, "seed": s,
                    })
                    lat.append((time.time() - t0) * 1000.0)
                router.check_health_now()
            router.wait_handoffs(30.0)
            hit = sum(
                s.batcher.prefix_hit_tokens_total for s in servers
            )
            prompt_t = sum(
                s.batcher.prompt_tokens_total for s in servers
            )
            with router._lock:
                handoffs = router.handoffs_completed_total
                stale = router.cache_stale_routes_total
            lat.sort()
            return {
                "fleet_prefix_hit_ratio": round(
                    hit / max(1, prompt_t), 6
                ),
                "prefix_hit_tokens_total": int(hit),
                "prompt_tokens_total": int(prompt_t),
                "ttft_ms_p50": round(lat[len(lat) // 2], 2),
                "ttft_ms_p99": round(lat[-1], 2),
                "handoffs_completed": int(handoffs),
                "stale_routes": int(stale),
            }, router, servers
        except BaseException:
            router.stop()
            for s in servers:
                s.stop()
            raise

    ll, ll_router, ll_servers = fleet_ab("least-loaded")
    ll_router.stop()
    for s in ll_servers:
        s.stop()
    ca, ca_router, ca_servers = fleet_ab("cache-aware")
    try:
        # Dedup-by-migration drill (the demote-after-export
        # acceptance): publish one FRESH chain on BOTH replicas
        # directly (fresh = no deeper session suffix hangs off it, so
        # the leaves-first source drop can actually release it), then
        # migrate it — fleet duplicate bytes must DECREASE (the
        # source demotes/drops its copy; the destination already
        # holding it makes the import a benign no-op).
        dup_tokens = rng2.randint(1, 512, size=48).tolist()
        dup_prompt = {"prompt": dup_tokens, "max_new_tokens": 2,
                      "seed": 99}
        for s in ca_servers:
            post(s.address, dup_prompt)
        ca_router.check_health_now()
        dup_before = ca_router.fleet_kv_json()["fleet"][
            "duplicate_kv_bytes"
        ]
        from jax_llama_tpu.router import chain_keys as _ck

        # "prompt" payloads admit the raw token list verbatim — the
        # chain keys recompute exactly.
        keys_hex = [
            k.hex() for k in _ck(
                dup_tokens, ca_servers[0].batcher.block_size,
            )
        ]
        ca_router.migrate_chain(keys_hex, src=0, dst=1)
        assert ca_router.wait_handoffs(30.0)
        dup_after = ca_router.fleet_kv_json()["fleet"][
            "duplicate_kv_bytes"
        ]
    finally:
        ca_router.stop()
        for s in ca_servers:
            s.stop()
    ab_ok = (
        ca["fleet_prefix_hit_ratio"] >= ll["fleet_prefix_hit_ratio"]
        and dup_after < dup_before
    )
    tail.append(
        f"dryrun_multichip_serving ok: fleet-TTFT A/B cache-aware "
        f"hit ratio={ca['fleet_prefix_hit_ratio']} vs least-loaded "
        f"{ll['fleet_prefix_hit_ratio']} (>= required: {ab_ok}), "
        f"ttft p50 {ca['ttft_ms_p50']} vs {ll['ttft_ms_p50']} ms, "
        f"duplicate bytes {dup_before} -> {dup_after} after "
        f"demote-after-export handoff"
    )

    ok = (
        parity_ok and lowering_ok and routed_ok and fleet_ok and ab_ok
    )
    result = {
        "n_devices": n_devices,
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "tail": "\n".join(tail) + "\n",
        "serving_mesh": {
            "mesh": mesh_shape(mesh),
            "sharded_chunk_token_identical": parity_ok,
            "mesh_lowering_contracts_clean": lowering_ok,
            "mesh_contract_programs": mesh_contracts,
            "routed_replicas": 2,
            "routed_token_identical": routed_ok,
            "routed_both_replicas_served": both_served,
            "routed_tokens_per_s_wall_cpu": routed_tps,
            "route_policy": "least-loaded",
            # Fleet cache baseline (router /debug/kv/fleet): the next
            # MULTICHIP round diffs these — duplicate-chain bytes are
            # the disaggregation scheduler's headline input.
            "fleet_kv": {
                "duplicate_chains": fl["duplicate_chains"],
                "fleet_duplicate_kv_blocks": fl["duplicate_kv_blocks"],
                "fleet_duplicate_kv_bytes": fl["duplicate_kv_bytes"],
                "fleet_prefix_hit_ratio": fl["prefix_hit_ratio"],
                "per_replica_hit_ratio": per_replica_hit,
                "digest_scrape_ms": fleet_scrape_ms,
                "fleet_view_nonzero_duplicates": fleet_ok,
            },
            # r08: globally cache-aware routing A/B on the
            # deterministic revisit-heavy workload — the hit-ratio
            # delta is the router-side radix index earning its keep;
            # the duplicate-bytes drop is the demote-after-export
            # handoff deduplicating the fleet.  CPU behavior round —
            # TTFT ms roll forward at the next TPU round.
            "fleet_ab_r08": {
                "workload": (
                    "4 sessions x 3 growing turns, sequential, "
                    "max_new=2"
                ),
                "cache_aware": ca,
                "least_loaded": ll,
                "duplicate_kv_bytes_before_handoff": dup_before,
                "duplicate_kv_bytes_after_handoff": dup_after,
                "cache_aware_ge_least_loaded": ab_ok,
            },
        },
    }
    print(_json.dumps(result))
    if record_path:
        with open(record_path, "w") as f:
            _json.dump(result, f, indent=1)
            f.write("\n")
    if not ok:
        # The certs are the point: a red parity/lowering/routing cert
        # must fail `make mesh-serve` (and any CI wiring), not just
        # print "ok": false.
        raise SystemExit(result["rc"])


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax_llama_tpu as jlt
    from jax_llama_tpu.engine import GenerationConfig, generate
    from jax_llama_tpu.ops.quant import quantize_params

    # param_dtype bf16: decode is HBM-bandwidth-bound, so serving keeps
    # weights in bf16 (2 bytes/param of traffic per step, not 4).
    config = jlt.get_config(
        "llama3-8b",
        dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        multiple_of=256, vocab_size=32000, max_seq_len=1024,
        param_dtype="bfloat16",
    )
    params = jlt.init_params(jax.random.PRNGKey(0), config)
    n_params = jlt.param_count(params)

    B, P, N = 8, 128, 128
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, config.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), dtype=bool)
    key = jax.random.PRNGKey(0)

    _salt = [0]

    def salted_key():
        """SALT every timed call (fold a counter into the PRNG key,
        unused under greedy): byte-identical repeated requests can be
        served from a cache under this environment's tunnel, silently
        corrupting min-of-N (see ROADMAP "SALT the inputs")."""
        _salt[0] += 1
        return jax.random.fold_in(key, _salt[0])

    def run(p, max_new: int) -> float:
        gc = GenerationConfig(
            max_new_tokens=max_new, temperature=0.0, stop_tokens=()
        )
        skey = salted_key()
        t0 = time.time()
        out = generate(p, tokens, mask, skey, config=config, gen_config=gc)
        # Sync via host transfer, NOT block_until_ready: under the axon
        # tunnel backend block_until_ready/effects_barrier return while the
        # computation is still in flight, and the [B, P+N] int32 fetch is
        # a few KB — negligible vs the decode itself.
        np.asarray(out)
        return time.time() - t0

    def measure(p):
        """Steady-state decode rate: the (prefill + N) vs (prefill + 1)
        difference cancels both prefill time and the constant per-call
        dispatch overhead of this environment's tunnel out of the metric.

        RANK-PAIRED MEDIAN differencing (r5; was min-of-5 on each
        side): the 5 full and 5 short timings are each sorted, paired
        BY RANK (k-th order statistic of one against the k-th of the
        other — the runs are independent, so there is no meaningful
        run-to-run pairing to preserve), and the median of those
        rank-matched differences is taken.  min-of-min composed two
        independent minima, and the full-run side occasionally
        produces an anomalously FAST outlier (r5 instrumented run:
        full samples [0.456, 0.491, 0.492, 0.493, 0.493] s — one
        35 ms-fast fluke against a 2 ms-tight cluster) which min()
        then selects, overstating the rate by ~9%.  The rank-paired
        median is outlier-robust and agreed with the jitter-immune
        xplane device rate to 0.2% in the same session (2728 vs 2734
        tok/s, vs min-of-min's 2985).  The returned fulls[0] /
        shorts[0] companions are each side's min-of-5 (reported for
        context, not inputs to the rate)."""
        fulls = sorted(run(p, N) for _ in range(5))
        shorts = sorted(run(p, 1) for _ in range(5))
        diffs = sorted(f - s for f, s in zip(fulls, shorts))
        decode_s = max(diffs[len(diffs) // 2], 1e-9)
        return B * (N - 1) / decode_s, decode_s, fulls[0], shorts[0]

    t0 = time.time()
    run(params, N)
    run(params, 1)
    compile_s = time.time() - t0

    toks_per_s, decode_s, full, short = measure(params)

    qparams = quantize_params(params)
    run(qparams, N)
    run(qparams, 1)
    int8_toks_per_s, _, _, _ = measure(qparams)

    # ------------------------------------------------------------------
    # Decode HBM roofline: modeled bytes/step ÷ measured step time.
    # Decode is bandwidth-bound, so bytes = weight traffic (every matmul
    # weight read once per step; the embedding table contributes only B
    # row lookups) + KV-cache read at the mean context length.  Writes
    # and activations are <1% at this scale and are not modeled.
    # ------------------------------------------------------------------
    is_v5e = "v5 lite" in str(jax.devices()[0]).lower()
    embed_entries = config.vocab_size * config.dim

    def modeled_step_bytes(weight_itemsize: float) -> float:
        """The one byte model both figures below read: weights once per
        step + bf16 KV at mean context."""
        weight_bytes = (n_params - embed_entries) * weight_itemsize
        mean_ctx = P + (N + 1) / 2
        kv_bytes = (
            2 * config.n_layers * B * mean_ctx
            * config.kv_heads * config.head_dim * 2  # bf16 cache
        )
        return weight_bytes + kv_bytes

    def hbm_util(weight_itemsize: float, per_step_s: float) -> float:
        return (
            modeled_step_bytes(weight_itemsize)
            / per_step_s / V5E_HBM_BYTES_PER_S
        )

    bf16_hbm = hbm_util(2.0, decode_s / (N - 1))
    int8_step_s = B / int8_toks_per_s
    int8_hbm = hbm_util(1.0, int8_step_s)

    def roofline_tps(weight_itemsize: float) -> float:
        """Decode ceiling if every modeled byte moved at the v5e HBM peak
        with zero other time.  Context for vs_baseline: the param-scaled
        50-tok/s target sits at ~100% of this ceiling for the bf16 B=8
        geometry — crossing ~0.95 vs_baseline means saturating the chip's
        memory system, not trimming overhead."""
        return B / (
            modeled_step_bytes(weight_itemsize) / V5E_HBM_BYTES_PER_S
        )

    # ------------------------------------------------------------------
    # Long-prompt prefill through the compiled Pallas flash kernel
    # (attn_impl="auto" resolves to flash for T>8).  A lax.scan over k
    # independent prefills amortizes this environment's ~100ms per-call
    # dispatch overhead; (k=3) - (k=1) differencing cancels the rest.
    # Small vocab keeps the [1, S, V] fp32 logits that force the
    # computation from dominating memory; FLOPs are counted causally
    # (half the S×S score/weight matmuls — the flash kernel's block
    # skip means executed FLOPs match this closely).
    # ------------------------------------------------------------------
    from jax import lax
    from jax_llama_tpu.models import forward as model_forward

    prefill_sources: list = []  # "xplane_device" | "wall" per measured S

    def prefill_tflops(S: int, impl: str):
        cfg = config.replace(
            vocab_size=512, max_seq_len=S, attn_impl=impl
        )
        pparams = jlt.init_params(jax.random.PRNGKey(1), cfg)
        pos = jnp.arange(S, dtype=jnp.int32)[None, :]

        def one(p, toks):
            logits, _ = model_forward(p, toks, pos, cfg)
            return logits.astype(jnp.float32).sum()

        @jax.jit
        def reps(p, toks_k):
            return lax.scan(
                lambda c, t: (c + one(p, t), None), jnp.float32(0), toks_k
            )[0]

        def timed(k):
            toks = jnp.asarray(
                rng.randint(0, cfg.vocab_size, (k, 1, S)), jnp.int32
            )
            float(reps(pparams, toks))  # compile warmup (per k: shapes differ)
            best = float("inf")
            for i in range(5):  # min-of-5: same jitter policy as decode
                # Salt: vary one token per repetition (anti-caching).
                toks = toks.at[0, 0, 0].set((i * 7 + 1) % cfg.vocab_size)
                t0 = time.time()
                float(reps(pparams, toks))
                best = min(best, time.time() - t0)
            return best

        # Device-time measurement preferred (r5, same rationale as the
        # decode headline): one traced k=1 run's summed device-op time
        # IS the prefill — no differencing, no dispatch to cancel, no
        # min-of-min outlier bias (the wall path read ~2% low vs the
        # device figures).  The wall differencing (two extra compiles +
        # ~20 prefill executions per size) runs ONLY as the fallback.
        per_prefill_s = None
        try:
            from jax_llama_tpu.utils.profiling import device_op_times

            toks1 = jnp.asarray(
                rng.randint(0, cfg.vocab_size, (1, 1, S)), jnp.int32
            )
            float(reps(pparams, toks1))  # compile warmup
            agg = device_op_times(
                lambda: float(reps(pparams, toks1)), by="op"
            )
            dev_s = sum(agg.values()) / 1e12
            if dev_s > 0:
                per_prefill_s = dev_s
        except Exception:
            pass
        if per_prefill_s is None:
            # Provenance must be visible: the wall path reads ~2% low,
            # so cross-environment comparisons need to know which path
            # produced the number (the detail dict records it).
            prefill_sources.append("wall")
            per_prefill_s = max((timed(3) - timed(1)) / 2, 1e-9)
        else:
            prefill_sources.append("xplane_device")

        D, L, F = cfg.dim, cfg.n_layers, cfg.ffn_dim
        kv = cfg.kv_heads * cfg.head_dim
        matmul = 2 * S * L * (2 * D * D + 2 * D * kv + 3 * D * F)
        attn = 2 * S * S * D * L  # causal: QK half + PV half
        head = 2 * S * D * cfg.vocab_size
        flops = matmul + attn + head
        return per_prefill_s, flops / per_prefill_s / 1e12

    flash8k_s, flash8k_tf = prefill_tflops(8192, "auto")
    flash16k_s, flash16k_tf = prefill_tflops(16384, "auto")
    flash32k_s, flash32k_tf = prefill_tflops(32768, "auto")

    # ------------------------------------------------------------------
    # Long-context decode (BASELINE config 4's 8k->32k story): B=1 with a
    # 16k-token context — chunked flash prefill, then append-free decode
    # over the full cache.  KV reads dominate weight reads at this length
    # (~1.07GB cache + 1.94GB weights per step).
    # ------------------------------------------------------------------
    CTX, NEW = 16256, 64
    lc_tokens = jnp.asarray(
        rng.randint(0, config.vocab_size, (1, CTX)), jnp.int32
    )
    lc_mask = jnp.ones((1, CTX), dtype=bool)

    def lc_run(max_new: int) -> float:
        gc = GenerationConfig(
            max_new_tokens=max_new, temperature=0.0, stop_tokens=(),
            prefill_chunk=2048,
        )
        t0 = time.time()
        np.asarray(generate(
            params, lc_tokens, lc_mask, salted_key(), config=config,
            gen_config=gc,
        ))
        return time.time() - t0

    lc_run(NEW); lc_run(1)
    lc_full = min(lc_run(NEW) for _ in range(3))
    lc_short = min(lc_run(1) for _ in range(3))
    lc_toks_per_s = (NEW - 1) / max(lc_full - lc_short, 1e-9)

    # ------------------------------------------------------------------
    # Continuous-batching serving throughput through the Pallas
    # paged-attention decode kernel (block-table pool, 8 slots, ~1k-token
    # contexts).  Wall-clock; min-of-3 full drains.  The 8 submits are
    # admitted as ONE batched prefill dispatch (burst admission), and
    # the HEADLINE runs CHUNKED decode (decode_chunk=16: up to 16 fused
    # decode iterations per dispatch, host state device-resident) — each
    # dispatch costs ~100 ms of tunnel latency here, so the K=1 loop was
    # ~96% host overhead (BENCH_r05: 68 tok/s wall vs 1800 device).  The
    # K sweep below records where that gap goes.
    # ------------------------------------------------------------------
    from jax_llama_tpu.serving import ContinuousBatcher

    def serve_run(decode_chunk=16, p=params, **ctor_kw):
        # prefill_budget mirrors the run.py serving default (fused
        # prefill-decode scheduling); this COLD burst still admits
        # through the classic batched insert — nobody is decoding yet —
        # so the number stays comparable to r05's.  ctor_kw forwards
        # kernel-selection overrides (prefill_kernel / decode_kernel,
        # ops/kernels.py) for the A/B sections below.
        cb = ContinuousBatcher(
            p, config, n_slots=8, max_len=1024, block_size=128,
            decode_chunk=decode_chunk, prefill_budget=512, **ctor_kw,
        )
        _salt[0] += 1
        srng = np.random.RandomState(1000 + _salt[0])  # salted prompts
        for _ in range(8):
            # 850 tokens pad to 7 blocks (896); +48 stays within 1024.
            cb.submit(list(srng.randint(1, config.vocab_size, 850)),
                      max_new_tokens=48)
        t0 = time.time()
        first = cb.step()          # burst admission + first decode step
        admit_s = time.time() - t0
        emitted = len(first)
        while cb.pending():
            emitted += len(cb.step())
        return time.time() - t0, emitted, admit_s

    serve_run()  # compile warmup (insert + chunk programs, K ramp)
    serve_best, serve_toks, admit_s = min(serve_run() for _ in range(3))
    paged_serving_toks_per_s = serve_toks / serve_best

    # Decode-chunk K sweep (wall tok/s at K ∈ {1, 4, 8, 16}): the perf
    # trajectory's record of how much of the host-overhead gap each
    # chunk size closes.  K=16 is the headline above (min-of-3); the
    # smaller Ks run min-of-2 (the K=1 drain alone is ~5 s here).
    chunk_sweep = {"K16": round(paged_serving_toks_per_s, 2)}
    for K in (1, 4, 8):
        t_k, n_k, _ = min(serve_run(decode_chunk=K) for _ in range(2))
        chunk_sweep[f"K{K}"] = round(n_k / t_k, 2)

    # int8 WEIGHT-only serving (reachable via run.py --quantize but
    # never benched through the batcher before r06: the serving benches
    # only ever measured int8 KV): the same burst drain on
    # quantize_params weights — decode is weight-bandwidth-bound, so
    # this is the serving-path realization of the standalone int8
    # decode win.
    serve_run(p=qparams)  # warmup (int8 insert + chunk programs)
    i8_t, i8_n, _ = min(serve_run(p=qparams) for _ in range(2))
    paged_serving_int8w_toks_per_s = i8_n / i8_t

    # ------------------------------------------------------------------
    # Decode-kernel A/B (ops/kernels.py selection layer): the same
    # 8-slot burst drain through each decode attention path —
    #   paged        the custom block-table Pallas kernel (headline),
    #   stock_paged  the stock Pallas paged-attention kernel
    #                (--decode-kernel stock-paged; T=1 steps only, the
    #                fused-chunk prefill rows keep flash),
    #   gathered     the XLA dense-gather view (--decode-kernel
    #                gathered, i.e. use_pallas_kernel=False).
    # Wall tok/s, min-of-2 drains; key names embed tok_per_s so
    # --compare classifies regressions in the right direction.  A
    # kernel that fails to resolve on this backend records null
    # rather than killing the round.
    # ------------------------------------------------------------------
    decode_kernel_ab: dict = {
        "paged_tok_per_s": round(paged_serving_toks_per_s, 2),
    }
    for kname, ab_kw in (
        ("stock_paged", dict(decode_kernel="stock-paged")),
        ("gathered", dict(decode_kernel="gathered")),
    ):
        try:
            serve_run(**ab_kw)  # warmup (kernel-specific chunk programs)
            ab_t, ab_n, _ = min(serve_run(**ab_kw) for _ in range(2))
            decode_kernel_ab[f"{kname}_tok_per_s"] = round(ab_n / ab_t, 2)
        except Exception as e:  # pragma: no cover - backend-dependent
            print(f"decode_kernel_ab[{kname}] skipped: {e}",
                  file=sys.stderr)
            decode_kernel_ab[f"{kname}_tok_per_s"] = None

    # ------------------------------------------------------------------
    # Prefill-kernel sweep (ops/kernels.py): flash vs splash-mha TFLOPs
    # at 8k/16k/32k prompts.  The flash_prefill_* figures above run the
    # CACHELESS model forward, which the splash path never sees (splash
    # lands only on the serving cache-insert dispatch), so BOTH arms
    # here time the whole-prompt insert through a 1-slot batcher —
    # identical FLOP accounting, identical path, only the kernel
    # differs.  Head FLOPs are excluded (the insert samples one row);
    # the figures are therefore comparable to each other, not to
    # flash_prefill_*_tflops.
    # ------------------------------------------------------------------
    def insert_prefill_tflops(S: int, prefill_kernel: str):
        icfg = config.replace(vocab_size=512, max_seq_len=S + 128)
        ip = jlt.init_params(jax.random.PRNGKey(1), icfg)
        cb = ContinuousBatcher(
            ip, icfg, n_slots=1, max_len=S + 128, block_size=128,
            decode_chunk=1, prefill_budget=0,
            prefill_kernel=prefill_kernel,
        )

        def one():
            cb.submit(list(rng.randint(1, icfg.vocab_size, S)),
                      max_new_tokens=2)
            t0 = time.time()
            cb.step()  # whole-prompt insert + first decode step
            dt = time.time() - t0
            while cb.pending():
                cb.step()
            return dt

        one()  # compile warmup
        best = min(one() for _ in range(3))
        D, L, F = icfg.dim, icfg.n_layers, icfg.ffn_dim
        kvw = icfg.kv_heads * icfg.head_dim
        flops = (2 * S * L * (2 * D * D + 2 * D * kvw + 3 * D * F)
                 + 2 * S * S * D * L)  # causal attn: QK half + PV half
        return best, flops / max(best, 1e-9) / 1e12

    prefill_kernel_sweep: dict = {}
    for S_pf, tag in ((8192, "8k"), (16384, "16k"), (32768, "32k")):
        for kname in ("flash", "splash"):
            try:
                _, pf_tf = insert_prefill_tflops(S_pf, kname)
                prefill_kernel_sweep[f"{kname}_{tag}_tflops"] = (
                    round(pf_tf, 1)
                )
            except Exception as e:  # pragma: no cover - backend-dependent
                print(f"prefill_kernel_sweep[{kname}_{tag}] skipped: {e}",
                      file=sys.stderr)
                prefill_kernel_sweep[f"{kname}_{tag}_tflops"] = None

    # ------------------------------------------------------------------
    # Fused prefill-decode scheduling: TTFT / ITL under a MIXED workload
    # — 4 decode-heavy residents, then a burst of 3 long prompts lands
    # mid-decode.  Classic admission (prefill_budget=0) stalls every
    # resident for each whole-prompt prefill dispatch and collapses the
    # decode chunk to K=1 right after; the fused scheduler
    # (run.py --prefill-budget, default 512) advances the prompt inside
    # the decode chunks instead.  serving_ttft_ms is submit -> first
    # token of the burst requests; serving_itl_p99_ms is the residents'
    # inter-token gap while the burst is being admitted (the stall
    # shows up as a fat ITL tail).  The budget sweep records both at
    # B ∈ {0 = classic, 128, 512}.
    # ------------------------------------------------------------------
    def mixed_run(prefill_budget):
        cb = ContinuousBatcher(
            params, config, n_slots=8, max_len=1024, block_size=128,
            decode_chunk=16, prefill_budget=prefill_budget,
        )
        _salt[0] += 1
        srng = np.random.RandomState(3000 + _salt[0])
        residents = [
            cb.submit(list(srng.randint(1, config.vocab_size, 100)),
                      max_new_tokens=160)
            for _ in range(4)
        ]
        for _ in range(4):
            cb.step()  # residents admitted (cold, classic) + K ramp
        burst, t_sub, ttft = [], {}, {}
        for _ in range(3):
            rid = cb.submit(
                list(srng.randint(1, config.vocab_size, 850)),
                max_new_tokens=16,
            )
            t_sub[rid] = time.time()
            burst.append(rid)
        last_seen: dict = {r: None for r in residents}
        itl_gaps = []
        while cb.pending():
            evs = cb.step()
            now = time.time()
            burst_inflight = any(r not in ttft for r in burst)
            for rid, _tok, _done in evs:
                if rid in t_sub and rid not in ttft:
                    ttft[rid] = (now - t_sub[rid]) * 1000.0
                if rid in last_seen:
                    if last_seen[rid] is not None and burst_inflight:
                        itl_gaps.append(
                            (now - last_seen[rid]) * 1000.0
                        )
                    last_seen[rid] = now
        return (
            sorted(ttft.values()),
            itl_gaps,
            cb.stats()["decode_stall_ms_total"],
        )

    mixed_run(512)  # warmup (fused-chunk programs at the 512 budget)
    budget_sweep = {}
    serving_ttft = serving_itl_p99 = None
    for budget in (0, 128, 512):
        ttfts, gaps, stall_ms = mixed_run(budget)
        entry = {
            "ttft_ms_p50": round(float(np.percentile(ttfts, 50)), 1),
            "ttft_ms_p99": round(float(np.percentile(ttfts, 99)), 1),
            "itl_ms_p99": (
                round(float(np.percentile(gaps, 99)), 1) if gaps else None
            ),
            "decode_stall_ms": round(stall_ms, 1),
        }
        budget_sweep[f"B{budget}"] = entry
        if budget == 512:  # the headline serving config (run.py default)
            serving_ttft = {
                "p50": entry["ttft_ms_p50"], "p99": entry["ttft_ms_p99"]
            }
            serving_itl_p99 = entry["itl_ms_p99"]

    # ------------------------------------------------------------------
    # Multi-turn chat at KV-capacity scale (kvcache.py, r06): the radix
    # prefix index + host-DRAM block tier on the chat pattern the north
    # star cares about — thousands of sessions sharing system prompts
    # and resuming after idling out of HBM.
    #
    # chat_prefix_hit_ttft_ms: TTFT p50/p99 of a turn whose cached
    # prefix covers {0, 25, 75}% of the prompt (hit depth sweep; depth
    # 0 is the cold-prefill baseline and the deeper hits' win is pure
    # skipped prefill).  Prompt: 512 tokens against a warm pool with a
    # decoding resident, admitted through the fused prefill lane — the
    # run.py serving configuration.
    #
    # sessions_resident_max: how many 512-token sessions' KV one pool
    # can keep addressable with vs without the host tier — the
    # capacity multiplier (without: the HBM pool's idle LRU depth;
    # with: HBM + host tier, revisits restoring through the
    # ``restoring`` admission state).
    # ------------------------------------------------------------------
    def chat_bench():
        P = 512                      # chat prompt (4 blocks of 128)
        depths = {"d0": 0, "d25": 128, "d75": 384}  # block multiples
        ttft = {}
        for label, depth in depths.items():
            cb = ContinuousBatcher(
                params, config, n_slots=8, max_len=1024, block_size=128,
                decode_chunk=16, prefill_budget=512, prefix_cache=True,
            )
            _salt[0] += 1
            srng = np.random.RandomState(5000 + _salt[0])
            shared = list(srng.randint(1, config.vocab_size, depth))
            # Seed the shared prefix chain (one completed turn), then
            # hold a decoding resident so probes admit FUSED.
            if depth:
                cb.submit(shared + [7], max_new_tokens=2)
                while cb.pending():
                    cb.step()
            cb.submit(list(srng.randint(1, config.vocab_size, 64)),
                      max_new_tokens=512)
            cb.step(); cb.step(); cb.step()
            samples = []
            for _ in range(8):
                probe = shared + list(
                    srng.randint(1, config.vocab_size, P - depth)
                )
                t0 = time.time()
                rid = cb.submit(probe, max_new_tokens=4)
                first = None
                while first is None:
                    for ev in cb.step():
                        if ev[0] == rid:
                            first = time.time()
                            break
                samples.append((first - t0) * 1000.0)
                while any(
                    s is not None and s.request_id == rid
                    for s in cb.slots.values()
                ):
                    cb.step()
            ttft[label] = {
                "p50": round(float(np.percentile(samples, 50)), 1),
                "p99": round(float(np.percentile(samples, 99)), 1),
            }

        def resident_sessions(host_blocks):
            # 16-block pool (4 sessions' chains max in HBM); sessions
            # are revisited oldest-first, so WITHOUT the tier the LRU
            # has always just dropped the one being asked for.
            cb = ContinuousBatcher(
                params, config, n_slots=2, max_len=1024, block_size=128,
                n_blocks=16, decode_chunk=16, prefix_cache=True,
                host_kv_blocks=host_blocks,
            )
            _salt[0] += 1
            srng = np.random.RandomState(7000 + _salt[0])
            sessions = [
                list(srng.randint(1, config.vocab_size, P))
                for _ in range(8)
            ]
            for s in sessions:
                cb.submit(list(s), max_new_tokens=4)
                while cb.pending():
                    cb.step()
            h0 = cb.stats()["prefix_requests_hit_total"]
            for s in sessions:   # revisit every session, oldest first
                cb.submit(list(s), max_new_tokens=4)
                while cb.pending():
                    cb.step()
            hits = cb.stats()["prefix_requests_hit_total"] - h0
            # Sessions still addressable = revisits that hit (HBM or
            # restored from the tier) instead of cold re-prefilling.
            return hits, cb.stats()["swap_ins_total"]

        no_tier_hits, _ = resident_sessions(0)
        tier_hits, tier_swap_ins = resident_sessions(64)
        return ttft, {
            "hbm_only": int(no_tier_hits),
            "with_host_tier": int(tier_hits),
            "tier_swap_ins": int(tier_swap_ins),
        }

    chat_bench()  # warmup (suffix-insert + fused-walk + restore programs)
    chat_ttft, sessions_resident = chat_bench()

    # ------------------------------------------------------------------
    # Overload: open-loop (Poisson) load sweep through the HTTP server
    # (overload.py, r06) — goodput + per-class TTFT SLO attainment vs
    # offered rate, and the ladder-vs-static A/B at 4x the sustainable
    # rate (the drill the brownout ladder exists to win: interactive
    # attainment held, batch shed cleanly, zero hung clients).
    # ------------------------------------------------------------------
    try:
        overload_sweep = load_harness(params, config)
    except Exception as e:  # the headline numbers must survive
        overload_sweep = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ------------------------------------------------------------------
    # Speculative serving.  The draft is the target NUDGED by ~2%
    # deterministic relative noise (below): acceptance stays high — the
    # regime speculative decoding targets — but strictly < 1, so the
    # Leviathan reject/replacement path is actually exercised (the old
    # self-draft setup reported spec_serving_kernel_acceptance 1.0 on
    # the gathered path and its "kernel acceptance < 1" was a bf16
    # tiling artifact, not a verified rejection).  The HEADLINE runs
    # FUSED rounds (spec_rounds=8: up to 8 draft+verify rounds per
    # jitted dispatch with batcher state device-resident — BENCH_r05
    # measured the per-round loop at 46.3 tok/s wall vs 927.4 device,
    # ~20x host/tunnel overhead, the worst gap in the repo);
    # spec_serving_rounds_sweep records where that gap goes.  Kernel vs
    # gathered-view fallback at IDENTICAL block size and pool geometry,
    # as before.
    # ------------------------------------------------------------------
    import zlib

    def _perturbed_draft(p):
        """±2% relative Gaussian nudge on every float leaf, keyed by a
        stable per-leaf path hash (crc32, NOT Python's salted hash()):
        a deterministic draft that closely tracks the target without
        equalling it — the same logits family, slightly wrong."""
        base_key = jax.random.PRNGKey(42)

        def nudge(path, x):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                return x
            key = jax.random.fold_in(
                base_key,
                zlib.crc32(jax.tree_util.keystr(path).encode())
                & 0x7FFFFFFF,
            )
            noise = jax.random.normal(key, x.shape, jnp.float32)
            return (
                x.astype(jnp.float32) * (1.0 + 0.02 * noise)
            ).astype(x.dtype)

        return jax.tree_util.tree_map_with_path(nudge, p)

    draft_params = _perturbed_draft(params)

    def spec_run(use_kernel, spec_rounds=8):
        cb = ContinuousBatcher(
            params, config, n_slots=4, max_len=1024, block_size=128,
            draft_params=draft_params, draft_config=config, n_draft=3,
            use_pallas_kernel=use_kernel, spec_rounds=spec_rounds,
        )
        _salt[0] += 1
        srng = np.random.RandomState(2000 + _salt[0])  # salted prompts
        for _ in range(4):
            cb.submit(list(srng.randint(1, config.vocab_size, 500)),
                      max_new_tokens=48)
        t0 = time.time()
        emitted = 0
        while cb.pending():
            emitted += len(cb.step())
        return time.time() - t0, emitted, cb.stats()["draft_acceptance_rate"]

    spec_run(True)  # warmup (insert + fused-round programs, R ramp)
    sk_t, sk_n, spec_kernel_accept = min(spec_run(True) for _ in range(3))
    spec_kernel_toks_per_s = sk_n / sk_t
    spec_run(False)  # warmup
    sg_t, sg_n, spec_gathered_accept = min(spec_run(False) for _ in range(3))
    spec_gathered_toks_per_s = sg_n / sg_t

    # Spec-rounds sweep (wall tok/s at R ∈ {1, 2, 4, 8}, kernel path):
    # R1 reproduces the pre-fusion one-dispatch-per-round loop — the
    # r05 46.3 tok/s baseline — so R8/R1 is the dispatch-amortization
    # win.  R8 is the headline above (min-of-3); smaller Rs min-of-2.
    spec_rounds_sweep = {"R8": round(spec_kernel_toks_per_s, 2)}
    for R in (1, 2, 4):
        t_r, n_r, _ = min(spec_run(True, spec_rounds=R) for _ in range(2))
        spec_rounds_sweep[f"R{R}"] = round(n_r / t_r, 2)

    # Larger serving batch (B=16): decode is weight-bandwidth-bound, so
    # tokens/sec/chip scales with rows — extra evidence beyond the
    # fixed-B=8 headline (kept at 8 for r1/r2 comparability).
    tokens16 = jnp.asarray(
        rng.randint(0, config.vocab_size, (16, P)), jnp.int32
    )
    mask16 = jnp.ones((16, P), dtype=bool)

    def run16(max_new):
        gc = GenerationConfig(
            max_new_tokens=max_new, temperature=0.0, stop_tokens=()
        )
        t0 = time.time()
        out = generate(
            params, tokens16, mask16, salted_key(), config=config,
            gen_config=gc,
        )
        np.asarray(out)
        return time.time() - t0

    run16(N)
    run16(1)
    full16 = min(run16(N) for _ in range(5))
    short16 = min(run16(1) for _ in range(5))
    b16_toks_per_s = 16 * (N - 1) / max(full16 - short16, 1e-9)

    # ------------------------------------------------------------------
    # Decode step breakdown from an xplane trace (device-op time per
    # decode step, bucketed by HLO source attribution).  Optional: if the
    # profiler/proto stack is unavailable the bench still emits its line.
    # ------------------------------------------------------------------
    step_breakdown = None
    device_toks_per_s = None
    int8_device_toks_per_s = None
    b16_device_toks_per_s = None
    lc_device_toks_per_s = None
    lc_int8kv_device_toks_per_s = None
    serve_device = None
    spec_device = None
    hbm_ceiling_tps = None
    hbm_ceiling_gbps = None
    hbm_ceiling_tps_int8 = None
    lc_serving = None
    train_metrics = None
    try:

        # The framework's own measurement primitive (see its docstring
        # for the tunnel-vs-device rationale).
        from jax_llama_tpu.utils.profiling import device_op_times

        def _trace_device_ps(
            max_new: int, p=None, toks=None, msk=None, cfg=None,
            prefill_chunk=None,
        ):
            """Sum of device-op time (ps) for one traced generate call,
            bucketed by HLO source file.  Defaults to the headline bf16
            B=8 geometry; the int8 / B=16 / long-context companions pass
            their own operands."""
            gcN = GenerationConfig(
                max_new_tokens=max_new, temperature=0.0, stop_tokens=(),
                **(
                    {"prefill_chunk": prefill_chunk}
                    if prefill_chunk else {}
                ),
            )
            p = params if p is None else p
            toks = tokens if toks is None else toks
            msk = mask if msk is None else msk
            cfg = config if cfg is None else cfg

            def go():
                np.asarray(generate(
                    p, toks, msk, salted_key(), config=cfg,
                    gen_config=gcN,
                ))

            go()  # warmup outside the trace
            return device_op_times(go, by="source")

        def _device_decode_rate(rows: int, **kw):
            """Jitter-immune decode tokens/s: device-op time differenced
            between 32- and 1-token traced runs (31 steady-state steps)."""
            aN = _trace_device_ps(32, **kw)
            a1 = _trace_device_ps(1, **kw)
            step_ps = (sum(aN.values()) - sum(a1.values())) / 31
            return rows / (step_ps / 1e12) if step_ps > 0 else None

        agg32 = _trace_device_ps(32)
        step_breakdown = {
            src: round(ps / 1e6 / 32, 1)  # us per decode step (32-amortized)
            for src, ps in agg32.most_common(8)
        }
        try:
            # Device-time decode throughput: differencing two traced runs
            # (32 vs 1 new tokens) cancels the prefill, leaving 31 steps
            # of pure device-op time.  Unlike the wall-clock headline
            # this is immune to host/tunnel jitter and any device
            # time-sharing — wall-clock runs of IDENTICAL code have
            # measured 2.6-3.05 ms/step across sessions while this
            # figure stayed put to 0.01%.  A second-trace failure only
            # loses this figure, not the breakdown above.
            agg1 = _trace_device_ps(1)
            step_ps = (sum(agg32.values()) - sum(agg1.values())) / 31
            if step_ps > 0:
                device_toks_per_s = B / (step_ps / 1e12)
            # Differenced per-step breakdown: the 32-amortized figures
            # above still carry prefill ops in each bucket; subtracting
            # the 1-step trace cancels them exactly.  Rank and clamp on
            # the DIFFERENCED values (a prefill-dominated bucket can
            # difference to ~0 or jitter negative and must not displace a
            # real decode bucket).
            diffed = {
                src: max(agg32.get(src, 0) - agg1.get(src, 0), 0) / 1e6 / 31
                for src in set(agg32) | set(agg1)
            }
            step_breakdown = {
                src: round(us, 1)
                for src, us in sorted(
                    diffed.items(), key=lambda kv: -kv[1]
                )[:8]
            }
        except Exception:
            pass

        # --------------------------------------------------------------
        # Device-time companions for every wall decode figure (VERDICT
        # r4 item 1: the wall headline rode a min-of-min artifact; these
        # are the jitter-immune numbers the headline now prefers).  Each
        # is independent — a failure loses only its own field.
        # --------------------------------------------------------------
        try:
            # The breakdown section above usually already produced the
            # bf16 figure from its own agg32/agg1 differencing — don't
            # re-trace (4 extra generates) or risk clobbering a valid
            # value with a jittered None.
            if device_toks_per_s is None:
                device_toks_per_s = _device_decode_rate(B)
        except Exception:
            pass
        try:
            int8_device_toks_per_s = _device_decode_rate(B, p=qparams)
        except Exception:
            pass
        try:
            b16_device_toks_per_s = _device_decode_rate(
                16, toks=tokens16, msk=mask16
            )
        except Exception:
            pass
        # Long-context (16k B=1) decode: bf16 vs int8 KV (VERDICT r4
        # item 4 — the KV stream is the marginal byte at this length;
        # r5 probe measured 5.10 -> 4.66 ms/step, +9.4%).
        try:
            lc_device_toks_per_s = _device_decode_rate(
                1, toks=lc_tokens, msk=lc_mask, prefill_chunk=2048
            )
            lc_int8kv_device_toks_per_s = _device_decode_rate(
                1, toks=lc_tokens, msk=lc_mask, prefill_chunk=2048,
                cfg=config.replace(kv_cache_dtype="int8"),
            )
        except Exception:
            pass

        # --------------------------------------------------------------
        # MEASURED HBM ceiling: stream the exact bytes the roofline model
        # counts (every non-embedding weight leaf once + a bf16 buffer
        # sized to the KV read at mean context) through fp32 sum
        # reductions, and take pure device time from the trace.  This
        # turns the decode denominator into an observed number: on this
        # chip pure streaming reads move at ~90% of the 819 GB/s
        # nameplate (leaf granularity; a single contiguous 2 GB sum
        # reaches ~92%), so "decode / measured ceiling" is the honest
        # utilization — the modeled figure understates it by ~10%.
        # --------------------------------------------------------------
        try:
            leaves = [
                leaf
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    params
                )
                if "embed" not in jax.tree_util.keystr(path)
            ]
            mean_ctx = P + (N + 1) / 2
            kv_entries = int(
                2 * config.n_layers * B * mean_ctx
                * config.kv_heads * config.head_dim
            )
            kv_buf = jax.random.normal(
                jax.random.PRNGKey(2), (kv_entries,), dtype=jnp.bfloat16
            )

            @jax.jit
            def _stream(ls, kv):
                acc = jnp.float32(0)
                for leaf in ls:
                    acc += jnp.sum(leaf.astype(jnp.float32))
                return acc + jnp.sum(kv.astype(jnp.float32))

            def _stream_ceiling(ls):
                nbytes = sum(
                    l.size * l.dtype.itemsize for l in ls
                ) + kv_buf.size * 2
                float(_stream(ls, kv_buf))  # warmup
                agg = device_op_times(
                    lambda: float(_stream(ls, kv_buf)), by="op"
                )
                t = sum(agg.values()) / 1e12
                return B / t, nbytes / t / 1e9

            hbm_ceiling_tps, hbm_ceiling_gbps = _stream_ceiling(leaves)
            qleaves = [
                leaf
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    qparams
                )
                if "embed" not in jax.tree_util.keystr(path)
            ]
            hbm_ceiling_tps_int8, _ = _stream_ceiling(qleaves)
        except Exception:
            pass
        finally:
            # Drop the probe buffers AND the int8 param copy (the
            # ceiling probe is its last consumer): together ~1.1 GB of
            # HBM the later sections — the 6 GB training state
            # especially — need.  In a finally so a failure above can't
            # leak them into the training section and masquerade as an
            # unrelated training OOM.
            leaves = qleaves = kv_buf = None  # noqa: F841
            qparams = None  # noqa: F841

        # --------------------------------------------------------------
        # LONG-CONTEXT paged serving (VERDICT r3 item 8): the paged
        # kernel is the declared long-context decode path, so measure it
        # there — 2 slots at an 8k and a 16k context, kernel vs gathered
        # view at IDENTICAL pool geometry.  Wall tok/s would be tunnel-
        # bound (~100 ms dispatch vs ~10 ms device per step — the paths
        # would read identical), so the figure that carries the
        # comparison is device-op ms per decode step from an xplane
        # trace of 8 steps.
        # --------------------------------------------------------------
        try:
            lc_cfg = config.replace(max_seq_len=16384)

            def lc_serve_device_ms(
                ctx: int, max_len: int, use_kernel: bool, cfg=None,
            ) -> float:
                # block_size=None: the batcher's default (512 at both
                # capacities — the on-chip-swept DMA-efficiency sweet
                # spot); identical geometry on both paths.
                cb = ContinuousBatcher(
                    params, cfg or lc_cfg, n_slots=2, max_len=max_len,
                    prefill_chunk=2048, use_pallas_kernel=use_kernel,
                )
                _salt[0] += 1
                srng = np.random.RandomState(4000 + _salt[0])
                for _ in range(2):
                    cb.submit(
                        list(srng.randint(1, config.vocab_size, ctx)),
                        max_new_tokens=33,
                    )
                cb.step()   # admission (chunked prefills) + first decode
                cb.step()   # decode-step compile warmup
                agg = device_op_times(
                    lambda: [cb.step() for _ in range(8)], by="source"
                )
                while cb.pending():
                    cb.step()
                return sum(agg.values()) / 8 / 1e9

            lc_serving = {}
            # Contexts are block-multiples of the default size so the
            # padded prompt + 33 new tokens fits the capacity.
            for ctx, max_len, label in (
                (7680, 8192, "8k"), (15872, 16384, "16k")
            ):
                for use_kernel, path in ((True, "kernel"),
                                         (False, "gathered")):
                    ms = lc_serve_device_ms(ctx, max_len, use_kernel)
                    lc_serving[f"{label}_{path}_device_ms_per_step"] = (
                        round(ms, 2)
                    )
                    lc_serving[f"{label}_{path}_device_tokens_per_s"] = (
                        round(2 / ms * 1e3, 1)
                    )
        except Exception:
            lc_serving = None
        try:
            # int8 KV pool at 16k (kernel path; VERDICT r4 item 4): the
            # dequant scales fold in-kernel, so the pool streams at one
            # byte per element.  Documented A/B, not a silent default —
            # int8 is lossy (~4e-3 rel) and the measured win (~9% at
            # 16k B=1 decode) is half VERDICT's 15-25% trigger.  Own
            # try: a failure here must not discard the bf16 rows above.
            if lc_serving is not None:
                ms = lc_serve_device_ms(
                    15872, 16384, True,
                    cfg=lc_cfg.replace(kv_cache_dtype="int8"),
                )
                lc_serving["16k_kernel_int8kv_device_ms_per_step"] = (
                    round(ms, 2)
                )
                lc_serving["16k_kernel_int8kv_device_tokens_per_s"] = (
                    round(2 / ms * 1e3, 1)
                )
        except Exception:
            pass

        # --------------------------------------------------------------
        # Device-time companions for the SHORT-context serving drain and
        # the speculative rounds (VERDICT r4 item 5): the wall figures
        # are tunnel-bound (~100 ms/dispatch vs single-digit-ms device
        # steps), so regressions could hide inside tunnel noise.  Same
        # xplane pattern as long_context_serving.
        # --------------------------------------------------------------
        try:
            # Chunked batcher (the headline's configuration):
            # device_ms_per_step normalizes by the DECODE ITERATIONS the
            # traced window executed (steps_total delta), so the figure
            # stays per-iteration-comparable with the K=1 rounds'
            # per-dispatch number — the acceptance bar is that fusing K
            # iterations into one program does not regress the
            # per-iteration device time.
            cb = ContinuousBatcher(
                params, config, n_slots=8, max_len=1024, block_size=128,
                decode_chunk=16,
            )
            _salt[0] += 1
            srng = np.random.RandomState(6000 + _salt[0])
            for _ in range(8):
                # max_new 96 (896 + 96 <= 1024) so the traced window
                # below holds full K=16 chunks.
                cb.submit(list(srng.randint(1, config.vocab_size, 850)),
                          max_new_tokens=96)
            cb.step(); cb.step()  # admission + chunk compile warmup
            iters0 = cb.steps_total
            agg = device_op_times(
                lambda: [cb.step() for _ in range(4)], by="source"
            )
            iters = cb.steps_total - iters0
            while cb.pending():
                cb.step()
            ms = sum(agg.values()) / max(iters, 1) / 1e9
            serve_device = {
                "device_ms_per_step": round(ms, 2),
                "device_tokens_per_s": round(8 / ms * 1e3, 1),
                "traced_decode_iterations": iters,
            }
        except Exception:
            serve_device = None
        try:
            # Fused batcher (the headline's configuration):
            # device_ms_per_round normalizes by the ROUNDS the traced
            # window executed (steps_total delta — each fused dispatch
            # carries up to R=8), keeping the figure per-round-
            # comparable with the classic loop's per-dispatch number;
            # the acceptance bar is that fusing R rounds into one
            # program does not regress per-round device time.
            cb = ContinuousBatcher(
                params, config, n_slots=4, max_len=1024, block_size=128,
                draft_params=draft_params, draft_config=config,
                n_draft=3, spec_rounds=8,
            )
            _salt[0] += 1
            srng = np.random.RandomState(7000 + _salt[0])
            for _ in range(4):
                # max_new 96 (512 + 96 <= 1024) so the traced window
                # holds full fused chunks.
                cb.submit(list(srng.randint(1, config.vocab_size, 500)),
                          max_new_tokens=96)
            cb.step(); cb.step()  # admission + fused-round compile warmup
            emitted = [0]
            rounds0 = cb.steps_total

            def _rounds():
                emitted[0] = sum(len(cb.step()) for _ in range(4))

            agg = device_op_times(_rounds, by="source")
            rounds = max(cb.steps_total - rounds0, 1)
            while cb.pending():
                cb.step()
            ms = sum(agg.values()) / rounds / 1e9
            spec_device = {
                "device_ms_per_round": round(ms, 2),
                # Tokens actually emitted over the traced rounds — the
                # honest numerator for a speculative round (acceptance
                # decides it, not the slot count).
                "device_tokens_per_s": round(
                    emitted[0] / rounds / ms * 1e3, 1
                ),
                "traced_rounds": rounds,
            }
        except Exception:
            spec_device = None
        finally:
            # Last consumer of the perturbed draft copy: free its ~2 GB
            # (and the batcher still referencing it + its pools) before
            # the training section allocates its 6 GB state.
            cb = None  # noqa: F841
            draft_params = None  # noqa: F841

        # --------------------------------------------------------------
        # Training step throughput (the subsystem the reference lacks
        # entirely): one AdamW step on the bench model, B=4 x S=2048,
        # bf16 params, per-block remat with the default "dots" policy
        # (save matmul outputs; remat=False OOMs this chip at 1B scale,
        # full recompute costs +13%), flash-attention VJP.  Device time
        # from a trace of ONE donated step; MFU counts fwd 2NT + bwd 4NT
        # matmul flops plus 3x the causal attention flops — remat
        # recompute is NOT counted as useful work (standard MFU
        # convention).
        # --------------------------------------------------------------
        try:
            from jax_llama_tpu.train import (
                init_train_state, make_optimizer, train_step,
            )

            # attn_impl must be explicit: the preset default is "xla",
            # whose dense-bias fwd+bwd measured 674.5 ms/step vs the
            # flash VJP's 487.9 here (1.38x) — and flash is the path
            # that scales past this S anyway.
            tcfg = config.replace(
                max_seq_len=2048, remat=True, attn_impl="flash"
            )
            # Reuse the bench params as the training params: values are
            # random either way, and a second 2 GB init pushed this
            # section over the chip's HBM alongside the 6 GB train
            # state.  train_step DONATES the state, so this must stay
            # the LAST section that touches `params` (it is: every
            # other consumer runs above).
            topt = make_optimizer()
            tstate = init_train_state(params, topt)
            TB, TS = 4, 2048
            ttoks = jnp.asarray(
                rng.randint(0, config.vocab_size, (TB, TS)), jnp.int32
            )
            for _ in range(2):  # compile + warm (state donated through)
                tstate, tloss = train_step(
                    tstate, ttoks, config=tcfg, optimizer=topt
                )

            def _one_step():
                nonlocal tstate
                tstate, tl = train_step(
                    tstate, ttoks, config=tcfg, optimizer=topt
                )
                float(tl)

            tagg = device_op_times(_one_step, by="op")
            t_dev = sum(tagg.values()) / 1e12
            n_mat = n_params - embed_entries
            tflops = (
                6 * n_mat * TB * TS
                + 3 * (2 * TB * TS * TS * config.dim * config.n_layers)
            )
            train_metrics = {
                "train_step_device_ms": round(t_dev * 1e3, 1),
                "train_tokens_per_s": round(TB * TS / t_dev, 1),
                # Peak-relative like its siblings: null off-v5e.
                "train_mfu": (
                    round(tflops / t_dev / V5E_BF16_FLOPS, 3)
                    if is_v5e else None
                ),
            }
        except Exception as e:  # keep the bench's one-line contract,
            # but leave a diagnosable trace instead of a silent null
            # (an OOM here once hid behind "training": null).
            train_metrics = {
                "error": f"{type(e).__name__}: {str(e)[:160]}"
            }
    except Exception:
        step_breakdown = None
        device_toks_per_s = None

    # BASELINE.json's 50 tok/s/chip target is stated for Llama-3-70B on
    # v5p; decode is HBM-bandwidth-bound, so scale the per-chip target by
    # the param ratio to get an honest denominator for this bench model
    # rather than pretending a ~1B model beat a 70B target.
    target = 50.0 * (70e9 / n_params)
    # HBM-utilization numerators prefer the device rates too.
    if device_toks_per_s:
        bf16_hbm = hbm_util(2.0, B / device_toks_per_s)
    if int8_device_toks_per_s:
        int8_hbm = hbm_util(1.0, B / int8_device_toks_per_s)
    # The HEADLINE rides the xplane device-time rate when the profiler
    # stack is available (VERDICT r4 item 1): device-busy time is a
    # lower bound on wall time, so a wall rate above the device rate is
    # a measurement artifact by construction (r4's min-of-min
    # differencing did exactly that — see measure()'s docstring); the
    # wall figure stays as the cross-check companion.
    headline = device_toks_per_s or toks_per_s
    result = {
        "metric": "steady-state greedy decode throughput, ~1B Llama-3-arch "
                  f"bf16, batch {B}, prompt {P}, gen {N}, single chip",
        "value": round(headline, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(headline / target, 3),
        "detail": {
            "headline_source": (
                "xplane_device" if device_toks_per_s else "wall"
            ),
            "params": n_params,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "compile_s": round(compile_s, 1),
            "prefill+decode_s": round(full, 3),
            "prefill_s": round(short, 3),
            "per_token_ms": round(1e3 * decode_s / (N - 1), 2),
            # Wall companions (paired-median differencing — see
            # measure()): cross-checks for the device figures; device is
            # the headline when available.
            "decode_tokens_per_s_wall": round(toks_per_s, 2),
            "int8_tokens_per_s_wall": round(int8_toks_per_s, 2),
            "int8_tokens_per_s_device_xplane": (
                round(int8_device_toks_per_s, 2)
                if int8_device_toks_per_s else None
            ),
            # Roofline evidence (denominators are v5e public peaks; only
            # meaningful when device above is a v5 lite chip).
            "hbm_utilization_bf16": round(bf16_hbm, 3) if is_v5e else None,
            "hbm_utilization_int8": round(int8_hbm, 3) if is_v5e else None,
            "hbm_model": "weights-once-per-step + bf16 KV at mean context",
            # Bandwidth ceiling for this geometry (see roofline_tps):
            # vs_baseline 0.95 ~= 100% of the bf16 ceiling on this chip.
            "decode_roofline_tokens_per_s_bf16": (
                round(roofline_tps(2.0), 1) if is_v5e else None
            ),
            "decode_roofline_tokens_per_s_int8": (
                round(roofline_tps(1.0), 1) if is_v5e else None
            ),
            # MEASURED ceiling (VERDICT r3 item 2): device time to stream
            # the modeled step bytes through sum reductions, from an
            # xplane trace.  The observed streaming rate on this chip is
            # ~90% of nameplate, so this is the real denominator;
            # decode_vs_measured_ceiling uses the jitter-immune xplane
            # decode rate as numerator.
            "hbm_ceiling_measured_tokens_per_s": (
                round(hbm_ceiling_tps, 1) if hbm_ceiling_tps else None
            ),
            "hbm_ceiling_measured_gbps": (
                round(hbm_ceiling_gbps, 1) if hbm_ceiling_gbps else None
            ),
            # NB: the int8 probe's sum-reduce converts one BYTE per
            # element, so at int8 density the VPU convert — not HBM —
            # can bound the probe; treat this as a LOWER bound on the
            # int8 streaming ceiling (the int8 decode legitimately
            # lands a few % above it).
            "hbm_ceiling_measured_tokens_per_s_int8": (
                round(hbm_ceiling_tps_int8, 1)
                if hbm_ceiling_tps_int8 else None
            ),
            "decode_vs_measured_ceiling": (
                round(device_toks_per_s / hbm_ceiling_tps, 3)
                if device_toks_per_s and hbm_ceiling_tps else None
            ),
            # Compiled Pallas flash kernel, long-prompt prefill (B=1).
            # Device-op time when the profiler stack is up; the wall
            # differencing fallback reads ~2% low (prefill_sources says
            # which path produced each of the 8k/16k/32k figures).
            "prefill_sources": prefill_sources,
            "flash_prefill_8k_s": round(flash8k_s, 3),
            "flash_prefill_8k_tflops": round(flash8k_tf, 1),
            "flash_prefill_16k_s": round(flash16k_s, 3),
            "flash_prefill_16k_tflops": round(flash16k_tf, 1),
            "flash_prefill_32k_s": round(flash32k_s, 3),
            "flash_prefill_32k_tflops": round(flash32k_tf, 1),
            # Prefill-kernel sweep (ops/kernels.py): flash vs splash-mha
            # through the SERVING insert path (both arms; the splash
            # kernel only dispatches on cache-insert, so the cacheless
            # flash_prefill_* figures above can't host the A/B).  The
            # dotted keys embed "tflops" so --compare gates direction.
            "prefill_kernel_sweep": prefill_kernel_sweep,
            # BASELINE config 4 (long context): B=1, 16k-token context,
            # chunked flash prefill + append-free decode over the cache.
            # Wall + device companions, and the int8-KV variant (VERDICT
            # r4 item 4): at 16k the KV stream is the marginal byte.
            "decode_tokens_per_s_ctx16k_b1": round(lc_toks_per_s, 2),
            "decode_tokens_per_s_ctx16k_b1_device_xplane": (
                round(lc_device_toks_per_s, 2)
                if lc_device_toks_per_s else None
            ),
            "decode_tokens_per_s_ctx16k_b1_int8kv": (
                round(lc_int8kv_device_toks_per_s, 2)
                if lc_int8kv_device_toks_per_s else None
            ),
            "mxu_peak_tflops": V5E_BF16_FLOPS / 1e12 if is_v5e else None,
            "mxu_utilization_16k": (
                round(flash16k_tf * 1e12 / V5E_BF16_FLOPS, 3)
                if is_v5e else None
            ),
            # Continuous batching through the Pallas paged-attention
            # kernel (8 slots, 850-token prompts, 48 new tokens each),
            # CHUNKED decode (decode_chunk=16).  Wall-clock: each
            # dispatch still pays this environment's ~100ms tunnel
            # latency, but a dispatch now carries up to 16 decode
            # iterations with state device-resident, so the figure is
            # ~K x the K=1 loop's (see paged_serving_chunk_sweep and
            # paged_serving_host_overhead_ratio for the remaining gap
            # to the device rate).
            "paged_serving_tokens_per_s": round(
                paged_serving_toks_per_s, 2
            ),
            # Wall tok/s at decode_chunk K ∈ {1, 4, 8, 16}: the record
            # of how much of the dispatch-overhead gap each chunk size
            # closes (K1 reproduces the pre-chunking per-token loop).
            "paged_serving_chunk_sweep": chunk_sweep,
            # Device-time companion for the 8-slot drain (VERDICT r4
            # item 5): regressions become attributable to device vs
            # tunnel.
            "paged_serving_device": serve_device,
            # Host-overhead ratio: xplane device tok/s over wall tok/s
            # (>= 1; 1.0 = the host/tunnel adds nothing, BENCH_r05's
            # K=1 loop measured ~26x).  Null when the profiler stack is
            # unavailable.
            "paged_serving_host_overhead_ratio": (
                round(
                    serve_device["device_tokens_per_s"]
                    / paged_serving_toks_per_s, 2
                ) if serve_device else None
            ),
            # 8 submits -> ONE batched prefill dispatch + first decode.
            "burst_admission_s": round(admit_s, 3),
            # int8 WEIGHT-only serving (the quantize_params path run.py
            # --quantize reaches; the serving benches previously only
            # ever measured int8 KV): same burst drain, quantized
            # weight stream.
            "paged_serving_int8w_tokens_per_s": round(
                paged_serving_int8w_toks_per_s, 2
            ),
            # Decode-kernel A/B (ops/kernels.py): the burst drain per
            # decode attention path — custom paged (headline) vs stock
            # Pallas paged-attention vs the gathered XLA view.  Keys
            # embed "tok_per_s" for --compare direction classification;
            # a kernel unavailable on this backend records null.
            "decode_kernel_ab": decode_kernel_ab,
            # Fused prefill-decode scheduling (run.py --prefill-budget,
            # the headline serving config): time-to-first-token of a
            # 3 x 850-token burst landing against 4 mid-decode
            # residents, and the residents' p99 inter-token latency
            # while the burst admits.  The budget sweep's B0 entry is
            # the classic whole-prompt-admission baseline — its
            # decode_stall_ms is what fused scheduling drives to ~0.
            "serving_ttft_ms": serving_ttft,
            "serving_itl_p99_ms": serving_itl_p99,
            "serving_prefill_budget_sweep": budget_sweep,
            # KV capacity at chat scale (kvcache.py, r06): TTFT p50/p99
            # of a 512-token turn at prefix hit depth {0, 25, 75}%
            # (radix index, fused admission — the deeper the hit, the
            # less prefill the turn pays), and how many sessions stay
            # cache-addressable when revisited round-robin against a
            # 4-session HBM pool, without vs with the host-DRAM tier
            # (revisits swap back in through the restoring state).
            "chat_prefix_hit_ttft_ms": chat_ttft,
            "sessions_resident_max": sessions_resident,
            # Overload control (overload.py, r06): the open-loop
            # Poisson sweep — per-class served/refused/attainment and
            # goodput tokens/s at {0.5, 1, 2, 4}x the sustainable
            # request rate with the brownout ladder on, plus the
            # ladder-vs-static-max_queue A/B at 4x (interactive
            # attainment held vs collapsed; all refusals 503 +
            # Retry-After; hung_total must read 0 on both sides).
            "serving_overload": overload_sweep,
            # Long-context paged serving (2 slots, 8k/16k contexts):
            # device-op ms per decode step, kernel vs gathered view at
            # identical pool geometry (xplane; wall would be tunnel-
            # bound and read identical on both paths).
            "long_context_serving": lc_serving,
            # One AdamW train step, B=4 x S=2048, bf16 + remat + flash
            # VJP (device time; MFU excludes remat recompute).
            "training": train_metrics,
            # Speculative serving (perturbed-target draft, n_draft=3,
            # FUSED spec_rounds=8 headline): Pallas path (T=1-shaped
            # draft chain + multi-token verify kernel) vs the
            # gathered-view fallback at IDENTICAL pool geometry.  The
            # draft is the target nudged by ±2% deterministic noise, so
            # acceptance is genuinely < 1 and the reject/replacement
            # path is exercised (self-draft used to pin it at 1.0);
            # the acceptance fields attribute any throughput gap
            # between the two paths.
            "spec_serving_kernel_tokens_per_s": round(
                spec_kernel_toks_per_s, 2
            ),
            "spec_serving_kernel_acceptance": round(spec_kernel_accept, 3),
            "spec_serving_gathered_tokens_per_s": round(
                spec_gathered_toks_per_s, 2
            ),
            "spec_serving_gathered_acceptance": round(
                spec_gathered_accept, 3
            ),
            # Wall tok/s at spec_rounds R ∈ {1, 2, 4, 8} (kernel path):
            # R1 reproduces the pre-fusion per-round loop (the r05
            # 46.3 tok/s baseline), so R8/R1 is the fused-dispatch
            # amortization win.
            "spec_serving_rounds_sweep": spec_rounds_sweep,
            # Device-time per speculative round (kernel path, fused
            # batcher, steps_total-normalized) — the jitter-immune
            # denominator for the host-overhead ratios below.
            "spec_serving_device": spec_device,
            # Wall-vs-device host-overhead ratios for the speculative
            # drain (>= 1; 1.0 = the host/tunnel adds nothing): the
            # headline fused-R8 figure, and the R1 classic-loop
            # companion (r05 measured ~20x there) the fusion is
            # amortizing away.
            "spec_serving_host_overhead_ratio": (
                round(
                    spec_device["device_tokens_per_s"]
                    / spec_kernel_toks_per_s, 2
                ) if spec_device else None
            ),
            "spec_serving_host_overhead_ratio_r1": (
                round(
                    spec_device["device_tokens_per_s"]
                    / spec_rounds_sweep["R1"], 2
                ) if spec_device and spec_rounds_sweep.get("R1")
                else None
            ),
            # Batch-16 steady-state decode (headline stays B=8 for
            # round-over-round comparability; wall + device).
            "decode_tokens_per_s_b16_wall": round(b16_toks_per_s, 2),
            "decode_tokens_per_s_b16_device_xplane": (
                round(b16_device_toks_per_s, 2)
                if b16_device_toks_per_s else None
            ),
            # Device-op-time decode throughput from xplane differencing
            # (32 vs 1 new tokens): the tenancy/jitter-immune companion
            # of the wall-clock headline — if the two disagree, this one
            # is the chip's actual rate.
            "decode_tokens_per_s_device_xplane": (
                round(device_toks_per_s, 2) if device_toks_per_s else None
            ),
            # Device-op µs per decode step bucketed by HLO source file
            # (llama.py = the projection/MLP matmul fusions + cache
            # update ops — the bf16 weight stream used to misattribute
            # to quant.py through the ops.quant.matmul wrapper frame;
            # quant.py now measures actual int8 dequant work only,
            # attention.py = the decode attention chain, rope.py =
            # rotation).  Includes prefill amortized over 32 steps; None
            # when the profiler stack is unavailable.
            "step_breakdown_us": step_breakdown,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if "--compare" in sys.argv[1:]:
        compare_main()
    elif "--load-harness" in sys.argv[1:]:
        load_harness_main()
    elif "--multichip-serving" in sys.argv[1:]:
        record = None
        if "--record" in sys.argv[1:]:
            record = sys.argv[sys.argv.index("--record") + 1]
        multichip_serving_main(record_path=record)
    else:
        main()
