"""Benchmark: greedy decode throughput on the local TPU chip.

Prints ONE JSON line:
    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

The metric mirrors BASELINE.json ("Llama-3 decode tokens/sec/chip"); the
baseline denominator is its v5p target of 50 tok/s/chip for 70B.  The
reference publishes no numbers of its own (BASELINE.md), so vs_baseline is
measured against that target.

The bench model is a ~1B-param Llama-3-architecture config (GQA 2:1, SwiGLU,
bf16) — the largest that comfortably fits a single v5e-lite chip with its KV
cache.  Decode throughput is measured over full-length generations with no
stop tokens, steady-state (after one compile warmup), batch 8.  The headline
value is the bf16-weight path (parity-honest vs the reference's fp32/bf16
serving); the int8 weight-only serving path is reported in `detail`.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax_llama_tpu as jlt
    from jax_llama_tpu.engine import GenerationConfig, generate
    from jax_llama_tpu.ops.quant import quantize_params

    # param_dtype bf16: decode is HBM-bandwidth-bound, so serving keeps
    # weights in bf16 (2 bytes/param of traffic per step, not 4).
    config = jlt.get_config(
        "llama3-8b",
        dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        multiple_of=256, vocab_size=32000, max_seq_len=1024,
        param_dtype="bfloat16",
    )
    params = jlt.init_params(jax.random.PRNGKey(0), config)
    n_params = jlt.param_count(params)

    B, P, N = 8, 128, 128
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, config.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), dtype=bool)
    key = jax.random.PRNGKey(0)

    def run(p, max_new: int) -> float:
        gc = GenerationConfig(
            max_new_tokens=max_new, temperature=0.0, stop_tokens=()
        )
        t0 = time.time()
        out = generate(p, tokens, mask, key, config=config, gen_config=gc)
        # Sync via host transfer, NOT block_until_ready: under the axon
        # tunnel backend block_until_ready/effects_barrier return while the
        # computation is still in flight, and the [B, P+N] int32 fetch is
        # a few KB — negligible vs the decode itself.
        np.asarray(out)
        return time.time() - t0

    def measure(p):
        """Steady-state decode rate: the (prefill + N) vs (prefill + 1)
        difference cancels both prefill time and the constant per-call
        dispatch overhead of this environment's tunnel out of the metric.
        min-of-5 on each side tames the tunnel's run-to-run jitter."""
        full = min(run(p, N) for _ in range(5))
        short = min(run(p, 1) for _ in range(5))
        decode_s = max(full - short, 1e-9)
        return B * (N - 1) / decode_s, decode_s, full, short

    t0 = time.time()
    run(params, N)
    run(params, 1)
    compile_s = time.time() - t0

    toks_per_s, decode_s, full, short = measure(params)

    qparams = quantize_params(params)
    run(qparams, N)
    run(qparams, 1)
    int8_toks_per_s, _, _, _ = measure(qparams)

    # BASELINE.json's 50 tok/s/chip target is stated for Llama-3-70B on
    # v5p; decode is HBM-bandwidth-bound, so scale the per-chip target by
    # the param ratio to get an honest denominator for this bench model
    # rather than pretending a ~1B model beat a 70B target.
    target = 50.0 * (70e9 / n_params)
    result = {
        "metric": "steady-state greedy decode throughput, ~1B Llama-3-arch "
                  f"bf16, batch {B}, prompt {P}, gen {N}, single chip",
        "value": round(toks_per_s, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(toks_per_s / target, 3),
        "detail": {
            "params": n_params,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "compile_s": round(compile_s, 1),
            "prefill+decode_s": round(full, 3),
            "prefill_s": round(short, 3),
            "per_token_ms": round(1e3 * decode_s / (N - 1), 2),
            "int8_tokens_per_s": round(int8_toks_per_s, 2),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
