"""Request-timeline tracing, latency histograms, and SLO accounting.

The serving stack's earlier observability was flat counters plus one
``ttft_ms_ewma`` gauge — enough to graph throughput, useless for
answering "where did THIS request's 900 ms go?" or "what goodput do we
hold under a 200 ms TTFT SLO?".  This module is the sensor layer those
questions (and ROADMAP item 5's online chunk controller) need:

  * **Event timeline** (:class:`Observability`).  Every request owns a
    bounded span timeline through the admission state machine —
    ``queued -> prefilling -> restoring -> decoding ->
    finished/failed/cancelled`` (the PR 5/6 states) — and every jitted
    serving dispatch gets a span in a bounded ring recording its kind
    (``decode`` / ``fused`` / ``spec`` / ``insert`` / ``suffix_insert``
    / ``adopt``), effective K/R, slot occupancy, prompt tokens advanced
    by a riding prefill lane, packed-fetch wall time, and how many
    host-tier swap-ins were in flight (the decode/swap overlap, made
    visible).  Request spans are causally linked to the dispatch spans
    they rode in (span.dispatches lists dispatch seq numbers), so a
    timeline answers "which chunk dispatches carried my prefill" and a
    dispatch answers "whose tokens did I emit".
  * **Latency histograms** (:class:`Histogram`).  Prometheus cumulative-
    bucket histograms for TTFT, inter-token latency, queue wait,
    prefill-chunk latency, swap-in latency, jit compile time, and
    dispatch wall time — the distributions the flat EWMA hid.
    ``dispatch_ms`` is a LABELED family: one series per dispatch kind
    (``{kind="decode"|"fused"|"spec"|"insert"|"suffix_insert"|
    "adopt"}``), so a spec-round regression no longer hides inside a
    lumped all-kinds distribution.  Rendered straight into the
    ``/metrics`` text exposition (``_bucket``/``_sum``/``_count``).
  * **Device-time attribution** (:class:`CostModelCache` + the
    ``mxu_utilization`` / ``hbm_utilization`` / ``host_overhead_ratio``
    gauges).  Each jitted serving program's static cost (FLOPs + bytes
    accessed, from ``jit(...).lower(...).cost_analysis()`` at the LIVE
    geometry, cached per jit-cache key — trace-time work only, never a
    steady-state dispatch) rides its dispatch record; per-kind sliding
    windows turn measured dispatch wall time into live roofline
    utilization and a wall-vs-device-estimate host-overhead ratio —
    the ~20-26x device-vs-wall gap BENCH_r05 measured offline, now a
    scrapeable gauge.  Peaks default to the v5e single-chip numbers
    bench.py rooflines against (197 bf16 TFLOPs, 819 GB/s HBM);
    run.py ``--peak-tflops`` / ``--peak-hbm-gbps`` repin them.
  * **Jit-cache observability**.  A ``jax.monitoring`` listener turns
    every backend compile into a ``compile_ms`` observation, a span in
    the trace (its own ``jit compiles`` track), and a per-program
    counter (:meth:`Observability.record_compile`; serving.py names
    the program via :func:`attribute_compiles` around each dispatch),
    and ``/metrics`` exposes per-program jit-cache entry counts — a
    bucketing bug that blows the jit cache is a visible counter, not a
    mystery stall.
  * **SLO accounting**.  With ``slo_ttft_ms`` / ``slo_itl_ms``
    configured (run.py ``--slo-ttft-ms`` / ``--slo-itl-ms``), every
    finished request is scored against both deadlines:
    ``slo_attainment`` gauges (windowed, last 256 requests) and a
    ``goodput_tokens_total`` counter (tokens from requests that met
    every configured deadline — the objective an online
    ``decode_chunk``/``prefill_budget`` controller will maximize).
    An unconfigured dimension always passes, so with no SLO flags the
    gauges read 1.0 and goodput equals delivered tokens.
  * **Metric registry** (:data:`METRICS` / :func:`metric_meta`).  The
    explicit ``# TYPE`` + ``# HELP`` source for every scalar the
    ``/metrics`` endpoint exposes — replacing the old ``"total" in k``
    type heuristic (which already needed a hand-carved
    ``radix_nodes_total`` exception).
  * **Trace export**.  :meth:`Observability.trace_json` emits
    Chrome/Perfetto ``trace_event`` JSON for a recent serving window —
    dispatch spans on one track, request lifecycles on per-request
    tracks, fault/quarantine/kv-tier annotations as instant events —
    loadable in ``chrome://tracing`` or https://ui.perfetto.dev (the
    server serves it at ``GET /debug/trace``).
  * **Decision audit log** (:class:`DecisionLog`).  Every control-plane
    decision — a router's route/reroute/handoff pick (with the
    candidate set and scores it chose from), a brownout-ladder rung
    move, a crash-recovery/quarantine/probe rebuild, a shed — lands as
    one ring-buffered structured event carrying the external request id
    where one exists, so ``GET /debug/decisions`` answers "why did
    request X land on replica Y" and joins back to the request's
    ``/debug/requests/<id>`` timeline by id.  The server's decisions
    live on its Observability instance (they survive batcher rebuilds
    like everything else here); the ReplicaRouter owns its own log.
  * **Flight recorder**.  The bounded rings above (decisions, the
    annotation/state-transition ring, dispatch spans) plus a periodic
    :meth:`Observability.record_metrics_snapshot` ring and the
    :class:`StructuredLogger` tail are the black-box a postmortem
    needs: ``GET /debug/bundle`` (server.py / router.py) exports them
    as one artifact — config + metrics + last-N decisions + log tail +
    Perfetto trace — capturing "the 30 s before the 503 storm".
  * **Anomaly detection building block** (:class:`EwmaDetector`).  An
    online EWMA mean/variance z-score detector — the router's
    per-replica health sentinel (router.py) runs one per latency-class
    signal; kept here because it is pure host math and unit-testable
    without HTTP.

Overhead contract: everything here is HOST-side bookkeeping recorded at
boundaries the serving loop already crosses (admission, the one packed
fetch per chunk, slot frees).  Recording performs **zero device
dispatches and zero host syncs** — ``make perf-smoke`` asserts the
1-fetch / 0-upload steady state is bit-identical with tracing on (it is
always on; the rings are bounded deques, a few hundred bytes per
entry).  All methods are thread-safe (one lock; the serving loop
writes, HTTP handler threads snapshot).
"""

from __future__ import annotations

import bisect
import json
import math
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .degrade import FEATURES
from .faults import SITES

# Dispatch kinds serving.py records — each owns a labeled dispatch_ms
# histogram series and a device-time attribution window.
# record_dispatch VALIDATES against this set: a typo'd kind would
# otherwise mint a phantom metrics series nobody scrapes.
# The ":"-suffixed variants are per-kernel attribution splits
# (ops/kernels.py): same dispatch site as the base kind, but served by
# an alternative kernel — so ``llm_mxu_utilization{kind}`` turns the
# kernel A/B into a live gauge.  Fused chunks and spec rounds keep ONE
# kind each (mixed prefill/decode resp. draft/verify FLOPs — a kernel
# split would attribute the mix to one kernel and lie).
DISPATCH_KINDS = frozenset({
    "decode", "fused", "spec", "insert", "suffix_insert", "adopt",
    "decode:stock-paged", "insert:splash",
})

# Default hardware peaks for the utilization gauges: the public TPU
# v5e single-chip numbers bench.py's rooflines use (BENCH_r05's
# denominators).  run.py --peak-tflops / --peak-hbm-gbps repin them
# for other chips; 0 disables the corresponding gauge.
DEFAULT_PEAK_FLOPS = 197e12        # bf16 MXU peak (FLOP/s)
DEFAULT_PEAK_BYTES_PER_S = 819e9   # HBM bandwidth (B/s)

# ---------------------------------------------------------------------------
# Histograms (Prometheus cumulative buckets)
# ---------------------------------------------------------------------------

# Default latency buckets in MILLISECONDS: sub-ms dispatches through
# multi-second prefills/swaps.  +Inf is implicit.
DEFAULT_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class Histogram:
    """A Prometheus-style cumulative histogram (fixed upper bounds).

    ``observe(v)`` is a bisect + two adds; NOT itself synchronized —
    every caller inside :class:`Observability` holds the owner's lock,
    so a concurrent ``/metrics`` render can never see a bucket updated
    ahead of ``_count``.  ``expose(prefix)`` renders the standard
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` family with its
    ``# HELP`` / ``# TYPE`` header.  ``labels`` names one series of a
    LABELED family (e.g. ``{"kind": "decode"}``): the labels render
    into every sample line and ``expose(header=False)`` suppresses the
    family header so sibling series share one ``# TYPE``.  Bucket
    counts are stored NON-cumulative and summed at exposition
    (observe stays O(log B))."""

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_text
        self.labels = dict(labels) if labels else {}
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"histogram buckets must ascend: {buckets}")
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1

    def cumulative(self) -> List[Tuple[str, int]]:
        """[(le_label, cumulative_count)] including +Inf."""
        out: List[Tuple[str, int]] = []
        acc = 0
        for b, c in zip(self.buckets, self.counts):
            acc += c
            out.append((format(b, "g"), acc))
        out.append(("+Inf", acc + self.counts[-1]))
        return out

    def expose(self, prefix: str = "", header: bool = True) -> List[str]:
        n = prefix + self.name
        lines = (
            [f"# HELP {n} {self.help}", f"# TYPE {n} histogram"]
            if header else []
        )
        base = "".join(
            f'{k}="{v}",' for k, v in sorted(self.labels.items())
        )
        for le, c in self.cumulative():
            lines.append(f'{n}_bucket{{{base}le="{le}"}} {c}')
        lab = "{" + base.rstrip(",") + "}" if base else ""
        lines.append(f"{n}_sum{lab} {round(self.sum, 3)}")
        lines.append(f"{n}_count{lab} {self.count}")
        return lines


# The serving stack's histogram families (name -> help); every
# Observability owns one of each.  All values are milliseconds.
HISTOGRAMS = {
    "ttft_ms": (
        "Time to first token per delivered request (ms; client-observed, "
        "crash-recovery replays included)"),
    "itl_ms": (
        "Inter-token latency per delivered token after the first (ms; "
        "tokens inside one fused chunk arrive together, so chunked decode "
        "shows a mass near 0 plus one chunk-period mode)"),
    "queue_wait_ms": (
        "Submit-to-admission wait per request (ms; the queued span)"),
    "prefill_chunk_ms": (
        "Wall time of prefill-carrying dispatches (ms: fused prefill "
        "chunks and classic whole-prompt inserts)"),
    "swap_in_ms": (
        "Host-tier swap-in latency per restored admission (ms: staging "
        "H2D start to pool adoption)"),
    "compile_ms": (
        "Backend compile time per jit-cache miss (ms; fed by the "
        "jax.monitoring listener — a busy series here means the jit "
        "cache is being blown, see jit_cache_entries)"),
    "dispatch_ms": (
        "Wall time per jitted serving dispatch incl. its packed fetch "
        "(ms; one K-iteration or R-round chunk each; LABELED by "
        "dispatch kind)"),
    "prefix_hit_depth_tokens": (
        "Prefix-cache hit depth per admission (TOKENS served from "
        "cached blocks; the 0-hit mass lands in the first bucket — "
        "a cold fleet reads as all-first-bucket)"),
    "session_kv_blocks": (
        "KV pool blocks a session held at slot free (BLOCKS, not ms; "
        "the per-session cache footprint distribution)"),
}

# Families rendered as one labeled series per dispatch kind rather
# than a single lumped series (Observability keeps one Histogram per
# kind, created lazily on first dispatch of that kind).
LABELED_HISTOGRAMS = frozenset({"dispatch_ms"})

# Non-latency families override the ms bucket ladder with their own
# unit's (tokens / blocks, pow2 — the same bucketing the admission
# paths use for jit-cache keys, so histogram edges line up with the
# actual quantization of the measured values).
HISTOGRAM_BUCKETS: Dict[str, Tuple[float, ...]] = {
    "prefix_hit_depth_tokens": tuple(
        float(1 << i) for i in range(15)  # 1 .. 16384 tokens
    ),
    "session_kv_blocks": tuple(
        float(1 << i) for i in range(11)  # 1 .. 1024 blocks
    ),
}


# ---------------------------------------------------------------------------
# Metric registry: explicit # TYPE + # HELP for every /metrics scalar
# ---------------------------------------------------------------------------

def _reg(kind: str, help_text: str) -> Tuple[str, str]:
    if kind not in ("counter", "gauge"):
        raise ValueError(kind)
    return (kind, help_text)


METRICS: Dict[str, Tuple[str, str]] = {
    # -- batcher core -------------------------------------------------------
    "emitted_tokens_total": _reg("counter", "Tokens emitted to callers"),
    "decode_steps_total": _reg(
        "counter", "Decode iterations run (K per chunked dispatch)"),
    "active_slots": _reg("gauge", "Slots holding a live request"),
    "queued_requests": _reg("gauge", "Requests waiting for admission"),
    "free_blocks": _reg("gauge", "Unallocated KV pool blocks"),
    "total_blocks": _reg("gauge", "KV pool capacity in blocks"),
    "drafts_proposed_total": _reg(
        "counter", "Draft tokens proposed (speculative serving)"),
    "drafts_accepted_total": _reg(
        "counter", "Draft tokens accepted (speculative serving)"),
    "draft_acceptance_rate": _reg(
        "gauge", "Lifetime draft acceptance fraction"),
    "nonfinite_rows_total": _reg(
        "counter", "Requests failed by the non-finite logits guard"),
    # -- prefix cache / KV capacity ----------------------------------------
    "prefix_cached_blocks": _reg(
        "gauge", "Idle HBM-resident prefix-cache blocks (pre-radix "
                 "alias of the store's idle count)"),
    "prefix_requests_hit_total": _reg(
        "counter", "Admissions that reused cached prefix blocks"),
    "prefix_blocks_reused_total": _reg(
        "counter", "Cached prefix blocks reused by admissions"),
    "radix_nodes_total": _reg(
        "gauge", "Keyed blocks in the radix prefix tree (a resident "
                 "COUNT that shrinks on eviction, not a counter)"),
    "prefix_hit_tokens_ratio": _reg(
        "gauge", "Fraction of admitted prompt tokens served from cached "
                 "prefix blocks"),
    "host_kv_blocks": _reg("gauge", "Host-DRAM KV tier capacity (blocks)"),
    "host_tier_blocks": _reg(
        "gauge", "Blocks currently demoted to the host-DRAM tier"),
    "swap_queue_depth": _reg("gauge", "Host-tier swap-ins in flight"),
    "swap_ins_total": _reg("counter", "Host-tier swap-ins started"),
    "swap_in_blocks_total": _reg(
        "counter", "Blocks restored from the host tier (H2D)"),
    "swap_out_blocks_total": _reg(
        "counter", "Blocks demoted to the host tier (D2H)"),
    "swap_in_ms_total": _reg(
        "counter", "Cumulative swap-in wall time (ms)"),
    "swap_failures_total": _reg(
        "counter", "Swap-ins failed cleanly (request-scoped)"),
    # -- KV chain digest (kvcache.KvDigest — fleet cache telemetry) ---------
    "kv_digest_version": _reg(
        "gauge", "Chain-digest content version (bumps on publish/evict/"
                 "demote/restore; resets with the store on rebuild — "
                 "compare with !=, any change means the consumer's "
                 "copy is stale)"),
    "kv_digest_loss_version": _reg(
        "gauge", "Chain-digest loss version (bumps only when a chain "
                 "can LOSE HBM residency: evict/demote/host-drop — "
                 "the affinity-freshness signal the router consults)"),
    "kv_publish_events_total": _reg(
        "counter", "Chain blocks published into the prefix index"),
    "kv_evict_events_total": _reg(
        "counter", "Chain blocks evicted out of the prefix index"),
    "kv_demote_events_total": _reg(
        "counter", "Chain blocks demoted HBM -> host tier (digest "
                   "view of the swap-out ledger)"),
    "kv_restore_events_total": _reg(
        "counter", "Chain blocks restored host tier -> HBM (digest "
                   "view of the swap-in ledger)"),
    "kv_host_evict_events_total": _reg(
        "counter", "Host-tier slabs lost to the tier's own LRU"),
    "kv_block_bytes": _reg(
        "gauge", "Pool bytes one KV block occupies (k+v+pos+scales, "
                 "draft twins included) — the duplicate-chain "
                 "accounting unit"),
    # -- scale-out serving (serve_mesh.py / router.py) ----------------------
    "kv_export_blocks_total": _reg(
        "counter", "Prefix blocks exported to peer replicas "
                   "(disaggregation handoff, prefill side)"),
    "kv_import_blocks_total": _reg(
        "counter", "Prefix blocks landed from peer replicas "
                   "(disaggregation handoff, decode side)"),
    "kv_export_events_total": _reg(
        "counter", "Prefix handoff exports that moved >= 1 block"),
    "kv_import_events_total": _reg(
        "counter", "Prefix handoff imports that landed >= 1 block"),
    "kv_handoff_aborted_total": _reg(
        "counter", "Prefix handoff imports that hit the wall timeout "
                   "and unwound cleanly (blocks freed, nothing "
                   "published)"),
    "kv_export_demoted_blocks_total": _reg(
        "counter", "Exported prefix blocks demoted/dropped at the "
                   "source after a handoff (demote-after-export: the "
                   "migration deduplicates fleet HBM)"),
    "serve_mesh_data": _reg(
        "gauge", "Serving-mesh row shards (data*fsdp axes; 1 off-mesh)"),
    "serve_mesh_tensor": _reg(
        "gauge", "Serving-mesh tensor shards (KV-head sharding; 1 "
                 "off-mesh)"),
    "replica_id": _reg(
        "gauge", "This server's replica index behind a ReplicaRouter "
                 "(-1 standalone)"),
    # -- chunked decode host boundary --------------------------------------
    "decode_chunk_size": _reg(
        "gauge", "Effective K of the most recent chunk dispatch"),
    "decode_dispatches_total": _reg(
        "counter", "Jitted decode chunk dispatches"),
    "host_syncs_total": _reg(
        "counter", "Device-to-host fetches the serving loop performed"),
    "state_uploads_total": _reg(
        "counter", "Host-to-device state-sync dispatches"),
    "host_syncs_per_token": _reg(
        "gauge", "Fetches per emitted token (trends to 1/K steady-state)"),
    # -- speculative serving ------------------------------------------------
    "spec_rounds_per_dispatch": _reg(
        "gauge", "Effective R of the most recent speculative dispatch"),
    "spec_dispatches_total": _reg(
        "counter", "Jitted speculative dispatches (R rounds each)"),
    "spec_host_syncs_per_token": _reg(
        "gauge", "Speculative-path fetches per emitted token"),
    "spec_window_acceptance_rate": _reg(
        "gauge", "Draft acceptance over the last 64 spec dispatches"),
    # -- fused prefill-decode scheduling ------------------------------------
    "prefill_budget": _reg(
        "gauge", "Prompt tokens a fused admission advances per dispatch"),
    "prefill_tokens_inflight": _reg(
        "gauge", "Prompt tokens of the in-flight admission still to "
                 "prefill"),
    "prefill_chunks_total": _reg(
        "counter", "Chunk dispatches that carried a prefill lane"),
    "fused_admissions_total": _reg(
        "counter", "Admissions routed through the fused prefill lane"),
    "decode_stall_ms_total": _reg(
        "counter", "Wall time classic whole-prompt admissions stalled "
                   "decoding rows (ms)"),
    # -- fault injection -----------------------------------------------------
    "faults_injected_total": _reg("counter", "Injected faults raised"),
    "fault_delays_total": _reg("counter", "Injected delays served"),
    "fault_nans_armed_total": _reg(
        "counter", "Non-finite poisons armed by the injector"),
    # -- server layer --------------------------------------------------------
    "server_recoveries_total": _reg(
        "counter", "Batcher rebuild+replay crash recoveries"),
    "watchdog_stalls_total": _reg(
        "counter", "Serving-loop heartbeat stalls detected"),
    "watchdog_stalled": _reg("gauge", "Watchdog currently tripped (0/1)"),
    "watchdog_last_step_age_seconds": _reg(
        "gauge", "Seconds since the serving loop's last heartbeat"),
    "quarantine_rebuilds_total": _reg(
        "counter", "Batcher rebuilds onto a feature fallback"),
    "probe_rebuilds_total": _reg(
        "counter", "Batcher rebuilds re-enabling a probed feature"),
    "nonfinite_requests_failed_total": _reg(
        "counter", "Requests failed with HTTP 500 by the non-finite "
                   "guard"),
    "draining": _reg("gauge", "Server in drain mode (0/1)"),
    "ttft_ms_ewma": _reg(
        "gauge", "EWMA time-to-first-token (ms, alpha 0.2; see the "
                 "ttft_ms histogram for the distribution)"),
    "itl_ms_ewma": _reg(
        "gauge", "EWMA inter-token latency (ms, alpha 0.2; the "
                 "per-replica degradation signal the router's health "
                 "sentinel z-scores; canary probes excluded)"),
    "canary_requests_total": _reg(
        "counter", "Synthetic canary-class probe requests served "
                   "(reserved class: excluded from SLO attainment, "
                   "goodput, latency histograms/EWMAs and the "
                   "brownout ladder's inputs)"),
    "decision_events_total": _reg(
        "counter", "Control-plane decisions recorded in the audit log "
                   "(brownout rung moves, recoveries, quarantines, "
                   "probes, sheds, drains — GET /debug/decisions)"),
    # -- request outcomes / SLO ---------------------------------------------
    "requests_finished_total": _reg(
        "counter", "Requests that delivered a complete generation"),
    "requests_failed_total": _reg(
        "counter", "Requests that ended in failure or timeout"),
    "requests_cancelled_total": _reg(
        "counter", "Requests cancelled (client disconnect or cancel)"),
    "slo_ttft_ms": _reg(
        "gauge", "Configured TTFT SLO deadline (ms; 0 = unset, "
                 "dimension always passes)"),
    "slo_itl_ms": _reg(
        "gauge", "Configured inter-token-latency SLO deadline (ms; "
                 "0 = unset)"),
    "requests_slo_ok_total": _reg(
        "counter", "Finished requests that met every configured SLO"),
    "goodput_tokens_total": _reg(
        "counter", "Tokens from requests that met every configured SLO "
                   "(the controller objective)"),
    "slo_ttft_attainment": _reg(
        "gauge", "Fraction of recent requests meeting the TTFT SLO "
                 "(window 256)"),
    "slo_itl_attainment": _reg(
        "gauge", "Fraction of recent requests meeting the ITL SLO "
                 "(window 256)"),
    "slo_attainment": _reg(
        "gauge", "Fraction of recent requests meeting every configured "
                 "SLO (window 256)"),
    # -- device-time attribution / jit-cache observability -------------------
    "compiles_total": _reg(
        "counter", "Backend jit compiles observed (cache misses; see "
                   "the compile_ms histogram and "
                   "program_compiles_total)"),
    "mxu_utilization": _reg(
        "gauge", "Modeled-FLOPs / wall-time fraction of the MXU peak "
                 "over the recent dispatch window (per dispatch kind)"),
    "hbm_utilization": _reg(
        "gauge", "Modeled bytes-accessed / wall-time fraction of the "
                 "HBM peak over the recent dispatch window (per "
                 "dispatch kind)"),
    "host_overhead_ratio": _reg(
        "gauge", "Dispatch wall time over the static-cost device-time "
                 "estimate (per dispatch kind; ~1 = device-bound, "
                 ">>1 = host overhead — the BENCH_r05 device-vs-wall "
                 "gap, live)"),
    "program_compiles_total": _reg(
        "counter", "Backend jit compiles attributed to each serving "
                   "program (per program)"),
    "jit_cache_entries": _reg(
        "gauge", "Live jit-cache entries per registered serving "
                 "program (a runaway series here is a bucketing bug "
                 "re-specializing a program per request)"),
    # -- overload control (overload.py) --------------------------------------
    "overload_rung": _reg(
        "gauge", "Brownout-ladder rung (0=normal 1=elevated "
                 "2=brownout-1 3=brownout-2 4=shed)"),
    "overload_transitions_total": _reg(
        "counter", "Brownout-ladder rung transitions (both directions)"),
    "overload_sheds_total": _reg(
        "counter", "Queued batch-class requests shed at the shed rung "
                   "(each got a clean 503 + Retry-After)"),
    "overload_refused_backlog_total": _reg(
        "counter", "Admissions refused by the queue-depth backstop "
                   "(503 + Retry-After)"),
    "overload_refused_deadline_total": _reg(
        "counter", "Admissions refused because the TTFT lower-bound "
                   "estimate provably misses the request's timeout_s"),
    "overload_refused_batch_total": _reg(
        "counter", "Batch-class admissions refused while the ladder "
                   "suspends the class (brownout-2 and above)"),
    "queued_interactive": _reg(
        "gauge", "Interactive-class requests waiting pre-admission"),
    "queued_batch": _reg(
        "gauge", "Batch-class requests waiting pre-admission"),
    "prefill_tokens_per_s_ewma": _reg(
        "gauge", "Observed prefill throughput EWMA (tokens/s; the "
                 "admission cost model's denominator)"),
    "decode_tokens_per_s_ewma": _reg(
        "gauge", "Observed decode throughput EWMA (tokens/s)"),
    "overload_ttft_estimate_ms": _reg(
        "gauge", "Most recent admission-time TTFT lower-bound estimate "
                 "(ms)"),
    "overload_batch_max_new_cap": _reg(
        "gauge", "Current brownout cap on batch-class max_new_tokens "
                 "(0 = uncapped)"),
    "slo_interactive_attainment": _reg(
        "gauge", "Interactive-class SLO attainment over the ladder's "
                 "recent signal window"),
    "slo_batch_attainment": _reg(
        "gauge", "Batch-class SLO attainment over the ladder's recent "
                 "signal window"),
}

# Generated families: per-site injection counters, per-feature
# degradation state.
for _site in SITES:
    METRICS[f"faults_injected_{_site}_total"] = _reg(
        "counter", f"Injected faults raised at site {_site}")
for _f in FEATURES:
    METRICS[f"feature_quarantined_{_f}"] = _reg(
        "gauge", f"{_f} currently quarantined onto its fallback (0/1)")
    METRICS[f"feature_failures_{_f}_total"] = _reg(
        "counter", f"Failures attributed to {_f}")
    METRICS[f"feature_quarantines_{_f}_total"] = _reg(
        "counter", f"Times {_f} entered quarantine")


def metric_meta(name: str) -> Optional[Tuple[str, str]]:
    """(type, help) for a scalar metric name (without the ``llm_``
    prefix), or None for an unregistered name — the exposition then
    falls back to the legacy heuristic and SAYS SO in the HELP line,
    which the /metrics parse test treats as a failure."""
    return METRICS.get(name)


# ---------------------------------------------------------------------------
# Static cost models + compile attribution
# ---------------------------------------------------------------------------

class CostModelCache:
    """Process-wide cache of static per-program cost models.

    ``get(program, key, lower)`` returns ``(flops, bytes_accessed)``
    from ``lower().cost_analysis()`` — ``lower`` is a thunk closing
    over the EXACT live dispatch args, so the model is computed at the
    live geometry.  The analysis runs once per ``(program, key)``
    (``key`` mirrors the jit-cache key: geometry + the static args
    that force a retrace) and is pure trace-time host work — it never
    dispatches to the device, so attribution adds zero steady-state
    device work.  A failed analysis (e.g. an exotic sharded lowering)
    caches ``None`` so it is never retried per dispatch.

    Thread-safe (``_lock``): batchers on different serving-loop
    threads share the one module-level instance; the analysis itself
    runs OUTSIDE the lock (two racing first-dispatches both lower —
    idempotent — rather than one blocking on the other's trace)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[Tuple, Optional[Tuple[float, float]]] = {}

    def get(self, program: str, key: Tuple,
            lower) -> Optional[Tuple[float, float]]:
        k = (program,) + tuple(key)
        with self._lock:
            if k in self._cache:
                return self._cache[k]
        cost: Optional[Tuple[float, float]] = None
        try:
            ca = lower().cost_analysis()
            if isinstance(ca, (list, tuple)):  # per-device variant
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                cost = (
                    float(ca.get("flops", 0.0) or 0.0),
                    float(ca.get("bytes accessed", 0.0) or 0.0),
                )
        except Exception:
            cost = None
        with self._lock:
            self._cache[k] = cost
        return cost

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """{program: {keys, flops/bytes of the most recent key}} for
        the /debug surface and tests."""
        with self._lock:
            items = list(self._cache.items())
        out: Dict[str, Dict[str, Any]] = {}
        for k, cost in items:
            ent = out.setdefault(k[0], {"keys": 0, "modeled": 0})
            ent["keys"] += 1
            if cost is not None:
                ent["modeled"] += 1
                ent["flops"], ent["bytes_accessed"] = cost
        return out


# Compile attribution: serving.py names the program it is about to
# dispatch (thread-local — each serving loop owns one batcher), and
# the process-wide jax.monitoring listener books any backend compile
# that fires during the call onto that program's Observability sink.
# Compiles outside an attributed dispatch (e.g. bench warmups on the
# main thread) are deliberately ignored: there is no sink to misfeed.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compile_attr = threading.local()
_listener_state = {"installed": False}
_listener_lock = threading.Lock()


def attribute_compiles(sink: "Observability", program: str) -> None:
    """Point this thread's compile events at ``sink`` as ``program``
    (two attribute writes — cheap enough for every dispatch)."""
    _compile_attr.sink = sink
    _compile_attr.program = program


def _compile_listener(event: str, duration_secs: float, **kw) -> None:
    if event != _COMPILE_EVENT:
        return
    sink = getattr(_compile_attr, "sink", None)
    if sink is None:
        return
    try:
        sink.record_compile(
            getattr(_compile_attr, "program", "unknown"),
            duration_secs * 1000.0,
        )
    except Exception:
        pass  # a metrics sink must never break a compile


def install_compile_listener() -> bool:
    """Register the process-wide compile listener (idempotent; lazy
    jax import keeps this module importable without jax)."""
    with _listener_lock:
        if _listener_state["installed"]:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _compile_listener
            )
        except Exception:
            return False
        _listener_state["installed"] = True
        return True


# ---------------------------------------------------------------------------
# Decision audit log + anomaly-detection building block
# ---------------------------------------------------------------------------

class DecisionLog:
    """Bounded ring of structured control-plane decision events.

    One event per decision the control plane took — route / reroute /
    handoff (router.py), brownout rung move / recovery / quarantine /
    probe / shed / drain (server.py), canary result / anomaly /
    verdict flip (the health sentinel) — each a dict carrying ``seq``
    (monotonic, survives ring eviction so consumers can detect gaps),
    ``t_ms`` (relative to the log's epoch), ``unix_s`` (wall clock,
    for cross-process joins), ``kind``, the external ``request_id``
    where one exists (the join key back to request timelines), and
    whatever fields the decision point attached (candidate sets,
    scores, hit depths, errors).

    Thread-safe under its own leaf lock (registered in
    analysis/lockcheck.py): decision points record from serving-loop /
    poller / handler threads while ``/debug/decisions`` snapshots.
    The lock is never held while calling out."""

    def __init__(self, ring: int = 512, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._t0 = clock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=ring)
        self._seq = 0
        self.counts: Dict[str, int] = {}

    def record(self, kind: str, request_id: Optional[str] = None,
               **fields) -> int:
        """Append one decision event; returns its seq number."""
        ev: Dict[str, Any] = {
            "seq": -1,
            "t_ms": round((self._clock() - self._t0) * 1000.0, 3),
            "unix_s": round(time.time(), 3),
            "kind": kind,
        }
        if request_id:
            ev["request_id"] = request_id
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self._ring.append(ev)
            self.counts[kind] = self.counts.get(kind, 0) + 1
            return ev["seq"]

    def total(self) -> int:
        """Events ever recorded (ring evictions included)."""
        with self._lock:
            return self._seq

    def counts_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)

    def json(self, n: int = 128, kind: Optional[str] = None,
             request_id: Optional[str] = None) -> Dict[str, Any]:
        """The ``GET /debug/decisions[?n=&kind=&request_id=]`` payload:
        the most recent ``n`` events after filtering (events the ring
        already evicted are gone — ``events_total`` vs ``len`` tells a
        consumer how much history survives)."""
        with self._lock:
            evs = list(self._ring)
            total = self._seq
            counts = dict(self.counts)
            ring = self._ring.maxlen
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if request_id is not None:
            evs = [e for e in evs if e.get("request_id") == request_id]
        evs = evs[-n:] if n > 0 else []
        return {
            "decisions": [dict(e) for e in evs],
            "events_total": total,
            "counts": counts,
            "ring": ring,
        }

    def for_request(self, request_id: str,
                    n: int = 64) -> List[Dict[str, Any]]:
        """The decision events carrying ``request_id`` — the join the
        fleet request lookup attaches to a timeline."""
        return self.json(n=n, request_id=request_id)["decisions"]


class EwmaDetector:
    """Online EWMA mean/variance with z-score anomaly scoring.

    ``update(x)`` returns the z-score of ``x`` against the statistics
    BEFORE the update (so a spike scores against the healthy baseline,
    not against itself), or None during warmup (< ``min_samples``
    observations — no baseline, no verdict).  The variance follows the
    standard exponentially-weighted recurrence; the divisor is floored
    (relative to the mean, and absolutely by ``floor``) so a
    near-constant healthy signal does not turn measurement noise into
    infinite z.  ``floor`` must be set in the SIGNAL'S OWN UNITS: for
    millisecond latencies a floor of ~1 ms says "a deviation under a
    millisecond is never an anomaly, whatever the variance" — without
    it, a 0.05 ms queue-wait baseline turns one harmless 3 ms blip
    into z≈500 and a false critical verdict.

    NOT itself synchronized: the health sentinel mutates it under its
    own lock."""

    def __init__(self, alpha: float = 0.2, min_samples: int = 5,
                 floor: float = 1e-6):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.floor = float(floor)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> Optional[float]:
        x = float(x)
        z: Optional[float] = None
        if self.n >= self.min_samples:
            sd = math.sqrt(max(self.var, 0.0))
            z = (x - self.mean) / max(
                sd, abs(self.mean) * 0.05, self.floor
            )
        if self.n == 0:
            self.mean = x
        else:
            a = self.alpha
            d = x - self.mean
            self.mean += a * d
            self.var = (1.0 - a) * (self.var + a * d * d)
        self.n += 1
        return z


# ---------------------------------------------------------------------------
# Timeline / dispatch records
# ---------------------------------------------------------------------------

# Request lifecycle states (the PR 5/6 admission state machine) plus
# terminal outcomes.
STATES = ("queued", "prefilling", "restoring", "decoding")
OUTCOMES = ("finished", "failed", "cancelled")

_MAX_SPANS = 64            # per timeline (replays append; bound them)
_MAX_SPAN_DISPATCHES = 512  # dispatch links per span
_MAX_RIDS = 8              # batcher incarnations indexed per timeline


class _Span:
    __slots__ = ("state", "t0", "t1", "dispatches", "dropped", "note")

    def __init__(self, state: str, t0: float, note: Optional[str] = None):
        self.state = state
        self.t0 = t0
        self.t1: Optional[float] = None
        self.dispatches: List[int] = []
        self.dropped = 0  # dispatch links past _MAX_SPAN_DISPATCHES
        self.note = note


class _Timeline:
    __slots__ = (
        "request_id", "rids", "prompt_tokens", "created", "spans",
        "outcome", "error", "route", "kv",
    )

    def __init__(self, request_id: str, rid: int, prompt_tokens: int,
                 t: float):
        self.request_id = request_id
        self.rids: List[int] = [rid]
        self.prompt_tokens = prompt_tokens
        self.created = t
        self.spans: List[_Span] = []
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        # Routing decision (ReplicaRouter via the X-Routed-By header):
        # which replica/policy served this request — shown by
        # /debug/requests/<id> next to the spans it annotates.
        self.route: Optional[str] = None
        # Per-session KV accounting (request_kv): blocks held, prefix
        # hit depth in tokens, swap bytes moved, evictions suffered.
        self.kv: Dict[str, Any] = {}


class Observability:
    """The serving stack's shared observability sink (module docstring).

    One instance is shared by a ``ContinuousBatcher`` and its
    ``LLMServer`` — and survives crash-recovery/quarantine rebuilds the
    same way the fault injector does (it rides the captured ctor
    kwargs), so timelines and histograms span batcher incarnations.

    ``ring`` bounds the dispatch ring, ``max_timelines`` the request-
    timeline LRU, ``max_events`` the annotation ring.  ``clock`` is
    injectable for tests."""

    def __init__(
        self,
        slo_ttft_ms: Optional[float] = None,
        slo_itl_ms: Optional[float] = None,
        ring: int = 512,
        max_timelines: int = 1024,
        max_events: int = 256,
        slo_window: int = 256,
        peak_flops: float = DEFAULT_PEAK_FLOPS,
        peak_bytes_per_s: float = DEFAULT_PEAK_BYTES_PER_S,
        util_window: int = 64,
        decision_ring: int = 512,
        max_snapshots: int = 128,
        clock=time.monotonic,
    ):
        self.slo_ttft_ms = (
            float(slo_ttft_ms) if slo_ttft_ms else None
        )
        self.slo_itl_ms = float(slo_itl_ms) if slo_itl_ms else None
        self._clock = clock
        self.t0 = clock()
        # Wall-clock anchor captured at the SAME instant as the
        # monotonic t0: the fleet-merge (router /debug/trace) shifts
        # each replica's relative timestamps into a common frame via
        # the difference of these anchors (clock-offset normalization).
        self.t0_unix = time.time()
        self._lock = threading.Lock()
        self._seq = 0
        self.dispatches: "deque[Dict[str, Any]]" = deque(maxlen=ring)
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=max_events)
        self._max_timelines = int(max_timelines)
        self._timelines: "OrderedDict[str, _Timeline]" = OrderedDict()
        self._by_rid: Dict[int, _Timeline] = {}
        # Decision audit log (its own leaf lock — never nested with
        # self._lock) + the flight recorder's periodic metric-snapshot
        # ring (server.py feeds it every flight_interval_s; the
        # /debug/bundle artifact exports it).  Both survive batcher
        # rebuilds with the rest of this instance.
        self.decisions = DecisionLog(ring=decision_ring, clock=clock)
        self.metric_snapshots: "deque[Dict[str, Any]]" = deque(
            maxlen=max_snapshots
        )
        # Device-time attribution: hardware peaks (0 disables the
        # corresponding gauge) and a per-kind sliding window of
        # (flops, bytes, wall_ms, device_est_ms) from dispatches that
        # carried a cost model.
        self.peak_flops = float(peak_flops or 0.0)
        self.peak_bytes_per_s = float(peak_bytes_per_s or 0.0)
        self._util_window = int(util_window)
        self._util: Dict[str, "deque[Tuple[float, float, float, float]]"]
        self._util = {}
        # Jit-cache observability: compile spans (bounded ring, a
        # trace track of their own) + per-program counters, fed by the
        # process-wide jax.monitoring listener via record_compile.
        self.compiles: "deque[Dict[str, Any]]" = deque(maxlen=max_events)
        self.compiles_total = 0
        self.compiles_by_program: Dict[str, int] = {}
        # Optional dispatch-record sink (overload.py's throughput
        # EWMAs feed off it).  Called OUTSIDE self._lock with the
        # already-built record dict — the sink takes its own lock, and
        # calling out under ours would order the two locks.  Settable
        # after construction (the server wires its controller here).
        self.on_dispatch: Optional[Any] = None
        self.hist: Dict[str, Histogram] = {
            name: Histogram(
                name, help_text,
                buckets=HISTOGRAM_BUCKETS.get(name, DEFAULT_BUCKETS_MS),
            )
            for name, help_text in HISTOGRAMS.items()
            if name not in LABELED_HISTOGRAMS
        }
        # Per-kind dispatch_ms series (one Histogram per dispatch
        # kind, created lazily under the lock on first dispatch).
        self.hist_dispatch: Dict[str, Histogram] = {}
        # Outcome / SLO accounting.
        self.requests_finished_total = 0
        self.requests_failed_total = 0
        self.requests_cancelled_total = 0
        self.requests_slo_ok_total = 0
        self.goodput_tokens_total = 0
        self._slo_window: "deque[Tuple[bool, bool, bool]]" = deque(
            maxlen=slo_window
        )

    # -- internal helpers ---------------------------------------------------

    def _now_ms(self) -> float:
        return (self._clock() - self.t0) * 1000.0

    def _evict_locked(self) -> None:
        while len(self._timelines) > self._max_timelines:
            # Prefer the oldest TERMINAL timeline: evicting a live one
            # mid-flight would make its later request_end a no-op (the
            # finished counter undercounts and /debug 404s for a
            # request still being served) — and the longest-lived
            # requests are exactly the ones worth debugging.  Only
            # when every entry is live (a pathological burst) does the
            # oldest go regardless, keeping the bound hard.
            key = next(
                (k for k, tl in self._timelines.items()
                 if tl.outcome is not None),
                next(iter(self._timelines)),
            )
            tl = self._timelines.pop(key)
            for rid in tl.rids:
                if self._by_rid.get(rid) is tl:
                    del self._by_rid[rid]

    def _current_span(self, tl: _Timeline) -> Optional[_Span]:
        return tl.spans[-1] if tl.spans else None

    def _begin_span_locked(self, tl: _Timeline, state: str,
                           note: Optional[str] = None) -> None:
        t = self._now_ms()
        cur = self._current_span(tl)
        if cur is not None and cur.t1 is None:
            cur.t1 = t
            if cur.state == "queued" and state in (
                "prefilling", "restoring"
            ):
                self.hist["queue_wait_ms"].observe(t - cur.t0)
        if len(tl.spans) >= _MAX_SPANS:
            return
        tl.spans.append(_Span(state, t, note))

    # -- request lifecycle (called by the batcher / server) -----------------

    def request_queued(self, rid: int, prompt_tokens: int) -> None:
        """A request entered the batcher queue (``submit``); creates a
        timeline under the provisional id ``r<rid>`` until the server
        binds the external one."""
        with self._lock:
            tl = _Timeline(f"r{rid}", rid, prompt_tokens, self._clock())
            self._timelines[tl.request_id] = tl
            self._by_rid[rid] = tl
            self._begin_span_locked(tl, "queued")
            self._evict_locked()

    def bind(self, rid: int, request_id: str,
             replay: bool = False) -> None:
        """Attach the server's external request id to ``rid``'s
        timeline.  On a crash-recovery replay (``replay=True``, passed
        by the server's rebuild-and-replay path) the external id
        already owns a timeline: the fresh rid (and its new ``queued``
        span) folds into it, so ``/debug/requests/<id>`` shows the
        whole story across batcher incarnations.

        A NON-replay bind that collides with an existing timeline is a
        client reusing an ``X-Request-Id`` (proxies and retry layers
        do): the new request keeps its provisional ``r<rid>`` timeline
        instead of folding — merging two unrelated requests would
        clobber the live timeline's outcome and grow the merged record
        without bound on every reuse."""
        with self._lock:
            tl_rid = self._by_rid.get(rid)
            existing = self._timelines.get(request_id)
            if existing is None:
                if tl_rid is None:
                    return
                self._timelines.pop(tl_rid.request_id, None)
                tl_rid.request_id = request_id
                self._timelines[request_id] = tl_rid
            elif existing is not tl_rid and replay:
                if tl_rid is not None:
                    self._timelines.pop(tl_rid.request_id, None)
                    room = max(0, _MAX_SPANS - len(existing.spans))
                    for sp in tl_rid.spans[:room]:
                        sp.note = sp.note or "replay"
                        existing.spans.append(sp)
                existing.rids.append(rid)
                # Bound the per-timeline rid list (and the _by_rid
                # index entries it keeps alive): only the most recent
                # incarnations stay addressable by bare rid.
                while len(existing.rids) > _MAX_RIDS:
                    old = existing.rids.pop(0)
                    if self._by_rid.get(old) is existing:
                        del self._by_rid[old]
                existing.outcome = None
                existing.error = None
                self._by_rid[rid] = existing
                self._timelines.move_to_end(request_id)

    def set_route(self, request_id: str, route: str) -> None:
        """Record a ReplicaRouter's decision on the request's timeline
        (called by the server after ``bind`` when the POST carried an
        ``X-Routed-By`` header) AND drop an instant event into the
        annotation ring, so the decision shows both in
        ``/debug/requests/<id>`` and on the trace."""
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is not None:
                tl.route = route
            self.events.append({
                "t_ms": round(self._now_ms(), 3), "name": "routed",
                "fields": {"request_id": request_id, "via": route},
            })

    def begin_span(self, rid: int, state: str,
                   note: Optional[str] = None) -> None:
        """Transition ``rid`` into a lifecycle state (ends the current
        span; queued->prefilling/restoring edges feed the queue-wait
        histogram)."""
        with self._lock:
            tl = self._by_rid.get(rid)
            if tl is not None:
                self._begin_span_locked(tl, state, note)

    def request_end(self, rid: int, outcome: str,
                    error: Optional[str] = None) -> None:
        """Terminal transition (finished / failed / cancelled)."""
        with self._lock:
            tl = self._by_rid.get(rid)
            if tl is None:
                return
            t = self._now_ms()
            cur = self._current_span(tl)
            if cur is not None and cur.t1 is None:
                cur.t1 = t
            tl.outcome = outcome
            tl.error = error
            if outcome == "finished":
                self.requests_finished_total += 1
            elif outcome == "cancelled":
                self.requests_cancelled_total += 1
            else:
                self.requests_failed_total += 1

    def request_rejected(self, request_id: str, error: str) -> None:
        """A request the server answered (504/503) without it ever
        reaching the batcher — the overload signature: it expired in
        the server inbox, so no rid exists and ``request_queued`` never
        fired.  Record a minimal terminal timeline under the external
        id and count the failure, so ``/debug/requests/<id>`` and
        ``requests_failed_total`` agree with the error the client saw
        (without this, attainment drops while the failure counter
        stays flat — the two overload signals would contradict)."""
        with self._lock:
            # The failure COUNTS regardless of id reuse — every 504 the
            # client saw is a failure, or attainment drops while the
            # counter stays flat (the divergence this method removes).
            self.requests_failed_total += 1
            if request_id in self._timelines:
                return  # id reuse: keep the existing richer record
            tl = _Timeline(request_id, rid=-1, prompt_tokens=0,
                           t=self._clock())
            tl.rids = []  # no batcher incarnation ever existed
            t = self._now_ms()
            sp = _Span("queued", t)
            sp.t1 = t
            tl.spans.append(sp)
            tl.outcome = "failed"
            tl.error = error
            self._timelines[request_id] = tl
            self._evict_locked()

    # -- dispatch spans ------------------------------------------------------

    def record_dispatch(
        self,
        kind: str,
        k: int = 1,
        occupancy: int = 0,
        prefill_tokens: int = 0,
        wall_ms: float = 0.0,
        fetch_ms: float = 0.0,
        swap_inflight: int = 0,
        rids: Sequence[int] = (),
        program: Optional[str] = None,
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
    ) -> int:
        """Record one jitted serving dispatch and link it into the
        CURRENT span of every request that rode it.  Returns the
        dispatch's ring-global seq number.  ``wall_ms`` covers dispatch
        submit through the packed fetch (what the host actually waited);
        ``fetch_ms`` isolates the ``np.asarray`` device sync.
        ``program`` names the jitted program; ``flops`` /
        ``bytes_accessed`` are its static cost model (CostModelCache) —
        when present the record carries a roofline device-time estimate
        and feeds the per-kind utilization window."""
        if kind not in DISPATCH_KINDS:
            raise ValueError(
                f"unknown dispatch kind {kind!r}; have "
                f"{sorted(DISPATCH_KINDS)}"
            )
        t = self._now_ms()
        rec = {
            "seq": -1, "kind": kind, "k": int(k),
            "occupancy": int(occupancy),
            "prefill_tokens": int(prefill_tokens),
            "start_ms": round(t - wall_ms, 3),
            "wall_ms": round(wall_ms, 3),
            "fetch_ms": round(fetch_ms, 3),
            "swap_inflight": int(swap_inflight),
            "rids": list(rids),
        }
        if program is not None:
            rec["program"] = program
        est_ms = None
        if flops is not None and bytes_accessed is not None:
            est = 0.0
            if self.peak_flops > 0:
                est = max(est, float(flops) / self.peak_flops * 1000.0)
            if self.peak_bytes_per_s > 0:
                est = max(
                    est,
                    float(bytes_accessed) / self.peak_bytes_per_s
                    * 1000.0,
                )
            if est > 0:
                est_ms = est
                rec["flops"] = float(flops)
                rec["bytes_accessed"] = float(bytes_accessed)
                rec["device_est_ms"] = round(est, 6)
        with self._lock:
            seq = self._seq
            self._seq += 1
            rec["seq"] = seq
            self.dispatches.append(rec)
            h = self.hist_dispatch.get(kind)
            if h is None:
                h = self.hist_dispatch[kind] = Histogram(
                    "dispatch_ms", HISTOGRAMS["dispatch_ms"],
                    labels={"kind": kind},
                )
            h.observe(wall_ms)
            if est_ms is not None:
                dq = self._util.get(kind)
                if dq is None:
                    dq = self._util[kind] = deque(
                        maxlen=self._util_window
                    )
                dq.append(
                    (float(flops), float(bytes_accessed), wall_ms,
                     est_ms)
                )
            if prefill_tokens > 0 or kind in ("insert", "suffix_insert"):
                self.hist["prefill_chunk_ms"].observe(wall_ms)
            for rid in rids:
                tl = self._by_rid.get(rid)
                if tl is None:
                    continue
                sp = self._current_span(tl)
                if sp is None:
                    continue
                if len(sp.dispatches) < _MAX_SPAN_DISPATCHES:
                    sp.dispatches.append(seq)
                else:
                    sp.dropped += 1
        # Outside the lock: the overload controller's EWMA ingest takes
        # its own lock (lock-order discipline; the record dict is
        # already fully built and never mutated after this point).
        if self.on_dispatch is not None:
            self.on_dispatch(rec)
        return seq

    def record_compile(self, program: str, dur_ms: float) -> None:
        """One backend jit compile landed (fed by the jax.monitoring
        listener; ``program`` is whatever serving.py last attributed
        on the compiling thread).  Becomes a compile_ms observation, a
        span on the trace's ``jit compiles`` track, and a per-program
        counter."""
        with self._lock:
            t = self._now_ms()
            self.hist["compile_ms"].observe(dur_ms)
            self.compiles.append({
                "program": program, "t_ms": round(t, 3),
                "dur_ms": round(dur_ms, 3),
            })
            self.compiles_total += 1
            self.compiles_by_program[program] = (
                self.compiles_by_program.get(program, 0) + 1
            )

    def record_swap_in(self, ms: float, blocks: int) -> None:
        """A host-tier swap-in landed (staging start -> adoption)."""
        with self._lock:
            self.hist["swap_in_ms"].observe(ms)
        self.annotate("kv_swap_in", ms=round(ms, 3), blocks=blocks)

    # -- per-session KV accounting ------------------------------------------

    # request_kv fields that ACCUMULATE across calls (a replay or a
    # second swap-in adds to the session's ledger); everything else is
    # set-latest (gauge semantics: blocks_held, prefix_hit_tokens).
    _KV_ADDITIVE = frozenset({
        "swap_in_bytes", "swap_out_bytes", "evictions_suffered",
    })

    def request_kv(self, rid: int, **fields) -> None:
        """Merge per-session KV accounting onto ``rid``'s timeline —
        blocks held, prefix-hit depth in tokens, swap bytes moved,
        evictions suffered — shown under ``kv`` in
        ``/debug/requests/<id>``.  Host bookkeeping only."""
        with self._lock:
            tl = self._by_rid.get(rid)
            if tl is None:
                return
            for k, v in fields.items():
                if k in self._KV_ADDITIVE:
                    tl.kv[k] = tl.kv.get(k, 0) + v
                else:
                    tl.kv[k] = v

    def observe_kv(self, hit_depth_tokens: Optional[int] = None,
                   session_blocks: Optional[int] = None) -> None:
        """Feed the KV-capacity histograms: prefix-hit depth at
        admission, session block footprint at slot free."""
        with self._lock:
            if hit_depth_tokens is not None:
                self.hist["prefix_hit_depth_tokens"].observe(
                    hit_depth_tokens
                )
            if session_blocks is not None:
                self.hist["session_kv_blocks"].observe(session_blocks)

    def annotate(self, name: str, **fields) -> None:
        """Instant event into the bounded annotation ring (fault
        injections, quarantine transitions, kv-tier demotions...) —
        rendered as instant events in the Perfetto export."""
        with self._lock:
            self.events.append({
                "t_ms": round(self._now_ms(), 3), "name": name,
                "fields": fields,
            })

    def events_json(self, n: int = 256) -> List[Dict[str, Any]]:
        """Snapshot of the annotation ring (state transitions, fault
        injections, kv-tier events) — the flight recorder's
        state-transition record in ``/debug/bundle``."""
        with self._lock:
            items = list(self.events)[-n:] if n > 0 else []
        return [dict(e) for e in items]

    def record_metrics_snapshot(self, snapshot: Dict[str, Any]) -> None:
        """Flight recorder: append one periodic metric snapshot (a
        compact scalar dict the serving loop builds every
        ``flight_interval_s``) to the bounded ring — pure host
        bookkeeping, exported by ``/debug/bundle`` so a postmortem can
        see the trend into the incident, not just the final values."""
        rec = {
            "t_ms": round(self._now_ms(), 3),
            "unix_s": round(time.time(), 3),
        }
        rec.update(snapshot)
        with self._lock:
            self.metric_snapshots.append(rec)

    def metric_snapshots_json(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self.metric_snapshots]

    # -- server-side latency / SLO ------------------------------------------

    def observe_ttft(self, ms: float) -> None:
        # Locked: a concurrent /metrics scrape renders under the lock
        # and must never see a bucket updated ahead of _count (the
        # +Inf == _count invariant the parse test asserts).
        with self._lock:
            self.hist["ttft_ms"].observe(ms)

    def observe_itl(self, ms: float) -> None:
        with self._lock:
            self.hist["itl_ms"].observe(ms)

    def slo_account(
        self,
        ttft_ms: Optional[float],
        max_itl_ms: Optional[float],
        tokens: int,
        completed: bool = True,
    ) -> bool:
        """Score one finished request against the configured SLOs.
        ``ttft_ms`` None means no token was ever delivered (fails a
        configured TTFT SLO); an unconfigured dimension always passes;
        ``completed=False`` (failure/timeout) can never be goodput.
        Returns whether the request met every configured deadline."""
        ttft_ok = self.slo_ttft_ms is None or (
            ttft_ms is not None and ttft_ms <= self.slo_ttft_ms
        )
        itl_ok = self.slo_itl_ms is None or (
            max_itl_ms is None or max_itl_ms <= self.slo_itl_ms
        )
        ok = bool(completed and ttft_ok and itl_ok)
        with self._lock:
            self._slo_window.append((ttft_ok and completed,
                                     itl_ok and completed, ok))
            if ok:
                self.requests_slo_ok_total += 1
                self.goodput_tokens_total += int(tokens)
        return ok

    # -- exposition -----------------------------------------------------------

    def metrics(self) -> Dict[str, float]:
        """Scalar gauges/counters for the /metrics exposition (the
        histograms render separately via ``expose_histograms``)."""
        # Taken BEFORE self._lock: the decision log has its own leaf
        # lock and the two must never nest.
        decisions_total = self.decisions.total()
        with self._lock:
            n = len(self._slo_window) or 1
            ttft_ok = sum(1 for a, _, _ in self._slo_window if a)
            itl_ok = sum(1 for _, b, _ in self._slo_window if b)
            both = sum(1 for _, _, c in self._slo_window if c)
            return {
                "requests_finished_total": self.requests_finished_total,
                "requests_failed_total": self.requests_failed_total,
                "requests_cancelled_total": self.requests_cancelled_total,
                "decision_events_total": decisions_total,
                "compiles_total": self.compiles_total,
                "slo_ttft_ms": self.slo_ttft_ms or 0.0,
                "slo_itl_ms": self.slo_itl_ms or 0.0,
                "requests_slo_ok_total": self.requests_slo_ok_total,
                "goodput_tokens_total": self.goodput_tokens_total,
                "slo_ttft_attainment": round(ttft_ok / n, 4),
                "slo_itl_attainment": round(itl_ok / n, 4),
                "slo_attainment": round(both / n, 4),
            }

    def expose_histograms(self, prefix: str = "llm_") -> List[str]:
        with self._lock:
            lines: List[str] = []
            for h in self.hist.values():
                lines.extend(h.expose(prefix))
            # The labeled dispatch_ms family: one HELP/TYPE header,
            # then every kind's series (header even when no dispatch
            # has landed yet, so the family is always discoverable).
            n = prefix + "dispatch_ms"
            lines.append(f"# HELP {n} {HISTOGRAMS['dispatch_ms']}")
            lines.append(f"# TYPE {n} histogram")
            for kind in sorted(self.hist_dispatch):
                lines.extend(
                    self.hist_dispatch[kind].expose(prefix, header=False)
                )
            return lines

    def utilization_metrics(
        self,
    ) -> List[Tuple[str, Dict[str, str], float]]:
        """Labeled device-time attribution samples for /metrics:
        ``(family, labels, value)`` triples — per-kind
        mxu_utilization / hbm_utilization / host_overhead_ratio over
        the recent dispatch window, plus per-program compile counters.
        Families are registered in METRICS; the server renders one
        HELP/TYPE header per family."""
        out: List[Tuple[str, Dict[str, str], float]] = []
        with self._lock:
            windows = {
                kind: list(dq) for kind, dq in self._util.items() if dq
            }
            compiles = sorted(self.compiles_by_program.items())
        for kind in sorted(windows):
            dq = windows[kind]
            wall_ms = sum(w for _, _, w, _ in dq)
            if wall_ms <= 0:
                continue
            wall_s = wall_ms / 1000.0
            lab = {"kind": kind}
            if self.peak_flops > 0:
                fl = sum(f for f, _, _, _ in dq)
                out.append((
                    "mxu_utilization", lab,
                    round(fl / wall_s / self.peak_flops, 6),
                ))
            if self.peak_bytes_per_s > 0:
                by = sum(b for _, b, _, _ in dq)
                out.append((
                    "hbm_utilization", lab,
                    round(by / wall_s / self.peak_bytes_per_s, 6),
                ))
            est_ms = sum(e for _, _, _, e in dq)
            if est_ms > 0:
                out.append((
                    "host_overhead_ratio", lab,
                    round(wall_ms / est_ms, 3),
                ))
        for prog, n in compiles:
            out.append(("program_compiles_total", {"program": prog}, n))
        return out

    # -- debug JSON ------------------------------------------------------------

    def _span_json(self, sp: _Span) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "state": sp.state,
            "start_ms": round(sp.t0, 3),
            "end_ms": round(sp.t1, 3) if sp.t1 is not None else None,
            "duration_ms": (
                round(sp.t1 - sp.t0, 3) if sp.t1 is not None else None
            ),
            "dispatches": list(sp.dispatches),
        }
        if sp.dropped:
            out["dispatches_dropped"] = sp.dropped
        if sp.note:
            out["note"] = sp.note
        return out

    def timeline_json(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The ``/debug/requests/<id>`` payload: the request's span
        timeline (accepts the external id, the provisional ``r<rid>``
        id, or a bare batcher rid)."""
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is None:
                tl = self._timelines.get(f"r{request_id}")
            if tl is None:
                try:
                    tl = self._by_rid.get(int(request_id))
                except ValueError:
                    tl = None
            if tl is None:
                return None
            seqs = {
                s for sp in tl.spans for s in sp.dispatches
            }
            return {
                "request_id": tl.request_id,
                "rids": list(tl.rids),
                "prompt_tokens": tl.prompt_tokens,
                "outcome": tl.outcome,
                "error": tl.error,
                "route": tl.route,
                "kv": dict(tl.kv),
                "spans": [self._span_json(sp) for sp in tl.spans],
                "dispatch_spans": [
                    dict(d) for d in self.dispatches if d["seq"] in seqs
                ],
            }

    def requests_json(self, n: int = 64) -> Dict[str, Any]:
        """Index of recent request timelines (most recent last).
        ``n <= 0`` returns nothing (``[-0:]`` would return the whole
        store)."""
        with self._lock:
            items = list(self._timelines.values())[-n:] if n > 0 else []
            return {"requests": [
                {
                    "request_id": tl.request_id,
                    "rids": list(tl.rids),
                    "outcome": tl.outcome,
                    "states": [sp.state for sp in tl.spans],
                }
                for tl in items
            ]}

    def dispatches_json(self, n: int = 128) -> Dict[str, Any]:
        with self._lock:
            items = list(self.dispatches)[-n:] if n > 0 else []
            return {"dispatches": [dict(d) for d in items]}

    def trace_json(self, window_ms: Optional[float] = None) -> Dict[str, Any]:
        """Chrome/Perfetto ``trace_event`` JSON for the recent serving
        window (default: everything the rings still hold).  Dispatches
        render on pid 1 / tid 1, request lifecycles on one tid per
        request, annotations as instant events — load the payload in
        chrome://tracing or https://ui.perfetto.dev."""
        horizon = None
        if window_ms is not None:
            horizon = self._now_ms() - float(window_ms)
        # Snapshot under the lock, BUILD outside it: constructing tens
        # of thousands of event dicts while holding the one lock the
        # serving loop needs per dispatch would inject exactly the
        # decode-chunk stall this layer exists to measure.  Dispatch
        # and annotation dicts are created once and never mutated, so
        # the list copies are reference-shallow; only the mutable
        # _Span fields are copied out.
        with self._lock:
            dispatches = list(self.dispatches)
            events = list(self.events)
            compiles = list(self.compiles)
            now_ms = self._now_ms()
            timelines = [
                (tl.request_id, tl.outcome, [
                    (sp.state, sp.t0, sp.t1, sp.dispatches[:64])
                    for sp in tl.spans
                ])
                for tl in self._timelines.values()
            ]
        ev: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "dispatches"}},
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "jit compiles"}},
        ]
        for d in dispatches:
            if horizon is not None and d["start_ms"] < horizon:
                continue
            ev.append({
                "name": f"{d['kind']} k={d['k']}",
                "cat": "dispatch", "ph": "X", "pid": 1, "tid": 1,
                "ts": round(d["start_ms"] * 1000.0, 1),
                "dur": max(1, round(d["wall_ms"] * 1000.0)),
                "args": {
                    k: d[k] for k in (
                        "seq", "occupancy", "prefill_tokens",
                        "fetch_ms", "swap_inflight", "rids",
                        "program", "device_est_ms",
                    ) if k in d
                },
            })
        for c in compiles:
            end = c["t_ms"]
            if horizon is not None and end < horizon:
                continue
            ev.append({
                "name": f"compile {c['program']}",
                "cat": "compile", "ph": "X", "pid": 1, "tid": 0,
                "ts": round((end - c["dur_ms"]) * 1000.0, 1),
                "dur": max(1, round(c["dur_ms"] * 1000.0)),
                "args": {"program": c["program"]},
            })
        tid = 2
        for request_id, outcome, spans in timelines:
            spans = [
                sp for sp in spans
                if horizon is None or sp[2] is None or sp[2] >= horizon
            ]
            if not spans:
                continue
            ev.append({
                "ph": "M", "pid": 1, "tid": tid,
                "name": "thread_name",
                "args": {"name": f"req {request_id}"},
            })
            for state, t0, t1, links in spans:
                if t1 is None:
                    t1 = now_ms
                ev.append({
                    "name": state, "cat": "request", "ph": "X",
                    "pid": 1, "tid": tid,
                    "ts": round(t0 * 1000.0, 1),
                    "dur": max(1, round((t1 - t0) * 1000.0)),
                    "args": {
                        "request_id": request_id,
                        "dispatches": links,
                        "outcome": outcome,
                    },
                })
            tid += 1
        # KV-cache events (tier demotions / host-LRU drops / evictions
        # / swap-ins / handoff export+import) get their OWN track, so a
        # trace window reads cache churn as one lane instead of noise
        # interleaved with dispatch annotations.  Each instant's args
        # keep whatever rid/request_id the emitter attached — the link
        # back to the owning request's track.
        kv_tid = tid
        kv_named = False
        for e in events:
            if horizon is not None and e["t_ms"] < horizon:
                continue
            is_kv = e["name"].startswith("kv_") or e["name"] in (
                "prefix_export", "prefix_import",
            )
            if is_kv and not kv_named:
                kv_named = True
                ev.append({
                    "ph": "M", "pid": 1, "tid": kv_tid,
                    "name": "thread_name",
                    "args": {"name": "kv cache"},
                })
            ev.append({
                "name": e["name"], "cat": "annotation", "ph": "i",
                "pid": 1, "tid": kv_tid if is_kv else 1, "s": "g",
                "ts": round(e["t_ms"] * 1000.0, 1),
                "args": dict(e["fields"]),
            })
        # t0_unix_s: the wall-clock instant ts==0 corresponds to —
        # the router's fleet merge uses it to shift every replica's
        # relative timestamps into one frame (Perfetto ignores
        # unknown top-level keys).
        return {
            "traceEvents": ev, "displayTimeUnit": "ms",
            "t0_unix_s": round(self.t0_unix, 6),
        }


# ---------------------------------------------------------------------------
# Structured logging
# ---------------------------------------------------------------------------

class StructuredLogger:
    """One formatter for every server/batcher log line.

    ``json_mode=False`` (default) renders ``ts event k=v ...`` text;
    ``json_mode=True`` (run.py ``--log-json``) renders one JSON object
    per line with stable ``event`` / ``request_id`` / ``dispatch_seq``
    fields, so a fleet's log pipeline can join server lines to
    ``/debug`` timelines without regexes.  Writes are single ``print``
    calls (atomic enough under the GIL for line-oriented collectors).

    Every formatted line also lands in a bounded in-memory ring — the
    flight recorder's LOG TAIL, exported by ``/debug/bundle`` so a
    postmortem artifact carries the last ``ring`` log lines even when
    nobody captured stdout.  ``quiet=True`` keeps the ring but never
    prints (the server's default logger when the caller supplied
    none: the bundle still has a tail, stdout stays silent)."""

    def __init__(self, json_mode: bool = False, stream=None,
                 ring: int = 256, quiet: bool = False):
        self.json_mode = bool(json_mode)
        self.stream = stream if stream is not None else sys.stdout
        self.quiet = bool(quiet)
        self._lock = threading.Lock()
        self._ring: "deque[str]" = deque(maxlen=ring)

    def log(self, event: str, message: str = "", **fields) -> None:
        if self.json_mode:
            rec: Dict[str, Any] = {
                "ts": round(time.time(), 3), "event": event,
            }
            if message:
                rec["message"] = message
            rec.update({k: v for k, v in fields.items() if v is not None})
            line = json.dumps(rec, default=str)
        else:
            parts = [event]
            if message:
                parts.append(message)
            parts.extend(
                f"{k}={v}" for k, v in fields.items() if v is not None
            )
            line = " ".join(parts)
        with self._lock:
            self._ring.append(line)
        if not self.quiet:
            print(line, file=self.stream, flush=True)

    def tail(self, n: int = 256) -> List[str]:
        """The most recent formatted log lines (flight-recorder tail)."""
        with self._lock:
            out = list(self._ring)
        return out[-n:] if n > 0 else []
