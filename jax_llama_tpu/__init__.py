"""jax_llama_tpu — a TPU-native LLaMA framework built from scratch in JAX.

Public API (capability parity with the reference's ``jax_llama/__init__.py``
surface, re-expressed for the functional TPU-first design):

  Model:      LLaMAConfig, get_config, init_params, forward, KVCache,
              init_cache
  Parallel:   make_mesh, auto_mesh, use_mesh, constrain
  Decode:     GenerationConfig, generate, score, generate_speculative,
              LLaMA, ContinuousBatcher
  Tokenizers: ByteTokenizer (vocab-file-free; LLaMA2/3 tokenizers in
              jax_llama_tpu.tokenizers)
  Weights:    convert_meta_checkpoint, save_checkpoint, load_checkpoint
              (jax_llama_tpu.convert; CLI: python -m jax_llama_tpu.convert)
"""

from .config import LLaMAConfig, get_config, swiglu_hidden_size
from .engine import GenerationConfig, generate, score
from .generation import LLaMA
from .serving import ContinuousBatcher
from .server import LLMServer
from .spec_decode import generate_speculative
from .models import (
    AuxOutput,
    KVCache,
    forward,
    init_cache,
    init_params,
    param_count,
)
from .ops.quant import QuantizedTensor, quantize_params
from .parallel import auto_mesh, constrain, make_mesh, use_mesh
from .tokenizers import ByteTokenizer

__version__ = "0.1.0"

__all__ = [
    "LLaMAConfig",
    "get_config",
    "swiglu_hidden_size",
    "GenerationConfig",
    "generate",
    "score",
    "generate_speculative",
    "ContinuousBatcher",
    "LLMServer",
    "LLaMA",
    "ByteTokenizer",
    "AuxOutput",
    "KVCache",
    "forward",
    "init_cache",
    "init_params",
    "param_count",
    "auto_mesh",
    "constrain",
    "make_mesh",
    "use_mesh",
    "QuantizedTensor",
    "quantize_params",
    "__version__",
]
