"""Serving CLI: load an Orbax checkpoint onto a mesh and complete prompts.

Entry-point parity with the reference example (``/root/reference/
jax_example.py:10-43``: build mesh → tokenizer → convert weights →
device_put → complete 2 prompts), redesigned around this framework's
pipeline: weights restore *sharded* straight from Orbax (no double host-RAM
copy — the defect flagged at SURVEY.md §3.1), and the decode loop is the
native jitted engine.

    python -m jax_llama_tpu.run \
        --ckpt-dir /path/to/llama3-8b-orbax \
        --tokenizer /path/to/tokenizer.model \
        [--llama2] [--tensor 4] [--fsdp 1] \
        [--prompt "..." --prompt "..."] \
        [--max-gen-len 256] [--temperature 0.8] [--top-p 0.95]
"""

from __future__ import annotations

import argparse

DEFAULT_PROMPTS = [
    "I believe the meaning of life is",
    "Simply put, the theory of relativity states that",
]


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True, help="Orbax checkpoint dir")
    ap.add_argument("--tokenizer", default=None)
    ap.add_argument("--llama2", action="store_true",
                    help="sentencepiece (llama2) tokenizer")
    ap.add_argument("--byte-tokenizer", action="store_true",
                    help="vocab-file-free byte tokenizer (smoke tests)")
    ap.add_argument("--tensor", type=int, default=0,
                    help="tensor-parallel degree (0 = all local devices)")
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--prompt", action="append", default=None)
    ap.add_argument("--max-gen-len", type=int, default=256)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-p", type=float, default=0.95)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--attn", default=None,
                    choices=["xla", "flash", "auto"],
                    help="override attn_impl from the checkpoint config "
                         "(auto = flash prefill + append-free xla decode; "
                         "recommended for long prompts)")
    ap.add_argument("--prefill-kernel", default=None,
                    choices=["flash", "splash", "auto"],
                    help="attention kernel for prefill/insert dispatches "
                         "(ops/kernels.py registry; auto = splash when the "
                         "geometry qualifies, else flash; fallback ladder "
                         "splash -> flash -> xla)")
    ap.add_argument("--decode-kernel", default=None,
                    choices=["paged", "stock-paged", "gathered", "auto"],
                    help="attention kernel for paged decode steps (auto = "
                         "the custom paged kernel; gathered = disable the "
                         "Pallas kernel, gathered-view XLA attention; "
                         "fallback ladder stock-paged -> paged -> gathered)")
    ap.add_argument("--quantize", action="store_true",
                    help="int8-quantize weights after load (weight-only, "
                         "per-channel; ~2x decode throughput)")
    ap.add_argument("--serve", action="store_true",
                    help="continuous-batching mode: read prompts (one per "
                         "line) from stdin, stream completions as they "
                         "finish; requests share a slot pool")
    ap.add_argument("--slots", type=int, default=4,
                    help="slot-pool size for --serve / --http")
    ap.add_argument("--serve-mesh", default=None, metavar="DP,TP",
                    help="serving-mesh geometry for --serve/--http: "
                         "'dp,tp' shards each batcher replica's chunk "
                         "programs over a data(dp) x tensor(tp) mesh — "
                         "the KV block pool shards its KV-head axis "
                         "over tp, per-slot state rows over dp "
                         "(parallel/serve_mesh.py; tp must divide the "
                         "model's KV heads, dp must divide --slots).  "
                         "A bare 'tp' means '1,tp'.  Default: the "
                         "--data/--fsdp/--tensor mesh")
    ap.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="data-parallel serving replicas behind one "
                         "HTTP door (--http only): N independent "
                         "batcher+server replicas — each owning a "
                         "mesh slice when the host has "
                         "N x (dp*tp) devices, sharing the mesh "
                         "otherwise — fronted by a ReplicaRouter "
                         "(router.py) that exposes the same protocol "
                         "on the --http port")
    ap.add_argument("--route", default="least-loaded",
                    choices=("least-loaded", "affinity", "cache-aware"),
                    help="replica routing policy: 'least-loaded' "
                         "(fewest in-flight requests), 'affinity' "
                         "(sticky sessions by prompt prefix, so "
                         "revisited chats land on the replica holding "
                         "their radix prefix chain), or 'cache-aware' "
                         "(GLOBALLY cache-aware: the router folds "
                         "every replica's chain digest into one radix "
                         "index and routes each request to the "
                         "replica holding the deepest matching "
                         "prefix, spilling to least-loaded past an "
                         "occupancy watermark and migrating chains "
                         "to where traffic lands via the handoff "
                         "scheduler)")
    ap.add_argument("--canary-interval-s", type=float, default=10.0,
                    help="router synthetic-canary period for "
                         "--replicas N: every interval the router "
                         "POSTs a tiny deterministic greedy probe "
                         "(reserved 'canary' priority class — "
                         "excluded from SLO/goodput/brownout inputs) "
                         "directly to every replica, token-checks it "
                         "against the fleet oracle, and feeds "
                         "latency/correctness into the per-replica "
                         "health sentinel (GET /debug/fleet).  "
                         "<= 0 disables the prober")
    ap.add_argument("--autoscale", action="store_true",
                    help="elastic fleet for --replicas N: start the "
                         "FleetController — scale-up under sustained "
                         "interactive-attainment / queue-wait "
                         "pressure, sentinel-gated scale-down with "
                         "live session migration (no dropped "
                         "sessions), every action a recorded "
                         "decision (GET /debug/decisions?kind=scale)."
                         "  New replicas reuse the seed replicas' "
                         "geometry (fresh device slices while the "
                         "host has them, time-sharing replica 0's "
                         "mesh after)")
    ap.add_argument("--autoscale-min", type=int, default=1,
                    help="floor on fleet size under --autoscale")
    ap.add_argument("--autoscale-max", type=int, default=8,
                    help="ceiling on fleet size under --autoscale")
    ap.add_argument("--autoscale-interval-s", type=float, default=5.0,
                    help="control-loop period under --autoscale "
                         "(<= 0: no background loop — operator "
                         "drives ticks)")
    ap.add_argument("--replica-roles", default=None, metavar="R,R,...",
                    help="prefill/decode disaggregation for "
                         "--replicas N: a comma list of one role per "
                         "replica ('prefill' | 'decode').  Cold "
                         "prompts route to the least-loaded prefill "
                         "replica; a request finishing there streams "
                         "its prefix KV to a decode replica "
                         "(export->import handoff) and the session "
                         "re-pins there, so revisits decode warm.  "
                         "Requires --route cache-aware (the "
                         "scheduler routes off the global radix "
                         "index); needs at least one replica of "
                         "each role")
    ap.add_argument("--decode-chunk", type=int, default=8,
                    help="fuse up to this many decode iterations per "
                         "jitted dispatch in --serve / --http "
                         "(token-identical to 1; stop detection and "
                         "batcher state live on device, the host syncs "
                         "once per chunk instead of once per token; "
                         "effective K adapts down to 1 around "
                         "admissions; 1 restores the classic per-token "
                         "loop.  Speculative serving chunks by ROUNDS "
                         "through --spec-rounds instead)")
    ap.add_argument("--prefill-budget", type=int, default=512,
                    help="fused prefill-decode scheduling for --serve / "
                         "--http: admissions that would stall decoding "
                         "rows advance up to this many prompt tokens "
                         "per decode-chunk dispatch instead of running "
                         "a separate whole-prompt prefill (stall-free "
                         "chunked prefill; token-identical, first token "
                         "emitted by the dispatch that finishes the "
                         "prompt).  The default amortizes a 16k prompt "
                         "over ~32 steady decode chunks; 0 restores "
                         "classic whole-prompt admission.  Ignored "
                         "under --draft-ckpt-dir (speculative serving "
                         "keeps classic admission)")
    ap.add_argument("--draft-ckpt-dir", default=None,
                    help="Orbax checkpoint dir of a DRAFT model for "
                         "speculative serving in --serve / --http "
                         "(must share the target's vocabulary; the "
                         "draft only changes speed, never content)")
    ap.add_argument("--n-draft", type=int, default=4,
                    help="draft tokens proposed per speculative round "
                         "(with --draft-ckpt-dir)")
    ap.add_argument("--spec-rounds", type=int, default=8,
                    help="fuse up to this many speculative draft+verify "
                         "rounds per jitted dispatch (the speculative "
                         "twin of --decode-chunk; token-identical to 1 "
                         "including the acceptance pattern; the "
                         "effective R adapts down to 1 around "
                         "admissions; 1 restores the classic "
                         "per-round loop)")
    ap.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="serve over HTTP on this port (POST /generate "
                         "with blocking or NDJSON-streaming responses, "
                         "POST /chat for llama-3 tokenizers, "
                         "GET /metrics, /healthz) instead of the stdin "
                         "loop; 0 picks a free port")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable prompt prefix caching in the serving "
                         "pool (on by default; hits are token-identical "
                         "in tested configurations — this is a "
                         "memory/debug knob; equivalent to "
                         "--prefix-index off)")
    ap.add_argument("--prefix-index", default="radix",
                    choices=["radix", "exact", "off"],
                    help="prefix-cache index for --serve / --http: "
                         "'radix' (default) shares partial prompt "
                         "prefixes across ALL cached chains through a "
                         "block-granular radix tree (leaves-first "
                         "eviction, host-tier residency); 'exact' keeps "
                         "the legacy flat exact-chain map (the "
                         "behavioral oracle, no host tier); 'off' "
                         "disables matching and retention")
    ap.add_argument("--host-kv-blocks", type=int, default=0,
                    help="host-DRAM KV block tier capacity for --serve "
                         "/ --http (requires --prefix-index radix): "
                         "cold prefix-cache blocks evict into pinned "
                         "host memory instead of being freed, and "
                         "sessions whose cached prefix was demoted "
                         "swap it back into HBM asynchronously, "
                         "overlapped on the decode chunk (a restoring "
                         "request waits; decode rows never stall).  "
                         "0 (default) disables the tier; size it to "
                         "taste — each block holds "
                         "2*n_layers*kv_heads*block_size*head_dim KV "
                         "entries per model")
    ap.add_argument("--logprobs", action="store_true",
                    help="compute per-token model logprobs so HTTP "
                         "requests may ask for them (\"logprobs\": true)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address for --http")
    ap.add_argument("--inject-faults", default=None, metavar="SPEC",
                    help="deterministic fault injection for chaos runs "
                         "(--http only): comma-separated "
                         "site[@N|~P]:kind[=v] rules — sites step, "
                         "insert, suffix_insert, prefill_chunk, alloc, "
                         "kv_swap, "
                         "flash_kernel, paged_kernel, spec_decode; "
                         "kinds error, "
                         "oom, delay=SECONDS, nan; e.g. 'step@5:error' "
                         "or 'paged_kernel~0.01:error'.  Also read from "
                         "the JLT_FAULTS env var")
    ap.add_argument("--fault-seed", type=int, default=0,
                    help="seed for probabilistic (site~P) fault rules")
    ap.add_argument("--max-recoveries", type=int, default=3,
                    help="crash recoveries (batcher rebuild + request "
                         "replay) allowed per --recovery-window-s "
                         "before the server hard-drains with 503s")
    ap.add_argument("--recovery-window-s", type=float, default=60.0)
    ap.add_argument("--watchdog-s", type=float, default=60.0,
                    help="flip /healthz degraded when the serving loop "
                         "heartbeat stalls past this many seconds "
                         "(0 disables the watchdog thread)")
    ap.add_argument("--quarantine-threshold", type=int, default=3,
                    help="failures attributable to one feature (flash/"
                         "paged kernel, speculative decode, prefix "
                         "cache) inside --quarantine-window-s before it "
                         "is quarantined onto its XLA/plain fallback "
                         "(the server stays up, degraded)")
    ap.add_argument("--quarantine-window-s", type=float, default=60.0)
    ap.add_argument("--quarantine-cooldown-s", type=float, default=30.0,
                    help="how long a quarantined feature stays on its "
                         "fallback before one probe re-trial")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM/SIGINT drain budget: in-flight "
                         "requests run to completion (new POSTs get "
                         "503 + Retry-After); stragglers past this "
                         "many seconds are failed with 503")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="time-to-first-token SLO deadline in ms for "
                         "--http: finished requests are scored against "
                         "it and /metrics exposes attainment gauges "
                         "(llm_slo_ttft_attainment, window 256) plus "
                         "llm_goodput_tokens_total — tokens from "
                         "requests that met EVERY configured deadline.  "
                         "0 (default) leaves the dimension unset "
                         "(always passes)")
    ap.add_argument("--slo-itl-ms", type=float, default=0.0,
                    help="inter-token-latency SLO deadline in ms for "
                         "--http: a request passes when its WORST "
                         "token gap stays under it.  0 (default) "
                         "leaves the dimension unset")
    ap.add_argument("--priority-classes", default="on",
                    choices=["on", "off"],
                    help="overload control for --http (overload.py): "
                         "'on' (default) enables the optional "
                         "per-request \"priority\" field (interactive "
                         "| batch) with strict interactive-first "
                         "admission, cost-based deadline refusals "
                         "(503 + load-derived Retry-After when a "
                         "request's timeout_s provably cannot be "
                         "met), and the SLO-driven brownout ladder; "
                         "'off' keeps plain FIFO admission with only "
                         "the --max-queue depth backstop")
    ap.add_argument("--max-queue", type=int, default=256,
                    help="pre-admission queue depth backstop for "
                         "--http: past it new POSTs are refused 503 + "
                         "Retry-After (each blocked POST holds an OS "
                         "thread, so this bounds handler-thread "
                         "memory under flood)")
    ap.add_argument("--brownout-attainment", type=float, default=0.85,
                    help="brownout ladder escalation bar: escalate "
                         "one rung when windowed interactive-class "
                         "SLO attainment drops below this (needs "
                         "--slo-ttft-ms / --slo-itl-ms to be scored)")
    ap.add_argument("--brownout-recover-attainment", type=float,
                    default=0.95,
                    help="brownout ladder recovery bar: step DOWN one "
                         "rung only once attainment is back at/above "
                         "this (must be >= --brownout-attainment — "
                         "the gap is the hysteresis band)")
    ap.add_argument("--brownout-queue-wait-ms", type=float, default=0.0,
                    help="queue-wait pressure bar for the ladder "
                         "(recent pre-admission wait p90 above it = "
                         "pressure); 0 derives 2x --slo-ttft-ms, or "
                         "2000 ms when no TTFT SLO is set")
    ap.add_argument("--brownout-dwell-s", type=float, default=2.0,
                    help="pressure must persist this long before each "
                         "one-rung escalation")
    ap.add_argument("--brownout-cooldown-s", type=float, default=10.0,
                    help="calm must persist this long before each "
                         "one-rung recovery step")
    ap.add_argument("--brownout-batch-max-new", type=int, default=64,
                    help="batch-class max_new_tokens cap applied at "
                         "brownout-1 (halves again at deeper rungs)")
    ap.add_argument("--brownout-demote-blocks", type=int, default=32,
                    help="idle KV blocks proactively demoted to the "
                         "host tier on entering brownout-1 and deeper "
                         "(no-op without --host-kv-blocks)")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="accelerator MXU peak in TFLOP/s for the "
                         "/metrics llm_mxu_utilization and "
                         "llm_host_overhead_ratio gauges (default: "
                         "the v5e bf16 peak bench.py rooflines "
                         "against); 0 disables the FLOPs-side gauges")
    ap.add_argument("--peak-hbm-gbps", type=float, default=819.0,
                    help="accelerator HBM bandwidth in GB/s for the "
                         "/metrics llm_hbm_utilization gauge "
                         "(default: the v5e peak); 0 disables it")
    ap.add_argument("--no-cost-models", action="store_true",
                    help="skip the per-program static cost models "
                         "(jit lowering cost_analysis at the live "
                         "geometry): the utilization / host-overhead "
                         "gauges go dark but first-dispatch trace "
                         "time drops — for compile-bound drills; "
                         "live serving keeps them ON (the analysis "
                         "is trace-time only, never per-dispatch)")
    ap.add_argument("--log-json", action="store_true",
                    help="structured JSON logging: one JSON object per "
                         "operational log line (event / request_id / "
                         "feature fields) instead of 'event k=v' text, "
                         "so a log pipeline joins server lines to the "
                         "/debug request timelines without regexes")
    args = ap.parse_args()
    # One formatter for every operational log line this process emits
    # (obs.StructuredLogger; --log-json flips it to JSON objects).
    # Generation OUTPUT (the completions themselves) stays on plain
    # stdout prints — it is the program's product, not its log.
    from .obs import StructuredLogger

    log = StructuredLogger(json_mode=args.log_json)
    if args.host_kv_blocks > 0 and (
        args.prefix_index != "radix" or args.no_prefix_cache
    ):
        # The tier hangs off radix-node residency; refusing loudly here
        # beats a silently inert flag (the batcher ctor tolerates the
        # combination only because the degradation layer's prefix-cache
        # quarantine must be able to rebuild with the cache off).
        raise SystemExit(
            "--host-kv-blocks requires --prefix-index radix with the "
            "prefix cache enabled (the host tier hangs off radix-node "
            "residency)"
        )
    if args.logprobs and args.http is None:
        raise SystemExit(
            "--logprobs only applies to the HTTP server (--http PORT); "
            "the stdin/--serve and one-shot modes have no logprobs output"
        )
    import os

    # The env var is checked here too: a JLT_FAULTS chaos drill that the
    # chosen mode cannot honor must refuse loudly, not run fault-free
    # while the operator believes injection was armed.
    fault_spec = args.inject_faults or os.environ.get("JLT_FAULTS")
    if fault_spec:
        if args.http is None:
            raise SystemExit(
                "--inject-faults / JLT_FAULTS only apply to the HTTP "
                "server (--http PORT) — the stdin/--serve and one-shot "
                "modes have no crash recovery, so a fault drill there "
                "would just crash the run"
            )
        # Validate the spec BEFORE the (potentially minutes-long) weight
        # load; faults.py imports no jax, so this is free.
        from .faults import FaultSpec

        try:
            FaultSpec.parse(fault_spec)
        except ValueError as e:
            raise SystemExit(f"bad fault spec: {e}")

    import jax

    from .convert.checkpoint import load_checkpoint
    from .generation import LLaMA
    from .parallel.mesh import make_mesh
    from .utils.profiling import DecodeStats, Timer

    n = len(jax.devices())
    tensor = args.tensor or n // (args.data * args.fsdp)
    # Use exactly the devices the mesh needs — a smaller-than-host mesh
    # (e.g. --tensor 2 on an 8-device host) is valid for smoke runs.
    mesh = make_mesh(
        data=args.data, fsdp=args.fsdp, tensor=tensor,
        devices=jax.devices()[: args.data * args.fsdp * tensor],
    )
    if args.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    if args.replicas > 1 and args.http is None:
        raise SystemExit(
            "--replicas > 1 needs the HTTP front-end (--http PORT): "
            "the ReplicaRouter speaks HTTP to its replicas"
        )
    if args.autoscale:
        if args.replicas < 2 or args.http is None:
            raise SystemExit(
                "--autoscale needs router mode (--replicas >= 2 with "
                "--http PORT): the FleetController scales the "
                "ReplicaRouter's fleet"
            )
        if args.replica_roles is not None:
            raise SystemExit(
                "--autoscale does not compose with --replica-roles: "
                "role disaggregation pins fleet membership (at least "
                "one replica of each role)"
            )
        if not (1 <= args.autoscale_min <= args.replicas
                <= args.autoscale_max):
            raise SystemExit(
                "--autoscale needs 1 <= --autoscale-min <= --replicas "
                "<= --autoscale-max"
            )
    if args.replica_roles is not None:
        roles = tuple(
            r.strip() for r in args.replica_roles.split(",") if r.strip()
        )
        if args.replicas < 2:
            raise SystemExit(
                "--replica-roles needs --replicas >= 2 (one prefill "
                "and one decode replica at minimum)"
            )
        if len(roles) != args.replicas:
            raise SystemExit(
                f"--replica-roles names {len(roles)} roles for "
                f"--replicas {args.replicas}; give one role per replica"
            )
        bad = sorted(set(roles) - {"prefill", "decode"})
        if bad:
            raise SystemExit(
                f"--replica-roles: unknown role(s) {bad}; valid roles "
                "are 'prefill' and 'decode'"
            )
        if not ("prefill" in roles and "decode" in roles):
            raise SystemExit(
                "--replica-roles needs at least one replica of EACH "
                "role (prefill and decode)"
            )
        if args.route != "cache-aware":
            raise SystemExit(
                "--replica-roles requires --route cache-aware (the "
                "disaggregation scheduler routes off the router's "
                "global radix index)"
            )
        args.replica_roles = roles
    serve_spec = None
    if args.serve_mesh is not None:
        if args.http is None and not args.serve:
            raise SystemExit(
                "--serve-mesh applies to the serving modes "
                "(--serve / --http PORT)"
            )
        from .parallel.serve_mesh import build_serve_mesh, parse_serve_mesh

        try:
            serve_spec = parse_serve_mesh(args.serve_mesh)
        except ValueError as e:
            raise SystemExit(str(e))
        if serve_spec.n_devices > n:
            raise SystemExit(
                f"--serve-mesh {args.serve_mesh} needs "
                f"{serve_spec.n_devices} devices, host has {n}"
            )
        # Replica 0's mesh; _serve_router slices further replicas their
        # own devices when the host has enough.
        mesh = build_serve_mesh(
            serve_spec, devices=jax.devices()[: serve_spec.n_devices]
        )

    if args.byte_tokenizer:
        from .tokenizers import ByteTokenizer

        tokenizer = ByteTokenizer()
    elif args.tokenizer is None:
        raise SystemExit("--tokenizer is required (or pass --byte-tokenizer)")
    elif args.llama2:
        from .tokenizers import LLaMA2Tokenizer

        tokenizer = LLaMA2Tokenizer(args.tokenizer)
    else:
        from .tokenizers import LLaMA3Tokenizer

        tokenizer = LLaMA3Tokenizer(args.tokenizer)

    with Timer() as load_t:
        params, config = load_checkpoint(
            args.ckpt_dir, mesh=mesh, fsdp=args.fsdp > 1
        )
    if args.attn:
        config = config.replace(attn_impl=args.attn)
    if serve_spec is not None:
        # A clear refusal at startup beats a silently unplaced mesh.
        from .parallel.serve_mesh import validate_serve_mesh

        validate_serve_mesh(config, mesh, args.slots)
    if args.quantize:
        from .ops.quant import is_quantized, quantize_params

        if not is_quantized(params):
            params = quantize_params(params, donate=True)
    log.log(
        "checkpoint_restored", ckpt_dir=args.ckpt_dir,
        mesh=str(dict(mesh.shape)), seconds=round(load_t.elapsed_s, 1),
    )

    if args.http is not None:
        _serve_http(params, config, tokenizer, mesh, args, logger=log)
        return
    if args.serve:
        _serve(params, config, tokenizer, mesh, args)
        return

    model = LLaMA(params=params, config=config, tokenizer=tokenizer, mesh=mesh)
    prompts = args.prompt or DEFAULT_PROMPTS

    with Timer() as gen_t:
        outs = model.generate_from_str(
            prompts, args.max_gen_len, args.temperature, args.top_p, args.seed
        )
    stats = DecodeStats(
        batch=len(prompts),
        prompt_len=max(len(tokenizer.encode(p, bos=True, eos=False))
                       for p in prompts),
        new_tokens=args.max_gen_len,
        prefill_s=0.0,
        decode_s=gen_t.elapsed_s,
        n_devices=n,
    )
    for p, o in zip(prompts, outs):
        print(f"\n=== {p!r}\n{o}")
    print(f"\n[{stats.summary()}] (incl. compile)")


def _chat_format_for(tokenizer):
    """The ONE 'is this a llama-3 chat tokenizer' heuristic: both the
    single-server /chat endpoint and the router's cache-aware /chat
    chain-key encoding must resolve the SAME ChatFormat, or the
    router's routing keys drift from what the replicas admit."""
    if hasattr(tokenizer, "special_tokens") and hasattr(
        tokenizer, "eot_id"
    ):
        from .tokenizers.llama3 import ChatFormat

        return ChatFormat(tokenizer)
    return None


def _load_draft(args, mesh):
    """Optional speculative-serving draft model (--draft-ckpt-dir):
    returns (draft_params, draft_config) or (None, None).  Loaded the
    same sharded way as the target; attn_impl follows the --attn
    override so both models resolve the same attention paths."""
    ckpt = getattr(args, "draft_ckpt_dir", None)
    if not ckpt:
        return None, None
    from .convert.checkpoint import load_checkpoint

    draft_params, draft_config = load_checkpoint(
        ckpt, mesh=mesh, fsdp=args.fsdp > 1
    )
    if args.attn:
        draft_config = draft_config.replace(attn_impl=args.attn)
    return draft_params, draft_config


def _serve_http(params, config, tokenizer, mesh, args, _test_hook=None,
                logger=None):
    """HTTP front-end: LLMServer over the batcher until interrupted.

    ``_test_hook(srv)``, when given, runs once the server is up and then
    the function returns instead of blocking (tests drive requests
    against the live server without a second process).
    """
    import os
    import time

    from .obs import Observability, StructuredLogger
    from .server import LLMServer
    from .serving import ContinuousBatcher

    if logger is None:
        logger = StructuredLogger(
            json_mode=getattr(args, "log_json", False)
        )
    if getattr(args, "replicas", 1) > 1:
        _serve_router(
            params, config, tokenizer, mesh, args,
            _test_hook=_test_hook, logger=logger,
        )
        return

    stops = tuple(
        int(s) for s in getattr(tokenizer, "stop_tokens", [tokenizer.eos_id])
    )
    # Fault injection (chaos runs / tests): --inject-faults wins over the
    # JLT_FAULTS env var; absent both, no injector is constructed.
    fault_spec = (
        getattr(args, "inject_faults", None) or os.environ.get("JLT_FAULTS")
    )
    injector = None
    if fault_spec:
        from .faults import FaultInjector, install_trace_hook

        injector = FaultInjector(
            fault_spec, seed=getattr(args, "fault_seed", 0)
        )
        # Arm the kernel/spec modules' trace-time hooks too (one
        # registry covers flash_kernel / paged_kernel / spec_decode),
        # so a drill can also exercise the first-compile (Mosaic-style)
        # failure mode — the batcher fires the same sites per dispatch.
        install_trace_hook(injector.fire)
        logger.log("faults_armed", spec=fault_spec)
    draft_params, draft_config = _load_draft(args, mesh)
    if getattr(args, "serve_mesh", None) and draft_config is not None:
        # main() validated the TARGET before the draft existed; an
        # explicit --serve-mesh whose tensor axis cannot divide the
        # draft's KV heads must refuse, not silently unplace.
        from .parallel.serve_mesh import validate_serve_mesh

        validate_serve_mesh(
            config, mesh, args.slots, draft_config=draft_config
        )
    # The observability sink (request timelines, dispatch spans, latency
    # histograms, SLO scoring) is constructed HERE so the CLI's SLO
    # deadlines reach it; the batcher adopts it into its captured ctor
    # kwargs, so crash-recovery/quarantine rebuilds keep one continuous
    # trace.  0/unset deadlines leave that SLO dimension always-passing.
    obs = Observability(
        slo_ttft_ms=getattr(args, "slo_ttft_ms", 0.0) or None,
        slo_itl_ms=getattr(args, "slo_itl_ms", 0.0) or None,
        peak_flops=getattr(args, "peak_tflops", 197.0) * 1e12,
        peak_bytes_per_s=getattr(args, "peak_hbm_gbps", 819.0) * 1e9,
    )
    cb = ContinuousBatcher(
        params, config, n_slots=args.slots,
        max_len=config.max_seq_len, stop_tokens=stops,
        temperature=args.temperature, top_p=args.top_p,
        seed=args.seed, mesh=mesh,
        logprobs=getattr(args, "logprobs", False),
        prefix_cache=not getattr(args, "no_prefix_cache", False),
        fault_injector=injector,
        decode_chunk=getattr(args, "decode_chunk", 8),
        draft_params=draft_params, draft_config=draft_config,
        n_draft=getattr(args, "n_draft", 4),
        spec_rounds=getattr(args, "spec_rounds", 8),
        prefill_budget=getattr(args, "prefill_budget", 512),
        prefix_index=getattr(args, "prefix_index", "radix"),
        host_kv_blocks=getattr(args, "host_kv_blocks", 0),
        obs=obs,
        cost_models=not getattr(args, "no_cost_models", False),
        prefill_kernel=getattr(args, "prefill_kernel", None),
        decode_kernel=getattr(args, "decode_kernel", None),
    )
    # Llama-3 tokenizers get the dialog endpoint for free (ChatFormat is
    # the reference's own framing; other tokenizers have no chat contract).
    chat_format = _chat_format_for(tokenizer)
    watchdog_s = getattr(args, "watchdog_s", 60.0)
    drain_timeout_s = getattr(args, "drain_timeout_s", 30.0)
    try:
        with LLMServer(
            cb, tokenizer=tokenizer, host=args.host, port=args.http,
            chat_format=chat_format,
            max_recoveries=getattr(args, "max_recoveries", 3),
            recovery_window_s=getattr(args, "recovery_window_s", 60.0),
            watchdog_deadline_s=watchdog_s if watchdog_s > 0 else None,
            quarantine_threshold=getattr(args, "quarantine_threshold", 3),
            quarantine_window_s=getattr(args, "quarantine_window_s", 60.0),
            quarantine_cooldown_s=getattr(
                args, "quarantine_cooldown_s", 30.0
            ),
            drain_timeout_s=drain_timeout_s,
            logger=logger,
            max_queue=getattr(args, "max_queue", 256),
            priority_classes=(
                getattr(args, "priority_classes", "on") == "on"
            ),
            brownout_enter_attainment=getattr(
                args, "brownout_attainment", 0.85
            ),
            brownout_exit_attainment=getattr(
                args, "brownout_recover_attainment", 0.95
            ),
            brownout_queue_wait_ms=(
                getattr(args, "brownout_queue_wait_ms", 0.0) or None
            ),
            brownout_dwell_s=getattr(args, "brownout_dwell_s", 2.0),
            brownout_cooldown_s=getattr(
                args, "brownout_cooldown_s", 10.0
            ),
            brownout_batch_max_new=getattr(
                args, "brownout_batch_max_new", 64
            ),
            brownout_demote_blocks=getattr(
                args, "brownout_demote_blocks", 32
            ),
        ) as srv:
            endpoints = "POST /generate" + (
                ", /chat" if chat_format is not None else ""
            )
            logger.log(
                "serving", address=srv.address,
                endpoints=(
                    f"{endpoints}, GET /metrics, /healthz, /debug/*"
                ),
            )
            if _test_hook is not None:
                _test_hook(srv)
                return
            # Drain-on-signal: SIGTERM (orchestrator shutdown) and the
            # first Ctrl-C flip the server into drain mode — in-flight
            # requests finish, new POSTs 503 with Retry-After, bounded
            # by --drain-timeout-s.  The handler only flips a plain
            # flag (a dict-slot store is async-signal-safe; calling
            # Event.set()/begin_drain() from the handler could deadlock
            # on the Event's non-reentrant lock if the signal lands
            # inside the main thread's own wait) and restores the
            # default SIGINT disposition so a SECOND Ctrl-C hard-stops;
            # the polling loop below does the actual drain.
            import signal

            state = {"signaled": False}

            def _on_signal(signum, frame):
                state["signaled"] = True
                signal.signal(signal.SIGINT, signal.default_int_handler)

            previous = []
            try:
                for sig in (signal.SIGTERM, signal.SIGINT):
                    previous.append((sig, signal.signal(sig, _on_signal)))
            except ValueError:
                previous = []  # not the main thread; no signal wiring
            try:
                while not state["signaled"]:
                    time.sleep(0.2)
                srv.begin_drain()
                logger.log(
                    "drain_begin",
                    "in-flight requests finish, new requests 503",
                    timeout_s=drain_timeout_s,
                )
                if srv.wait_drained(drain_timeout_s + 10):
                    logger.log("drained", "shutting down")
                else:
                    logger.log("drain_timeout", "shutting down")
            except KeyboardInterrupt:
                srv.begin_drain(timeout_s=0.0)
                logger.log("hard_shutdown", "second interrupt")
            finally:
                for sig, old in previous:
                    try:
                        signal.signal(sig, old)
                    except (ValueError, TypeError):
                        pass
    finally:
        if injector is not None:
            # The trace-time hook is a module global: clear it so an
            # embedding process (or the test suite) does not keep firing
            # a dead drill's injector on later traces.
            install_trace_hook(None)


def _serve_router(params, config, tokenizer, mesh, args,
                  _test_hook=None, logger=None) -> None:
    """``--replicas N`` mode: N independent batcher+server replicas —
    each owning its own device slice when the host has
    ``N x mesh_devices`` devices, sharing replica 0's mesh otherwise —
    behind one :class:`~jax_llama_tpu.router.ReplicaRouter` speaking
    the standard protocol on the ``--http`` port.

    ``_test_hook(router, servers)``, when given, runs once everything
    is up and then the function returns instead of blocking."""
    import os
    import signal
    import time

    import jax

    from .obs import Observability, StructuredLogger
    from .parallel.partition import shard_params
    from .parallel.serve_mesh import build_serve_mesh, parse_serve_mesh
    from .router import ReplicaRouter
    from .server import LLMServer
    from .serving import ContinuousBatcher

    if logger is None:
        logger = StructuredLogger(
            json_mode=getattr(args, "log_json", False)
        )
    stops = tuple(
        int(s) for s in getattr(tokenizer, "stop_tokens", [tokenizer.eos_id])
    )
    fault_spec = (
        getattr(args, "inject_faults", None) or os.environ.get("JLT_FAULTS")
    )
    injector = None
    if fault_spec:
        from .faults import FaultInjector, install_trace_hook

        # ONE injector serves the router site and every replica's
        # batcher sites, so site@N counters index process dispatches.
        injector = FaultInjector(
            fault_spec, seed=getattr(args, "fault_seed", 0)
        )
        install_trace_hook(injector.fire)
        logger.log("faults_armed", spec=fault_spec)
    draft_params, draft_config = _load_draft(args, mesh)

    # Per-replica meshes: slice fresh devices per replica when the host
    # has enough, otherwise every replica shares replica 0's mesh (the
    # CPU dev-box case — still N independent pools/queues, just
    # time-sharing the devices).
    spec = (
        parse_serve_mesh(args.serve_mesh)
        if getattr(args, "serve_mesh", None) else None
    )
    if spec is not None:
        # Startup-time refusal with the DRAFT model in hand too — the
        # main() check ran before the draft was loaded, and a draft
        # whose KV heads the tensor axis cannot divide would otherwise
        # silently fall back to unplaced.
        from .parallel.serve_mesh import validate_serve_mesh

        validate_serve_mesh(
            config, mesh, args.slots, draft_config=draft_config
        )
    devs = jax.devices()
    per = spec.n_devices if spec is not None else 0
    _geom_cache = {}

    def _geometry(i):
        """Replica ``i``'s (mesh, params, draft_params): a fresh
        device slice while the host still has one for index i,
        replica 0's mesh (time-shared) after — the same rule for seed
        replicas and autoscale-grown ones."""
        if i in _geom_cache:
            return _geom_cache[i]
        if spec is not None and len(devs) >= (i + 1) * per:
            m = build_serve_mesh(spec, devices=devs[i * per:(i + 1) * per])
            p = params if i == 0 else shard_params(params, m, config)
            # The draft rides the same per-replica device slice — a
            # draft committed to replica 0's devices would either fail
            # jit's device check or pay a cross-device transfer every
            # speculative dispatch on the other replicas.
            d = (
                draft_params if draft_params is None or i == 0
                else shard_params(draft_params, m, draft_config)
            )
        else:
            m, p, d = mesh, params, draft_params
        _geom_cache[i] = (m, p, d)
        return m, p, d

    if spec is not None and len(devs) < args.replicas * per:
        logger.log(
            "serve_mesh_shared",
            f"host has {len(devs)} devices < replicas x mesh "
            f"({args.replicas} x {per}); replicas time-share one mesh",
        )

    def make_replica(i):
        """Build + start replica ``i`` (batcher + server).  Doubles as
        the FleetController's ``replica_factory`` under --autoscale:
        a scale-up gets the next index's geometry and a distinct
        sampling seed, everything else identical to the seed fleet."""
        m, p, d = _geometry(i)
        obs = Observability(
            slo_ttft_ms=getattr(args, "slo_ttft_ms", 0.0) or None,
            slo_itl_ms=getattr(args, "slo_itl_ms", 0.0) or None,
            peak_flops=getattr(args, "peak_tflops", 197.0) * 1e12,
            peak_bytes_per_s=(
                getattr(args, "peak_hbm_gbps", 819.0) * 1e9
            ),
        )
        cb = ContinuousBatcher(
            p, config, n_slots=args.slots,
            max_len=config.max_seq_len, stop_tokens=stops,
            temperature=args.temperature, top_p=args.top_p,
            seed=args.seed + i, mesh=m,
            logprobs=getattr(args, "logprobs", False),
            prefix_cache=not getattr(args, "no_prefix_cache", False),
            fault_injector=injector,
            decode_chunk=getattr(args, "decode_chunk", 8),
            draft_params=d, draft_config=draft_config,
            n_draft=getattr(args, "n_draft", 4),
            spec_rounds=getattr(args, "spec_rounds", 8),
            prefill_budget=getattr(args, "prefill_budget", 512),
            prefix_index=getattr(args, "prefix_index", "radix"),
            host_kv_blocks=getattr(args, "host_kv_blocks", 0),
            obs=obs,
            cost_models=not getattr(args, "no_cost_models", False),
            prefill_kernel=getattr(args, "prefill_kernel", None),
            decode_kernel=getattr(args, "decode_kernel", None),
        )
        srv = LLMServer(
            cb, tokenizer=tokenizer, host=args.host, port=0,
            replica_id=i,
            max_recoveries=getattr(args, "max_recoveries", 3),
            recovery_window_s=getattr(args, "recovery_window_s", 60.0),
            watchdog_deadline_s=(
                getattr(args, "watchdog_s", 60.0) or None
            ),
            drain_timeout_s=getattr(args, "drain_timeout_s", 30.0),
            logger=logger,
            max_queue=getattr(args, "max_queue", 256),
            priority_classes=(
                getattr(args, "priority_classes", "on") == "on"
            ),
        )
        return srv.start()

    servers = []
    controller = None
    try:
        for i in range(args.replicas):
            servers.append(make_replica(i))
        # Cache-aware routing needs the router to speak the replicas'
        # chain-key schema: the tokenizer + chat format mirror each
        # replica's own /generate- and /chat-encoding, block_size is
        # the chain-key granularity (identical across replicas — same
        # config), and --replica-roles turns on the prefill/decode
        # disaggregation scheduler.
        router = ReplicaRouter(
            servers, host=args.host, port=args.http,
            policy=getattr(args, "route", "least-loaded"),
            fault_injector=injector, logger=logger,
            tokenizer=tokenizer,
            block_size=servers[0].batcher.block_size,
            chat_format=_chat_format_for(tokenizer),
            roles=getattr(args, "replica_roles", None),
            canary_interval_s=getattr(args, "canary_interval_s", 10.0),
        ).start()
        if getattr(args, "autoscale", False):
            from .router import FleetController

            controller = FleetController(
                router,
                replica_factory=make_replica,
                min_replicas=getattr(args, "autoscale_min", 1),
                max_replicas=getattr(args, "autoscale_max", 8),
                interval_s=getattr(args, "autoscale_interval_s", 5.0),
                drain_timeout_s=getattr(args, "drain_timeout_s", 30.0),
            )
            logger.log(
                "autoscale_armed",
                min=getattr(args, "autoscale_min", 1),
                max=getattr(args, "autoscale_max", 8),
                interval_s=getattr(args, "autoscale_interval_s", 5.0),
            )
        try:
            logger.log(
                "serving_replicas", address=router.address,
                replicas=args.replicas,
                policy=getattr(args, "route", "least-loaded"),
                meshes=[
                    str(dict(_geometry(i)[0].shape))
                    if _geometry(i)[0] is not None else None
                    for i in range(args.replicas)
                ],
            )
            if _test_hook is not None:
                _test_hook(router, servers)
                return
            state = {"signaled": False}

            def _on_signal(signum, frame):
                state["signaled"] = True
                signal.signal(signal.SIGINT, signal.default_int_handler)

            previous = []
            try:
                for sig in (signal.SIGTERM, signal.SIGINT):
                    previous.append((sig, signal.signal(sig, _on_signal)))
            except ValueError:
                previous = []
            try:
                while not state["signaled"]:
                    time.sleep(0.2)
                drain_s = getattr(args, "drain_timeout_s", 30.0)
                logger.log("drain_begin", "all replicas draining",
                           timeout_s=drain_s)
                for srv in servers:
                    srv.begin_drain()
                for srv in servers:
                    srv.wait_drained(drain_s + 10)
                logger.log("drained", "shutting down")
            except KeyboardInterrupt:
                for srv in servers:
                    srv.begin_drain(timeout_s=0.0)
                logger.log("hard_shutdown", "second interrupt")
            finally:
                for sig, old in previous:
                    try:
                        signal.signal(sig, old)
                    except (ValueError, TypeError):
                        pass
        finally:
            if controller is not None:
                controller.close(stop_owned=True)
            router.stop()
    finally:
        for srv in servers:
            srv.stop()
        if injector is not None:
            from .faults import install_trace_hook

            install_trace_hook(None)


def _serve(params, config, tokenizer, mesh, args) -> None:
    """Continuous-batching loop over stdin prompts (one per line)."""
    import sys

    from .serving import ContinuousBatcher

    stops = tuple(
        int(s) for s in getattr(tokenizer, "stop_tokens", [tokenizer.eos_id])
    )
    draft_params, draft_config = _load_draft(args, mesh)
    cb = ContinuousBatcher(
        params, config, n_slots=args.slots,
        max_len=config.max_seq_len, stop_tokens=stops,
        temperature=args.temperature, top_p=args.top_p,
        seed=args.seed, mesh=mesh,
        prefix_cache=not getattr(args, "no_prefix_cache", False),
        decode_chunk=getattr(args, "decode_chunk", 8),
        draft_params=draft_params, draft_config=draft_config,
        n_draft=getattr(args, "n_draft", 4),
        spec_rounds=getattr(args, "spec_rounds", 8),
        prefill_budget=getattr(args, "prefill_budget", 512),
        prefix_index=getattr(args, "prefix_index", "radix"),
        host_kv_blocks=getattr(args, "host_kv_blocks", 0),
    )
    rid_prompt: dict = {}
    emitted: dict = {}
    lines = [ln.rstrip("\n") for ln in sys.stdin if ln.strip()]
    for line in lines:
        try:
            rid = cb.submit(
                tokenizer.encode(line, bos=True, eos=False),
                max_new_tokens=args.max_gen_len,
            )
        except ValueError as e:
            # One over-long prompt must not take down the whole serve loop.
            print(f"\n=== {line!r}\n[rejected: {e}]", flush=True)
            continue
        rid_prompt[rid] = line
    while cb.pending():
        for rid, tok, done in cb.step():
            emitted.setdefault(rid, []).append(tok)
            if done:
                toks = emitted[rid]
                # The batcher finishes a request at its first stop token,
                # so a stop id can only be the terminal element; strip just
                # that one rather than filtering stop ids everywhere.
                if toks and toks[-1] in stops:
                    toks = toks[:-1]
                print(f"\n=== {rid_prompt[rid]!r}\n{tokenizer.decode(toks)}",
                      flush=True)
    print(f"\nserved {len(rid_prompt)} request(s) on {args.slots} slot(s)")


if __name__ == "__main__":
    main()
