"""High-level generation API: tokenize → pad → generate → detokenize.

Surface parity with the reference ``LLaMA`` wrapper (``/root/reference/
jax_llama/generation.py:15-79``): a struct bundling params + config +
tokenizer + mesh, with ``generate`` (token-level) and ``generate_from_str``
(string-level).  Differences by design:

  * The decode loop is this framework's own jitted engine
    (jax_llama_tpu.engine), not HF's mixin.
  * Left-padding uses the tokenizer's dedicated ``pad_id`` and an explicit
    boolean mask — the reference pads with *eos* and derives the mask as
    ``tokens != eos`` (generation.py:55-60), which mis-masks genuine eos in
    a prompt; the quirk is fixed, not replicated (flagged in SURVEY.md §2
    as a defect).
  * Decoding strips padding and truncates at the first stop token, like
    reference generation.py:69-78.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import LLaMAConfig
from .engine import GenerationConfig, generate as engine_generate, next_pow2


@dataclasses.dataclass
class LLaMA:
    """Bundles everything needed to serve a model (reference
    generation.py:15-19 bundles the same four things)."""

    params: Any
    config: LLaMAConfig
    tokenizer: Any
    mesh: Optional[Any] = None

    def _pad_id(self) -> int:
        pad = getattr(self.tokenizer, "pad_id", -1)
        if pad is None or pad < 0:
            pad = self.tokenizer.eos_id
        return pad

    def _stop_tokens(self) -> tuple:
        stops = getattr(self.tokenizer, "stop_tokens", None)
        if stops is None:
            stops = [self.tokenizer.eos_id]
        return tuple(int(s) for s in stops)

    def generate(
        self,
        tokens: jnp.ndarray,
        attn_mask: jnp.ndarray,
        max_gen_len: int,
        temperature: float = 0.8,
        top_p: float = 0.95,
        seed: int = 0,
    ) -> np.ndarray:
        """Token-level generation on left-padded [B, P] int32 input."""
        gen_config = GenerationConfig(
            max_new_tokens=max_gen_len,
            temperature=temperature,
            top_p=top_p,
            stop_tokens=self._stop_tokens(),
            pad_id=self._pad_id(),
        )
        rng = jax.random.PRNGKey(seed)
        out = engine_generate(
            self.params,
            jnp.asarray(tokens, dtype=jnp.int32),
            jnp.asarray(attn_mask, dtype=bool),
            rng,
            config=self.config,
            gen_config=gen_config,
            mesh=self.mesh,
        )
        return np.asarray(out)

    def generate_from_str(
        self,
        prompts: Sequence[str],
        max_gen_len: int,
        temperature: float = 0.8,
        top_p: float = 0.95,
        seed: int = 0,
    ) -> List[str]:
        """Encode (with BOS), left-pad, generate, decode (parity surface:
        reference generation.py:47-78)."""
        if not prompts:
            raise ValueError("prompts must be a non-empty sequence of strings")
        encoded = [
            self.tokenizer.encode(p, bos=True, eos=False) for p in prompts
        ]
        # Bucket the padded length to the next power of two so serving
        # varied prompt lengths triggers O(log max_len) compilations, not
        # one per distinct length.
        max_len = next_pow2(max(len(e) for e in encoded))
        pad = self._pad_id()
        B = len(encoded)
        tokens = np.full((B, max_len), pad, dtype=np.int32)
        mask = np.zeros((B, max_len), dtype=bool)
        for i, e in enumerate(encoded):
            tokens[i, max_len - len(e):] = e
            mask[i, max_len - len(e):] = True

        out = self.generate(tokens, mask, max_gen_len, temperature, top_p, seed)

        stops = set(self._stop_tokens())
        results = []
        for i in range(B):
            # Generated region starts right after the padded prompt.
            gen = out[i, max_len:]
            ids: List[int] = []
            for t in gen.tolist():
                if t in stops or t == pad:
                    break
                ids.append(t)
            results.append(self.tokenizer.decode(ids))
        return results
