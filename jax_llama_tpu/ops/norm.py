"""RMSNorm with an fp32 accumulation island.

Capability parity with the reference RMSNorm (``/root/reference/jax_llama/
model.py:28-48``): y = x * rsqrt(mean(x^2) + eps) * scale.  TPU numerics
policy: the mean/rsqrt runs in float32 regardless of the activation dtype
(bf16 squaring loses too much precision), and the result is cast back to the
input dtype after the scale multiply.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Root-mean-square layer norm over the last axis.

    Args:
      x: [..., dim] activations, any float dtype.
      scale: [dim] learned gain (stored dtype preserved).
      eps: variance epsilon.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(ms + eps)
    out = normed * scale.astype(jnp.float32)
    return out.astype(orig_dtype)
