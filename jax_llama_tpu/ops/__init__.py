from .norm import rms_norm
from .rope import rope_table, apply_rope
from .attention import sdpa, repeat_kv, attention_bias, NEG_INF
from .flash_attention import flash_attention, flash_attention_quantized
from .sampling import sample, greedy, top_p_filter, top_k_filter
from .quant import QuantizedTensor, quantize, quantize_params, is_quantized

__all__ = [
    "flash_attention",
    "flash_attention_quantized",
    "QuantizedTensor",
    "quantize",
    "quantize_params",
    "is_quantized",
    "rms_norm",
    "rope_table",
    "apply_rope",
    "sdpa",
    "repeat_kv",
    "attention_bias",
    "NEG_INF",
    "sample",
    "greedy",
    "top_p_filter",
    "top_k_filter",
]
