"""Rotary position embeddings, Meta-interleaved pairing, real-valued math.

The reference applies RoPE in complex arithmetic over interleaved pairs
``(x[2i], x[2i+1])`` (``/root/reference/jax_llama/model.py:50-92``).  Complex
dtypes are poison for the TPU vector unit, so we use the algebraically
identical real-valued form:

    out[2i]   = x[2i]*cos(t·w_i) - x[2i+1]*sin(t·w_i)
    out[2i+1] = x[2i]*sin(t·w_i) + x[2i+1]*cos(t·w_i)

NOTE this is the *interleaved* (Meta checkpoint) pairing, not the HF
half-split ("rotate_half") pairing — weight conversion from Meta checkpoints
needs no Q/K permutation with this convention.  Tables are precomputed in
float32 and rotation runs in float32 regardless of activation dtype.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


def llama3_scale_inv_freq(
    inv_freq: np.ndarray,
    scale_factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_len: int = 8192,
) -> np.ndarray:
    """Llama-3.1 frequency scaling for context extension (the published
    ``use_scaled_rope`` rule): high-frequency components (short wavelengths)
    are kept, low-frequency components are divided by ``scale_factor``, and
    the band between is linearly interpolated in wavelength space."""
    wavelen = 2.0 * np.pi / inv_freq
    low_wl = original_max_len / low_freq_factor
    high_wl = original_max_len / high_freq_factor
    smooth = (original_max_len / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    mid = ((1.0 - smooth) / scale_factor + smooth) * inv_freq
    out = np.where(wavelen > low_wl, inv_freq / scale_factor, inv_freq)
    in_band = (wavelen <= low_wl) & (wavelen >= high_wl)
    return np.where(in_band, mid, out)


def rope_table(
    head_dim: int,
    max_positions: int,
    theta: float = 10000.0,
    use_scaled_rope: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Precompute (cos, sin) tables, each [max_positions, head_dim // 2], fp32.

    Computed and returned on host in **numpy** (like the reference's
    host-side precompute, model.py:156-161): bit-stable across backends, and
    safe to memoize — a cached jnp array created inside a jit trace would
    leak a tracer into later traces; a numpy array is a fresh constant in
    every trace.
    """
    assert head_dim % 2 == 0
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if use_scaled_rope:
        inv_freq = llama3_scale_inv_freq(inv_freq)
    t = np.arange(max_positions, dtype=np.float64)
    angles = np.outer(t, inv_freq)  # [P, head_dim/2]
    return (
        np.cos(angles).astype(np.float32),
        np.sin(angles).astype(np.float32),
    )


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate q or k by position-dependent angles.

    Args:
      x: [batch, seq, heads, head_dim].
      cos, sin: [max_positions, head_dim // 2] fp32 tables from `rope_table`.
      positions: [batch, seq] int32 absolute position ids.
    Returns:
      Rotated tensor, same shape/dtype as x.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    x_even = xf[..., 0::2]  # [B, S, H, D/2]
    x_odd = xf[..., 1::2]
    c = jnp.take(cos, positions, axis=0)[:, :, None, :]  # [B, S, 1, D/2]
    s = jnp.take(sin, positions, axis=0)[:, :, None, :]
    out_even = x_even * c - x_odd * s
    out_odd = x_even * s + x_odd * c
    # Re-interleave: stack on a trailing axis then flatten the last two.
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape)
    return out.astype(orig_dtype)
