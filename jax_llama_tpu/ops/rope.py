"""Rotary position embeddings — half-split (rotate-half) runtime layout,
numerically identical to Meta's interleaved complex form.

The reference applies RoPE in complex arithmetic over interleaved pairs
``(x[2i], x[2i+1])`` (``/root/reference/jax_llama/model.py:50-92``).  Complex
dtypes are poison for the TPU vector unit, and the *interleaved* real-valued
form is nearly as bad: the strided even/odd slices and the re-interleave at
the end each lower to a lane-shuffling relayout copy (xplane-measured ~3µs
per decode layer at 1B scale).  So the runtime uses the HF-style half-split
pairing — pair i is ``(x[i], x[i + hd/2])``:

    out[i]        = x[i]*cos(t·w_i) - x[i+hd/2]*sin(t·w_i)
    out[i+hd/2]   = x[i]*sin(t·w_i) + x[i+hd/2]*cos(t·w_i)

i.e. contiguous half-lane slices, no shuffles.  Equivalence with the Meta
convention is exact — not approximate — because the q/k projection weights
are stored with their head_dim axis PERMUTED even-first at load time
(``models.llama.fuse_qkv``; the converter applies the same permutation):
feature i of the runtime layout is Meta feature 2i, feature i + hd/2 is
Meta feature 2i+1, so the half-split rotation of the permuted vector IS the
interleaved rotation of the original, and attention scores are invariant
because q and k share the permutation.  ``models.llama.split_qkv`` inverts
it, which is what the parity tests check token-for-token.

Tables are precomputed in float32 and rotation runs in float32 regardless
of activation dtype.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


def llama3_scale_inv_freq(
    inv_freq: np.ndarray,
    scale_factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_len: int = 8192,
) -> np.ndarray:
    """Llama-3.1 frequency scaling for context extension (the published
    ``use_scaled_rope`` rule): high-frequency components (short wavelengths)
    are kept, low-frequency components are divided by ``scale_factor``, and
    the band between is linearly interpolated in wavelength space."""
    wavelen = 2.0 * np.pi / inv_freq
    low_wl = original_max_len / low_freq_factor
    high_wl = original_max_len / high_freq_factor
    smooth = (original_max_len / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    mid = ((1.0 - smooth) / scale_factor + smooth) * inv_freq
    out = np.where(wavelen > low_wl, inv_freq / scale_factor, inv_freq)
    in_band = (wavelen <= low_wl) & (wavelen >= high_wl)
    return np.where(in_band, mid, out)


def rope_table(
    head_dim: int,
    max_positions: int,
    theta: float = 10000.0,
    use_scaled_rope: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Precompute (cos, sin) tables, each [max_positions, head_dim // 2], fp32.

    Computed and returned on host in **numpy** (like the reference's
    host-side precompute, model.py:156-161): bit-stable across backends, and
    safe to memoize — a cached jnp array created inside a jit trace would
    leak a tracer into later traces; a numpy array is a fresh constant in
    every trace.
    """
    assert head_dim % 2 == 0
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if use_scaled_rope:
        inv_freq = llama3_scale_inv_freq(inv_freq)
    t = np.arange(max_positions, dtype=np.float64)
    angles = np.outer(t, inv_freq)  # [P, head_dim/2]
    return (
        np.cos(angles).astype(np.float32),
        np.sin(angles).astype(np.float32),
    )


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate q or k by position-dependent angles.

    Args:
      x: [batch, seq, heads, head_dim] in the half-split feature layout
        (see module docstring — projections are stored pre-permuted).
      cos, sin: [max_positions, head_dim // 2] fp32 tables from `rope_table`.
      positions: [batch, seq] int32 absolute position ids.
    Returns:
      Rotated tensor, same shape/dtype as x.
    """
    orig_dtype = x.dtype
    d2 = x.shape[-1] // 2
    # Slice the halves BEFORE the fp32 cast (elementwise-identical to
    # casting first, so numerics are bit-exact): a whole-tensor
    # x.astype(f32) materializes an fp32 copy of q that XLA then layout-
    # copies across the fused-QKV -> attention seam — ~11.6 ms per 16k
    # prefill (xplane).  Sliced converts fuse straight into the rotation
    # multiplies and the seam relayout happens on bf16 (or not at all).
    x1 = x[..., :d2].astype(jnp.float32)  # [B, S, H, D/2] — lane halves
    x2 = x[..., d2:].astype(jnp.float32)
    c = jnp.take(cos, positions, axis=0)[:, :, None, :]  # [B, S, 1, D/2]
    s = jnp.take(sin, positions, axis=0)[:, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(orig_dtype)
