"""Grouped-query scaled-dot-product attention — XLA reference path.

Capability parity with the reference attention core (``/root/reference/
jax_llama/model.py:94-300``): GQA with KV-head replication *after* the cache
(the cache stays small, replication is per-step), causal + padding masking as
an additive fp32 bias, fp32 softmax.

TPU-first differences from the reference:
  * No materialized [1,1,S,S] causal-mask buffer (reference model.py:154) —
    masks are computed from position indices on the fly, so memory is
    O(T·S) per block at most, and the Pallas flash path (ops/flash_attention)
    never materializes scores at all.
  * einsum contractions keep [B, T, H, D] layout with explicit
    `preferred_element_type=float32` so the MXU accumulates in fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


def dropout(rng: jax.Array, x: jnp.ndarray, rate: float) -> jnp.ndarray:
    """Inverted dropout (expectation-preserving), shared by the attention
    probabilities path and the model's embedding/residual sites."""
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Broadcast KV heads to match query heads for GQA.

    x: [B, S, KVH, D] -> [B, S, KVH * n_rep, D].
    """
    if n_rep == 1:
        return x
    b, s, kvh, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, kvh, n_rep, d))
    return x.reshape(b, s, kvh * n_rep, d)


def attention_bias(
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_valid: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Additive fp32 attention bias combining causality and padding.

    Args:
      q_positions: [B, T] absolute positions of the query tokens.
      kv_positions: [B, S] absolute positions of the key/value slots.
      kv_valid: optional [B, S] bool — False for padding / unwritten cache
        slots.
    Returns:
      [B, 1, T, S] bias, 0 where attendable, finfo.min where masked.
    """
    allowed = kv_positions[:, None, :] <= q_positions[:, :, None]  # [B, T, S]
    if kv_valid is not None:
        allowed = jnp.logical_and(allowed, kv_valid[:, None, :])
    bias = jnp.where(allowed, 0.0, NEG_INF).astype(jnp.float32)
    return bias[:, None, :, :]


def sdpa_cached(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    bias_cache: jnp.ndarray,
    bias_new: jnp.ndarray,
    softmax_dtype: jnp.dtype = jnp.float32,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    return_weights: bool = False,
):
    """Append-free cached attention: softmax over the (immutable) cache and
    the step's new KV jointly, concatenated at the *scores* level.

    Equivalent to writing the new KV into the cache first and attending the
    whole buffer, but the cache is never mutated inside the layer stack —
    so the decode engine can apply ONE in-place dynamic-update-slice per
    step after the scan instead of rewriting the cache per layer, which
    costs a full-cache double-buffer copy every step inside lax.scan/while.

    Args:
      q: [B, T, H, D].
      k_cache, v_cache: [B, S, KVH, D] — previously written slots only
        (unwritten slots must be masked by ``bias_cache``); int8 when
        ``k_scale``/``v_scale`` are given.
      k_new, v_new: [B, T, KVH, D] — this step's projections.
      bias_cache: [B, 1, T, S] additive bias over the cache slots.
      bias_new: [B, 1, T, T] additive bias over the new tokens
        (within-step causality + padding).
      k_scale, v_scale: optional [B, S, KVH] fp32 dequant scales for an
        int8 cache.  Scales are constant along D, so they commute with
        both contractions: QK scores are rescaled after the dot, and
        v_scale folds into the softmax weights before the PV dot — the
        int8 payload goes straight into the MXU, never dequantized in HBM.
      return_weights: also return the post-softmax probabilities
        [B, H, T, S + T] (columns: cache slots then the step's new
        tokens; pre-v_scale-fold) — the eval/interp surface, parity with
        the reference's ``output_attentions`` (model.py:299).
    Returns:
      [B, T, H, D] in q.dtype; ``(out, weights)`` with return_weights.
    """
    b, t, h, d = q.shape
    kvh = k_cache.shape[2]
    g = h // kvh
    qg = q.reshape(b, t, kvh, g, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    kc = k_cache if k_scale is None else k_cache.astype(q.dtype)
    s1 = jnp.einsum(
        "btkgd,bskd->bkgts", qg, kc, preferred_element_type=jnp.float32
    ) * scale
    if k_scale is not None:
        s1 = s1 * jnp.transpose(k_scale, (0, 2, 1))[:, :, None, None, :]
    s1 = s1 + bias_cache[:, :, None]
    s2 = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k_new, preferred_element_type=jnp.float32
    ) * scale + bias_new[:, :, None]
    s = jnp.concatenate([s1, s2], axis=-1).astype(softmax_dtype)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    w1, w2 = w[..., : s1.shape[-1]], w[..., s1.shape[-1]:]
    vc = v_cache
    if v_scale is not None:
        # Fold the dequant scale into the (tiny) weights, not the cache.
        w1 = (
            w1.astype(jnp.float32)
            * jnp.transpose(v_scale, (0, 2, 1))[:, :, None, None, :]
        ).astype(q.dtype)
        vc = v_cache.astype(q.dtype)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", w1, vc, preferred_element_type=jnp.float32
    ) + jnp.einsum(
        "bkgts,bskd->btkgd", w2, v_new, preferred_element_type=jnp.float32
    )
    out = out.reshape(b, t, h, d).astype(q.dtype)
    if return_weights:
        return out, w.reshape(b, h, t, w.shape[-1])
    return out


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    softmax_dtype: jnp.dtype = jnp.float32,
    dropout_rng: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    return_weights: bool = False,
):
    """Scaled dot-product attention with GQA.

    Args:
      q: [B, T, H, D].
      k, v: [B, S, KVH, D] with H % KVH == 0.
      bias: optional [B, 1, T, S] additive bias (fp32).
      dropout_rng, dropout_rate: attention-probability dropout (training
        only; parity with the reference's attn_pdrop, model.py:276-288).
        Inverted scaling keeps the expectation unchanged.
      return_weights: also return the post-softmax (pre-dropout)
        probabilities [B, H, T, S] — the eval/interp surface, parity
        with the reference's ``output_attentions`` (model.py:299).
    Returns:
      [B, T, H, D] in q.dtype; ``(out, weights)`` with return_weights.
    """
    b, t, h, d = q.shape
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    g = h // kvh

    # Grouped einsum instead of repeat_kv(k/v): a materialized KV broadcast
    # would cost g× the cache's HBM traffic per step (and XLA:TPU was
    # observed to materialize it in fp32 — ~4× again).  Folding the group
    # dim into the contraction keeps K/V at their stored size and dtype;
    # only the (tiny) scores/weights carry the replication.
    qg = q.reshape(b, t, kvh, g, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        scores = scores + bias[:, :, None]  # [B,1,T,S] -> [B,1,1,T,S]
    scores = scores.astype(softmax_dtype)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    probs = weights
    if dropout_rng is not None and dropout_rate > 0.0:
        weights = dropout(dropout_rng, weights, dropout_rate)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", weights, v, preferred_element_type=jnp.float32
    )
    out = out.reshape(b, t, h, d).astype(q.dtype)
    if return_weights:
        return out, probs.reshape(b, h, t, probs.shape[-1])
    return out
