"""Pallas TPU paged-attention decode kernel — walks the block table
in-kernel.

The serving pool stores KV in fixed-size physical blocks
(``serving.BlockPool``); before this kernel, every decode step gathered
each row's blocks into a virtually-contiguous cache view and ran
``sdpa_cached`` over it — the pool bytes moved three times per step
(gather read, gather write, attention read).  Here the kernel's index
maps chase the block table directly via scalar prefetch, so the pool is
read ONCE and nothing contiguous is ever materialized (the vLLM
paged-attention idea, executed the Pallas way: the table lookup lives in
the BlockSpec index_map, the DMA pipeline does the pointer-chasing).

Layout contract: the pool is [KVH, NB, BLK, hd] per layer — KV-head
major, so a block's tile is a clean ``(KVH, BLK, hd)`` VMEM page.
Grid is ``(B, MB)`` with the per-row block sweep innermost; ONE grid
cell covers all KV heads of a block via a statically-unrolled in-kernel
loop (a finer (B, KVH, MB) grid was measured SLOWER than the gathered
view it replaces — per-cell overhead beat the bandwidth saving).
Online softmax state lives in VMEM scratch across the sweep, exactly
like ``ops.flash_attention``.  GQA: the ``group`` query heads of each
KV head ride the sublane axis of that head's q rows (padded to 8), so
decode reads each KV block once — never per query head.

The kernel attends the POOL only and emits a normalized output plus the
row logsumexp; the caller merges the current step's own K/V (one slot,
always attendable) at the scores level — the same two-source softmax
split as ``ops.attention.sdpa_cached``, so the pool stays immutable
through the layer scan and the decode step applies one scatter per step.

Scan compatibility: everything dynamic the kernel consumes — the block
table, per-row query positions, the derived live-block grid bounds, the
layer index, and the pool planes themselves — enters as traced operands
(scalar-prefetch or BlockSpec-mapped), so the whole op nests inside
``lax.scan`` loops without re-tracing: the model's layer scan selects
planes via ``layer``, and serving's fused decode chunk
(``serving._paged_decode_chunk``) additionally scans K decode
iterations around the layer scan, re-deriving positions/bounds per
iteration on device.  Under a mesh the shard_map wrapper nests inside
those scans the same way.

Fused prefill-decode scheduling (``serving._fused_chunk``) runs this
kernel's decode scan WHILE an admission's prompt is mid-prefill in the
same dispatch: the prefilling row rides the decode grid masked (its
query position is -1 until its last prompt chunk lands, so it attends
nothing and its write-back resolves to the sentinel block and drops) —
the standard idle-row contract, no new kernel case.  Its partially
written blocks are safe for the OTHER rows by construction: the table
walk only visits each row's own blocks.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (
    MASK_VALUE,
    _CompilerParams,
    _LANES,
    _SUBLANES,
    _resolve_interpret,
)

def _maybe_fault() -> None:
    """Chaos-drill hook: fires faults.py's trace-time registry (site
    "paged_kernel") — the paged twin of ops.flash_attention's hook."""
    from ..faults import fire_trace

    fire_trace("paged_kernel")


def _paged_kernel(
    tbl_ref,    # [B * MB] int32 scalar-prefetch: physical block id (NB = dead)
    qpos_ref,   # [B] int32 scalar-prefetch: FIRST token's query position
    #             (-1 = inactive row; token t sits at qpos + t)
    bound_ref,  # [B] int32 scalar-prefetch: live-block grid bound per row
    layer_ref,  # [1] int32 scalar-prefetch: pool layer this call reads
    q_ref,      # [1, KVH, TG8, d] — sublane row r = t*group + g
    k_ref,      # [1, KVH, 1, BLK, d] (int8 when quantized)
    v_ref,      # [1, KVH, 1, BLK, d] (int8 when quantized)
    pos_ref,    # [1, 1, BLK] int32 slot positions of the block
    *rest,      # [k_scale_ref, v_scale_ref] when quantized
    #             ([1, KVH, 1, 1, BLK] fp32); o_ref; lse_ref; scratch
    scale: float,
    n_blocks: int,
    kvh: int,
    tg8: int,
    t_tokens: int,
    group: int,
    quantized: bool = False,
):
    """Online-softmax sweep of one row's pool blocks.

    ``t_tokens`` queries per (row, query head) ride the sublane axis
    (row r = t*group + g); their positions are CONSECUTIVE — token t at
    ``qpos + t`` — so per-token masks derive from a sublane iota and no
    per-token position plane is needed.  T=1 keeps the original
    whole-tile skip for fully-masked tiles; T>1 additionally zeroes
    masked probabilities explicitly, because one tile can be live for a
    late token but fully masked for an early one (the skip guard is
    per-tile, not per-sublane).
    """
    if quantized:
        k_scale_ref, v_scale_ref, *rest = rest
    else:
        k_scale_ref = v_scale_ref = None
    o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    mb = pl.program_id(1)
    nmb = pl.num_programs(1)

    @pl.when(mb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    qp = qpos_ref[b]
    qp_last = qp + t_tokens - 1
    kp = pos_ref[0, :1, :]  # [1, BLK]
    # Three dead-block guards, all mandatory:
    #   * mb >= bound: past the row's last attendable block — the index
    #     maps clamped the fetch (no new DMA); the tile is a repeat.
    #   * table sentinel / inactive row.
    #   * all-masked tile (min live kp > last token's position):
    #     processing it would add p = exp(MASK - MASK) = 1 garbage into
    #     l/acc — the block must be SKIPPED, not merely masked (same
    #     invariant as flash block_live).
    live_kp = jnp.where(kp >= 0, kp, jnp.iinfo(jnp.int32).max)
    live = (
        (mb < bound_ref[b])
        & (tbl_ref[b * nmb + mb] < n_blocks)
        & (qp >= 0)
        & (jnp.min(live_kp) <= qp_last)
    )

    if t_tokens > 1:
        # Per-sublane query position: row r holds token r // group.
        # (Pad rows past t_tokens*group get later tokens' looser masks;
        # their q rows are zero-padding and their outputs are sliced off.)
        qp_rows = qp + jax.lax.broadcasted_iota(
            jnp.int32, (tg8, 1), 0
        ) // group  # [TG8, 1]
    else:
        qp_rows = None

    @pl.when(live)
    def _compute():
        # One grid cell covers ALL KV heads of the block (the loop
        # unrolls statically): grid cells are B × MB, not B × KVH × MB —
        # measured ~1 µs of per-cell overhead made the finer grid SLOWER
        # than the gathered-view fallback it replaces.
        for h in range(kvh):
            sl = slice(h * tg8, (h + 1) * tg8)
            q = q_ref[0, h]
            if quantized:
                # int8 pool: cast the tile in VMEM (int8 magnitudes are
                # exact in bf16) and fold the per-slot dequant scales at
                # the scores / probability level — the same commuting
                # trick as flash_attention_quantized, so HBM streams the
                # int8 bytes.
                k = k_ref[0, h, 0].astype(q.dtype)
                ksc = k_scale_ref[0, h, 0, :1, :]  # [1, BLK] fp32
            else:
                k = k_ref[0, h, 0]
                ksc = None
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [TG8, BLK]
            if quantized:
                s = s * ksc
            if t_tokens > 1:
                allowed = (kp >= 0) & (kp <= qp_rows)  # [TG8, BLK]
            else:
                allowed = (kp >= 0) & (kp <= qp)       # [1, BLK]
            s = jnp.where(allowed, s, MASK_VALUE)
            m_prev = m_ref[sl, :1]
            m_new = jnp.maximum(
                m_prev, jnp.max(s, axis=-1, keepdims=True)
            )
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            if t_tokens > 1:
                # A tile can be live for token T-1 yet fully masked for
                # token 0: that token's m_new stays MASK_VALUE and
                # exp(MASK - MASK) = 1 would poison l/acc — zero masked
                # probabilities explicitly (the T=1 path never hits this:
                # its one qp makes tile-liveness == row-liveness).
                p = jnp.where(allowed, p, 0.0)
            l_ref[sl] = jnp.broadcast_to(
                alpha * l_ref[sl, :1] + jnp.sum(p, axis=-1, keepdims=True),
                (tg8, l_ref.shape[1]),
            )
            if quantized:
                pv = (p * v_scale_ref[0, h, 0, :1, :]).astype(q.dtype)
                vb = v_ref[0, h, 0].astype(q.dtype)
            else:
                pv = p.astype(v_ref.dtype)
                vb = v_ref[0, h, 0]
            acc_ref[sl] = alpha * acc_ref[sl] + jax.lax.dot_general(
                pv, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[sl] = jnp.broadcast_to(m_new, (tg8, m_ref.shape[1]))

    @pl.when(mb == nmb - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (
            acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        ).reshape(kvh, tg8, -1).astype(o_ref.dtype)
        # lse stays ~MASK_VALUE for rows that attended nothing, so the
        # caller's merge weight exp(lse - m_tot) underflows to exactly 0.
        lse_ref[0] = (
            m_ref[:] + jnp.log(jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:]))
        ).reshape(kvh, tg8, -1)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@functools.partial(jax.jit, static_argnames=("t_tokens", "interpret"))
def paged_pool_attention(
    q: jnp.ndarray,        # [B, KVH, T*G, d]  (packed queries, r = t*G + g)
    k_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d] (or [KVH, NB, BLK, d])
    v_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d]
    pool_pos: jnp.ndarray,  # [NB, BLK] int32 (-1 = invalid slot)
    table: jnp.ndarray,    # [B, MB] int32 physical block ids (NB = unused)
    q_pos: jnp.ndarray,    # [B] int32 first token's position (-1 = inactive)
    k_scale: Optional[jnp.ndarray] = None,  # [L, KVH, NB, BLK] fp32 (int8)
    v_scale: Optional[jnp.ndarray] = None,
    t_tokens: int = 1,
    layer: Optional[jnp.ndarray] = None,    # int32 layer index into L
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Attend each row's table-mapped pool blocks; no gather, pool read once.

    The pool carries its LAYER axis and ``layer`` (a traced scalar — the
    layer scan's loop index) selects the plane inside the kernel's index
    maps.  Slicing ``pool[layer]`` at the caller instead would
    materialize a full copy of the layer's plane as the custom-call
    operand — 2 planes × L layers × plane-bytes of pure copy traffic per
    decode step, which at 16k context cost ~3× the kernel itself
    (xplane-measured r4: 4.7 of 9.3 ms/step).  A 4-D pool (single plane)
    is accepted for compatibility and reads layer 0.

    With ``t_tokens`` > 1 each row carries T queries at CONSECUTIVE
    positions (token t at ``q_pos + t`` — the speculative-verify /
    multi-token decode shape); they ride the sublane axis packed
    ``r = t*G + g``, so the pool still streams ONCE for the whole
    (row, T) group.  With ``k_scale``/``v_scale`` the pool is int8 and
    the per-slot dequant scales fold in-kernel (scores-level for K,
    probability-level for V) — the pool streams at one byte per element
    plus fp32 scales.

    Returns (out [B, KVH, T*G, d] fp32, normalized over the pool slots,
    lse [B, KVH, T*G] fp32 row logsumexp) for the caller's
    new-token merge (fp32 end-to-end through the merge — see the
    out_shape note in the kernel call).
    """
    _maybe_fault()
    if k_pool.ndim == 4:
        k_pool, v_pool = k_pool[None], v_pool[None]
        if k_scale is not None:
            k_scale, v_scale = k_scale[None], v_scale[None]
        layer = None
    # A multi-layer pool without a layer index would silently attend
    # layer 0 everywhere — fail at trace time instead.  ValueError, not
    # assert: unlike the adjacent shape asserts (whose mistakes surface
    # immediately as shape errors), this guard protects against silently
    # WRONG results and must survive `python -O`.
    if k_pool.shape[0] != 1 and layer is None:
        raise ValueError(
            "multi-layer pool requires the `layer` index (a 5-D pool with "
            "layer=None would attend layer 0 for every layer)"
        )
    layer_arr = (
        jnp.zeros((1,), jnp.int32) if layer is None
        else jnp.asarray(layer, jnp.int32).reshape(1)
    )
    B, KVH, TG, d = q.shape
    NB, BLK = pool_pos.shape
    MB = table.shape[1]
    L = k_pool.shape[0]
    assert k_pool.shape == (L, KVH, NB, BLK, d), (
        k_pool.shape, (L, KVH, NB, BLK, d)
    )
    assert TG % t_tokens == 0, (TG, t_tokens)
    group = TG // t_tokens
    quantized = k_scale is not None
    interpret = _resolve_interpret(interpret)
    TG8 = _round_up(TG, _SUBLANES)
    qg = jnp.pad(q, ((0, 0), (0, 0), (0, TG8 - TG), (0, 0)))
    scale = 1.0 / (d ** 0.5)

    # Narrow-sublane position plane [NB, 1, BLK]: a free expand_dims
    # view — Mosaic accepts 1-row tiles here (verified compiled), so no
    # sublane replication and no per-step materialization is needed.
    pos_r = pool_pos[:, None, :]
    tbl_flat = table.astype(jnp.int32).reshape(B * MB)
    q_pos = q_pos.astype(jnp.int32)
    qp_last = q_pos + (t_tokens - 1)

    # Per-row live-block grid bound: 1 + the last table slot whose block
    # holds any slot this row's LAST query may attend.  Blocks at/after
    # the bound (reserved-but-unwritten tail, sentinel entries) are
    # clamped in the index maps — consecutive grid steps fetch the SAME
    # tile, so the pipeline skips the DMA — and the kernel skips their
    # compute.
    blk_min = jnp.min(
        jnp.where(pool_pos >= 0, pool_pos, jnp.iinfo(jnp.int32).max),
        axis=1,
    )  # [NB] min live position per physical block
    blk_min = jnp.concatenate(
        [blk_min, jnp.full((1,), jnp.iinfo(jnp.int32).max, jnp.int32)]
    )  # sentinel id NB -> never attendable
    row_min = blk_min[jnp.minimum(table, NB)]  # [B, MB]
    attendable = row_min <= qp_last[:, None]
    bound = 1 + jnp.max(
        jnp.where(
            attendable, jnp.arange(MB, dtype=jnp.int32)[None, :], -1
        ),
        axis=1,
    )  # [B] in [0, MB]

    def _clamp_mb(b, mb, tbl, bound):
        mb = jnp.minimum(mb, jnp.maximum(bound[b] - 1, 0))
        return jnp.minimum(tbl[b * MB + mb], NB - 1)

    def kv_map(b, mb, tbl, qpos, bound, layer):
        return (layer[0], 0, _clamp_mb(b, mb, tbl, bound), 0, 0)

    def pos_map(b, mb, tbl, qpos, bound, layer):
        return (_clamp_mb(b, mb, tbl, bound), 0, 0)

    def q_map(b, mb, tbl, qpos, bound, layer):
        return (b, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, KVH, TG8, d), q_map),
        pl.BlockSpec((1, KVH, 1, BLK, d), kv_map),
        pl.BlockSpec((1, KVH, 1, BLK, d), kv_map),
        pl.BlockSpec((1, 1, BLK), pos_map),
    ]
    operands = [qg, k_pool, v_pool, pos_r]
    if quantized:
        # Narrow-sublane scale planes [L, KVH, NB, 1, BLK]: free
        # expand_dims views of the long-lived pool scales — NOT sublane-
        # replicated copies, which would re-materialize (and stream) 8x
        # the scale bytes per layer per step on the path this kernel
        # exists to make bandwidth-lean.
        def scale_map(b, mb, tbl, qpos, bound, layer):
            return (layer[0], 0, _clamp_mb(b, mb, tbl, bound), 0, 0)

        scale_spec = pl.BlockSpec((1, KVH, 1, 1, BLK), scale_map)
        in_specs += [scale_spec, scale_spec]
        operands += [
            k_scale.astype(jnp.float32)[:, :, :, None, :],
            v_scale.astype(jnp.float32)[:, :, :, None, :],
        ]

    out, lse = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, n_blocks=NB, kvh=KVH, tg8=TG8,
            t_tokens=t_tokens, group=group, quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B, MB),
            in_specs=in_specs,
            out_specs=(
                pl.BlockSpec((1, KVH, TG8, d), q_map),
                pl.BlockSpec((1, KVH, TG8, _LANES), q_map),
            ),
            scratch_shapes=[
                pltpu.VMEM((KVH * TG8, _LANES), jnp.float32),
                pltpu.VMEM((KVH * TG8, _LANES), jnp.float32),
                pltpu.VMEM((KVH * TG8, d), jnp.float32),
            ],
        ),
        out_shape=(
            # fp32: the caller's new-token merge rescales this by
            # exp(lse - m_tot) and divides by the joint denominator — a
            # bf16 round HERE is one more rounding than the gathered
            # path's single joint softmax takes, and it measurably
            # widens the T=1-vs-T=G+1 numerical gap that flips greedy
            # argmax at near-ties (speculative self-draft acceptance).
            # Decode-sized output: the extra bytes are noise.
            jax.ShapeDtypeStruct((B, KVH, TG8, d), jnp.float32),
            jax.ShapeDtypeStruct((B, KVH, TG8, _LANES), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(tbl_flat, q_pos, bound, layer_arr, *operands)
    return out[:, :, :TG, :], lse[:, :, :TG, 0]


def paged_decode_attention(
    q: jnp.ndarray,        # [B, T, H, d] — this step's queries
    k_new: jnp.ndarray,    # [B, T, KVH, d] — this step's projections
    v_new: jnp.ndarray,    # [B, T, KVH, d]
    k_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d] (or [KVH, NB, BLK, d])
    v_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d]
    pool_pos: jnp.ndarray,  # [NB, BLK]
    table: jnp.ndarray,    # [B, MB]
    q_pos: jnp.ndarray,    # [B] FIRST token's position (-1 = inactive row)
    k_scale: Optional[jnp.ndarray] = None,  # [L, KVH, NB, BLK] (int8 pool)
    v_scale: Optional[jnp.ndarray] = None,
    layer: Optional[jnp.ndarray] = None,    # int32 index into L
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """One decode step of attention over (pool blocks ∪ the step's T new
    slots).

    The pool pass runs in the Pallas kernel (T consecutive-position
    queries per row share ONE pool sweep — the speculative-verify shape);
    the step's own T tokens (token t attends new slots j <= t, plus
    itself) merge at the softmax level outside, keeping the pool
    immutable through the layer scan (same append-free contract as
    ``sdpa_cached``; the new tokens' K/V enter the merge at full
    precision, also matching sdpa_cached — only POOL reads see int8).
    Token t's position is ``q_pos + t`` for active rows (consecutive —
    the T>1 kernel's contract).  Returns [B, T, H, d].
    """
    B, T, H, d = q.shape
    KVH = k_new.shape[2]

    # Tensor/data-parallel serving: a pallas_call is not partitioned by
    # GSPMD, so under an active mesh the whole op runs per-shard inside
    # shard_map — KV heads split over "tensor" (the head layout
    # h = kvh*G + g makes contiguous H chunks == contiguous KVH chunks),
    # rows over the batch axes ("data", "fsdp") — the same pair the
    # model's `constrain` shards batch over, so an fsdp-only mesh also
    # routes through shard_map rather than leaving a GSPMD-sharded
    # pallas_call.  The pool shards on its leading KVH axis; the table
    # and q_pos shard with the rows; only pool_pos is replicated.  No
    # collectives are needed: every (row, kv head) pair is independent;
    # the caller's o-projection all-reduce (GSPMD) recombines heads
    # exactly as on the xla path.
    from ..parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        tp = mesh.shape.get("tensor", 1)
        row_axes = tuple(
            a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1
        )
        rp = int(np.prod([mesh.shape[a] for a in row_axes])) if row_axes else 1
        if tp > 1 or rp > 1:
            if KVH % tp != 0 or B % rp != 0:
                raise NotImplementedError(
                    f"paged kernel sharding needs kv_heads % tensor == 0 "
                    f"and n_slots % (data*fsdp) == 0 (got KVH={KVH}, "
                    f"tp={tp}, B={B}, rows={rp}); use a compatible mesh "
                    f"or the gathered-view path"
                )
            rows = row_axes if row_axes else None
            tens = "tensor" if tp > 1 else None
            head4 = P(rows, None, tens, None)
            pooled = (
                P(None, tens, None, None, None) if k_pool.ndim == 5
                else P(tens, None, None, None)
            )
            scale_spec = (
                P(None, tens, None, None) if k_pool.ndim == 5
                else P(tens, None, None)
            )
            layer_op = (
                jnp.zeros((), jnp.int32) if layer is None
                else jnp.asarray(layer, jnp.int32).reshape(())
            )
            args = [
                q, k_new, v_new, k_pool, v_pool, pool_pos, table, q_pos,
                layer_op,
            ]
            in_specs = [
                head4, head4, head4, pooled, pooled, P(None, None),
                P(rows, None), P(rows), P(),
            ]
            if k_scale is not None:
                args += [k_scale, v_scale]
                in_specs += [scale_spec, scale_spec]

            def body(q, k_new, v_new, k_pool, v_pool, pool_pos, table,
                     q_pos, layer, k_scale=None, v_scale=None):
                return _paged_decode_local(
                    q, k_new, v_new, k_pool, v_pool, pool_pos, table,
                    q_pos, k_scale, v_scale, layer, interpret,
                )

            from ..parallel.mesh import shard_map_compat

            fn = shard_map_compat(
                body, mesh=mesh, in_specs=tuple(in_specs),
                out_specs=head4, check_vma=False,
            )
            return fn(*args)

    return _paged_decode_local(
        q, k_new, v_new, k_pool, v_pool, pool_pos, table, q_pos,
        k_scale, v_scale, layer, interpret,
    )


def _paged_decode_local(
    q, k_new, v_new, k_pool, v_pool, pool_pos, table, q_pos,
    k_scale, v_scale, layer, interpret,
):
    """Single-shard body of ``paged_decode_attention`` (also the whole op
    when no mesh is active)."""
    B, T, H, d = q.shape
    KVH = k_new.shape[2]
    G = H // KVH
    scale = 1.0 / (d ** 0.5)

    # Head layout h = kvh * G + g (same contract as flash GQA packing);
    # kernel sublane packing r = t*G + g.
    q5 = q.reshape(B, T, KVH, G, d)
    qg = jnp.swapaxes(q5, 1, 2).reshape(B, KVH, T * G, d)
    out_pool, lse = paged_pool_attention(
        qg, k_pool, v_pool, pool_pos, table, q_pos,
        k_scale=k_scale, v_scale=v_scale, t_tokens=T, layer=layer,
        interpret=interpret,
    )
    out_pool = out_pool.reshape(B, KVH, T, G, d)
    lse = lse.reshape(B, KVH, T, G)

    # New-slot scores [B, KVH, T, G, T]: token t attends the step's own
    # slots j <= t (a token may attend itself; positions are consecutive
    # so j <= t IS the positional mask).
    s_new = jnp.einsum(
        "btkgd,bjkd->bktgj", q5, k_new,
        preferred_element_type=jnp.float32,
    ) * scale
    t_idx = jnp.arange(T, dtype=jnp.int32)
    causal = t_idx[:, None] >= t_idx[None, :]  # [T(t), T(j)]
    s_new = jnp.where(causal[None, None, :, None, :], s_new, MASK_VALUE)

    m_tot = jnp.maximum(lse, jnp.max(s_new, axis=-1))  # [B, KVH, T, G]
    w_pool = jnp.exp(lse - m_tot)
    p_new = jnp.exp(s_new - m_tot[..., None])          # [B, KVH, T, G, T]
    p_new = jnp.where(causal[None, None, :, None, :], p_new, 0.0)
    denom = w_pool + jnp.sum(p_new, axis=-1)
    new_contrib = jnp.einsum(
        "bktgj,bjkd->bktgd", p_new, v_new.astype(jnp.float32),
    )
    out = (
        out_pool.astype(jnp.float32) * w_pool[..., None] + new_contrib
    ) / denom[..., None]
    out = jnp.swapaxes(out, 1, 2).reshape(B, T, H, d)
    return out.astype(q.dtype)
