"""Pluggable attention-kernel selection layer + the two stock-Pallas
kernels it lands: splash-mha prefill and stock paged-attention decode.

Why a selection layer: BENCH_r05 put our custom flash prefill at
~149-160 TFLOPs (~78% of MXU peak) while plain matmuls hit ~90% — the
VPU softmax serializes against the MXU k-sweep, the exact pipelining
problem the upstream splash kernel family solves with tuned
``BlockSizes``.  Rather than rewriting ``ops/flash_attention.py``
in-place (and losing the known-good baseline), prefill and decode
attention become PLUGGABLE: config names a kernel per role, serving
resolves "auto" once at batcher construction (ctor-stable — no
per-dispatch cache-key churn), and each alternative kernel quarantines
back to the *custom* kernel it A/Bs against, never straight to XLA.

Roles and ladders (see README "Kernels"):

  prefill: splash -> flash -> xla
      ``splash`` = upstream ``make_splash_mha_single_device`` with a
      pure ``CausalMask`` offset per prefill chunk.  It lands on the
      whole-prompt / chunked-classic insert path only
      (``serving._paged_insert``): there the chunk's base offset is a
      PYTHON int (the insert's chunk-loop variable), which is what a
      splash mask needs — splash masks are built at trace time from
      static ints.  The fused prefill-decode chunk
      (``serving._fused_chunk``) keeps the custom flash kernel: its
      window base ``pf_base + pf_off`` is a TRACED scalar, outside
      splash's static mask surface (the ISSUE's measure-and-decide
      OR-clause, resolved structurally: no mask re-build per step can
      express a traced offset).
  decode: stock-paged -> paged -> gathered
      ``stock-paged`` = the upstream Pallas paged-attention kernel
      body, launched through a vendored wrapper that keeps the (m, l)
      softmax state the public entry point discards — our decode
      contract merges the step's own K/V at the softmax level against
      an immutable pool, so the kernel must return its logsumexp.
      T == 1 dispatches only (speculative verify keeps the custom
      kernel's native multi-token sweep); int8 pools stay on the
      custom kernel (in-kernel scale folding is its feature).

Every kernel here registers a ``ProgramContract`` + ``CommsBudget``
(analysis/contracts.py), a degrade.py feature site, and a faults.py
trace-time hook — the PR-11/12 landing checklist.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _CompilerParams, _resolve_interpret

# ---------------------------------------------------------------------------
# Selection registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One selectable attention kernel.

    ``fallback`` is the kernel quarantine rebuilds select (None = this
    IS the baseline for its role); ``feature`` / ``fault_site`` are the
    degrade.py and faults.py names wired for it (None = covered by the
    baseline's existing sites).
    """

    name: str
    role: str                      # "prefill" | "decode"
    fallback: Optional[str] = None
    feature: Optional[str] = None  # degrade.py FEATURES entry
    fault_site: Optional[str] = None  # faults.py SITES entry


PREFILL_KERNELS = {
    "flash": KernelSpec(
        "flash", "prefill",
        feature="flash_attention", fault_site="flash_kernel",
    ),
    "splash": KernelSpec(
        "splash", "prefill", fallback="flash",
        feature="splash_prefill", fault_site="splash_kernel",
    ),
}

DECODE_KERNELS = {
    "paged": KernelSpec(
        "paged", "decode",
        feature="paged_kernel", fault_site="paged_kernel",
    ),
    "stock-paged": KernelSpec(
        "stock-paged", "decode", fallback="paged",
        feature="stock_paged", fault_site="stock_paged_kernel",
    ),
    # The gathered view is not a kernel: it is the paged kernel's own
    # fallback (use_pallas_kernel=False), listed so the CLI surface and
    # the fallback ladder are complete.
    "gathered": KernelSpec("gathered", "decode"),
}


def resolve_prefill_kernel(name: Optional[str], config) -> str:
    """Map a CLI/ctor prefill-kernel name ("auto" included) to a
    concrete kernel name.  Auto policy: splash wherever its structural
    requirements can EVER hold (lane-aligned head_dim, full-precision
    cache) — per-call shape eligibility still gates each chunk, so an
    auto-splash config silently runs flash for non-128-multiple chunks.
    """
    name = name or "auto"
    if name == "auto":
        return (
            "splash"
            if config.head_dim % 128 == 0
            and config.kv_cache_dtype != "int8"
            else "flash"
        )
    if name not in PREFILL_KERNELS:
        raise ValueError(
            f"unknown prefill kernel {name!r}; "
            f"have {sorted(PREFILL_KERNELS)} or 'auto'"
        )
    return name


def resolve_decode_kernel(name: Optional[str], config) -> str:
    """Map a CLI/ctor decode-kernel name to a concrete kernel name.
    Auto resolves to the custom paged kernel: it keeps int8 pools,
    multi-token (speculative verify) sweeps, and the measured
    one-cell-per-block grid; stock-paged is the A/B alternative until a
    TPU round shows it ahead."""
    name = name or "auto"
    if name == "auto":
        return "paged"
    if name not in DECODE_KERNELS:
        raise ValueError(
            f"unknown decode kernel {name!r}; "
            f"have {sorted(DECODE_KERNELS)} or 'auto'"
        )
    return name


def splash_eligible(
    config,
    *,
    batch: int,
    q_len: int,
    kv_len: int,
    chunk_offset: Optional[int],
    quantized: bool = False,
    mesh=None,
) -> bool:
    """Static per-call predicate: can THIS prefill chunk run splash?

    Everything here is trace-time static (shapes, config, the mesh, the
    chunk's Python-int offset), so ``models._block`` decides per chunk
    with zero runtime cost, and serving's host mirror replicates the
    decision exactly (it passes the same arguments).  Splash needs
    lane-aligned geometry (head_dim and both sequence lengths multiples
    of 128 — the kernel's grid/lane tiling), a static mask offset, and
    a full-precision cache; under a mesh it runs per-shard (heads over
    "tensor", rows over the batch axes), so the same divisibility the
    paged kernel requires applies.
    """
    if config.prefill_kernel != "splash":
        return False
    if chunk_offset is None or quantized:
        return False
    d = config.head_dim
    if d % 128 != 0 or q_len % 128 != 0 or kv_len % 128 != 0:
        return False
    if mesh is None:
        from ..parallel.mesh import current_mesh

        mesh = current_mesh()
    if mesh is not None:
        if mesh.shape.get("seq", 1) > 1 or mesh.shape.get("stage", 1) > 1:
            return False
        tp = mesh.shape.get("tensor", 1)
        rp = int(
            np.prod([
                mesh.shape.get(a, 1) for a in ("data", "fsdp")
            ])
        )
        if config.kv_heads % tp != 0 or batch % rp != 0:
            return False
    return True


# ---------------------------------------------------------------------------
# Splash-mha prefill
# ---------------------------------------------------------------------------


def _maybe_fault_splash() -> None:
    """Chaos-drill hook: faults.py trace-time registry, site
    "splash_kernel" (the splash twin of ops.flash_attention's hook)."""
    from ..faults import fire_trace

    fire_trace("splash_kernel")


def _splash_block_sizes(T: int, S: int):
    """Tuned-enough BlockSizes: 512 where the length allows (the MXU
    pipelining win splash exists for), 128 otherwise (the kernel's lane
    minimum; eligibility already guarantees 128-multiples)."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    bq = 512 if T % 512 == 0 else 128
    bkv = 512 if S % 512 == 0 else 128
    return sk.BlockSizes(block_q=bq, block_kv=bkv, block_kv_compute=bkv)


@functools.partial(
    jax.jit, static_argnames=("chunk_offset", "interpret")
)
def splash_prefill(
    q: jnp.ndarray,   # [B, T, H, d] — this chunk's queries
    k: jnp.ndarray,   # [B, S, KVH, d] — the FULL post-write cache view
    v: jnp.ndarray,   # [B, S, KVH, d]
    *,
    chunk_offset: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Upstream splash-mha over one prefill chunk of a right-padded
    insert.

    Query row t sits at absolute position ``chunk_offset + t``; cache
    column j holds position j (the insert path's slot-index == position
    contract).  A pure ``CausalMask((T, S), offset=chunk_offset)``
    (semantics: query t attends j <= t + offset) is therefore EXACTLY
    the insert contract, with no SegmentIds: right padding means every
    column below a real token is real, so real queries only ever attend
    real written columns; padding queries attend padding columns and
    produce finite garbage that nothing consumes (the last-token gather
    indexes real rows only, and padding slots land in the pool carrying
    pos -1, which every decode kernel masks).  Columns at/after
    ``chunk_offset + T`` are unwritten cache tail — masked by causality.

    GQA is native (q [H, T, d] vs k/v [KVH, T, d] per row); the caller
    contract pre-scales q AND k by d**-0.25 (splash applies no scale;
    splitting the scale keeps both operands in comfortable bf16 range).
    Returns [B, T, H, d] in q's dtype.
    """
    _maybe_fault_splash()
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    B, T, H, d = q.shape
    S = k.shape[1]
    interpret = _resolve_interpret(interpret)
    mask = sm.MultiHeadMask(
        masks=[sm.CausalMask(shape=(T, S), offset=chunk_offset)] * H
    )
    kernel = sk.make_splash_mha_single_device(
        mask,
        block_sizes=_splash_block_sizes(T, S),
        interpret=interpret,
    )
    scale = d ** -0.25
    qs = jnp.swapaxes(q * scale, 1, 2)               # [B, H, T, d]
    ks = jnp.swapaxes(k * scale, 1, 2)               # [B, KVH, S, d]
    vs = jnp.swapaxes(v, 1, 2)
    out = jax.vmap(kernel)(qs, ks, vs)               # [B, H, T, d]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def splash_prefill_attention(
    q: jnp.ndarray,   # [B, T, H, d]
    k: jnp.ndarray,   # [B, S, KVH, d]
    v: jnp.ndarray,   # [B, S, KVH, d]
    *,
    chunk_offset: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Mesh-aware entry point for the splash prefill kernel.

    A pallas_call is not partitioned by GSPMD, so under an active mesh
    the kernel runs per-shard inside shard_map — heads over "tensor"
    (contiguous H chunks == contiguous KVH chunks under the
    h = kvh*G + g layout), rows over the batch axes — the same
    placement as ``ops.paged_attention``; each shard builds its own
    (local-head-count) mask.  No collectives: every (row, head) is
    independent; the caller's o-projection all-reduce recombines heads.
    ``splash_eligible`` already vetted the divisibility, so unlike the
    paged wrapper there is no raise path here.
    """
    from ..parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        tp = mesh.shape.get("tensor", 1)
        row_axes = tuple(
            a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1
        )
        if tp > 1 or row_axes:
            rows = row_axes if row_axes else None
            tens = "tensor" if tp > 1 else None
            spec = P(rows, None, tens, None)

            def body(q, k, v):
                # audit: trace-domain(chunk_offset is the insert
                # loop's PYTHON-int chunk base — multiples of the
                # fixed prefill chunk inside the pow2-bucketed group
                # width, O(blocks_per_slot) values, bounded where
                # serving constructs it; interpret is
                # platform-derived and ctor-stable, one value per
                # process)
                return splash_prefill(
                    q, k, v, chunk_offset=chunk_offset,
                    interpret=interpret,
                )

            from ..parallel.mesh import shard_map_compat

            fn = shard_map_compat(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False,
            )
            return fn(q, k, v)
    # audit: trace-domain(same bounds as the shard_map body above:
    # chunk_offset is serving's bounded Python-int chunk base,
    # interpret is platform-derived)
    return splash_prefill(
        q, k, v, chunk_offset=chunk_offset, interpret=interpret
    )


# ---------------------------------------------------------------------------
# Stock Pallas paged-attention decode
# ---------------------------------------------------------------------------


def _maybe_fault_stock() -> None:
    """Chaos-drill hook: faults.py trace-time registry, site
    "stock_paged_kernel" (the stock twin of ops.paged_attention's)."""
    from ..faults import fire_trace

    fire_trace("stock_paged_kernel")


def _pages_per_compute_block(mb: int) -> int:
    """Largest divisor of the per-row page count that is <= 8 — the
    stock kernel requires pages_per_sequence % pages_per_compute_block
    == 0, and ~8 pages per flash block keeps its VMEM double-buffer
    modest at every geometry we serve."""
    return max(d for d in range(1, min(mb, 8) + 1) if mb % d == 0)


def _stock_launch(
    q: jnp.ndarray,            # [B, G, d] — ONE kv head's query group
    k_pages: jnp.ndarray,      # [1, NP, BLK, d] flat page view
    v_pages: jnp.ndarray,      # [1, NP, BLK, d]
    lengths: jnp.ndarray,      # [B] int32
    page_indices: jnp.ndarray,  # [B, MB] int32 FLAT page ids
    *,
    pages_per_compute_block: int,
    interpret: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Vendored launch of the stock paged-attention kernel body.

    This mirrors the upstream ``paged_attention`` entry point's
    non-quantized / megacore=None / inline_seq_dim branch exactly (same
    grid, specs, scratch, scalar prefetch), with two deliberate
    differences: (a) it RETURNS the kernel's (out, m, l) instead of
    discarding m/l — our decode contract merges the step's own K/V at
    the softmax level against an immutable pool, which needs the pool
    logsumexp; and (b) ``interpret`` reaches the pallas_call, making
    the kernel CPU-testable (the upstream wrapper never exposes it).
    The kernel body itself is imported from jax, not copied.

    Returns (out [B, G, d] fp32/q-dtype NORMALIZED over the attended
    slots, m [B, G], l [B, G]); rows with length 0 keep the kernel's
    zero-init (m = -inf, l = 0, out = 0).
    """
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention_kernel as stock,
    )

    B, G, d = q.shape
    MB = page_indices.shape[1]
    page_size = k_pages.shape[2]
    if G % 8 != 0:
        # Upstream layout hint: reshape to [B, G, 1, d] and launch fp32
        # so XLA picks a <1x128> layout for the sub-8-sublane q tile.
        q4 = q.reshape(B, G, 1, d)
        q_block_spec = pl.BlockSpec(
            (None, G, None, d), lambda core, b, h, *_: (b, h, 0, 0)
        )
        q_dtype = jnp.float32
        launch_q = q4
    else:
        q_block_spec = pl.BlockSpec(
            (None, G, d), lambda core, b, h, *_: (b, h, 0)
        )
        q_dtype = q.dtype
        launch_q = q
    grid = (1, B, 1)  # (num_cores, batch, kv heads) — one head per call
    in_specs = [
        q_block_spec,
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        None,
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        None,
    ]
    scratch_shapes = (
        pltpu.VMEM(
            (2, pages_per_compute_block, page_size, d), k_pages.dtype
        ),
        None,
        pltpu.VMEM(
            (2, pages_per_compute_block, page_size, d), v_pages.dtype
        ),
        None,
        pltpu.SemaphoreType.DMA,
    )
    out, m, l = pl.pallas_call(
        functools.partial(
            stock.paged_flash_attention_kernel_inline_seq_dim,
            pages_per_sequence=MB,
            batch_size=B,
            pages_per_compute_block=pages_per_compute_block,
            mask_value=stock.DEFAULT_MASK_VALUE,
            attn_logits_soft_cap=None,
            megacore_mode=None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            in_specs=in_specs,
            out_specs=[q_block_spec, q_block_spec, q_block_spec],
            grid=grid,
            scratch_shapes=scratch_shapes,
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        out_shape=[
            jax.ShapeDtypeStruct(launch_q.shape, q_dtype),
            jax.ShapeDtypeStruct((*launch_q.shape[:-1], 1), jnp.float32),
            jax.ShapeDtypeStruct((*launch_q.shape[:-1], 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        lengths,
        page_indices.reshape(-1),
        jnp.zeros((1,), jnp.int32),  # buffer index
        jnp.zeros((1,), jnp.int32),  # step
        launch_q.astype(q_dtype),
        k_pages,
        None,
        v_pages,
        None,
    )
    return (
        out.reshape(B, G, d),
        m.reshape(B, G),
        l.reshape(B, G),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def stock_paged_decode(
    q: jnp.ndarray,        # [B, 1, H, d] — this step's queries
    k_new: jnp.ndarray,    # [B, 1, KVH, d] — this step's projections
    v_new: jnp.ndarray,    # [B, 1, KVH, d]
    k_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d] (or [KVH, NB, BLK, d])
    v_pool: jnp.ndarray,
    table: jnp.ndarray,    # [B, MB] int32 block ids (NB = sentinel)
    q_pos: jnp.ndarray,    # [B] int32 token position (-1 = inactive row)
    layer: Optional[jnp.ndarray] = None,  # int32 index into L
    *,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """One T=1 decode step over (pool blocks ∪ the step's new slot)
    using the STOCK Pallas paged-attention kernel body.

    Same contract as ``ops.paged_attention.paged_decode_attention``
    restricted to T == 1 and full-precision pools: the pool stays
    immutable through the layer scan, the step's own K/V merges at the
    softmax level, and the row's query position IS the pool fill
    (slot index == position on the insert path), so
    ``lengths = max(q_pos, 0)`` — inactive rows (q_pos -1) attend
    nothing (the kernel's zero-init leaves lse = -inf, the merge weight
    underflows to exactly 0, and the row's finite-garbage output drops
    at write-back), with NO extra serving plumbing.

    Layer/head plane selection rides the PAGE INDICES instead of the
    kernel (the stock kernel has no layer axis): the [L, KVH, NB, ...]
    pool reshapes — free, row-major — to one flat [1, L*KVH*NB, ...]
    page array, and each (traced) layer + (static) local kv head offsets
    the row's table by ``(layer*KVH + h) * NB``; sentinel entries clamp
    to page 0, which ``lengths`` guarantees is never attended (fill
    only covers allocated blocks).  A per-KV-head Python loop launches
    the kernel with num_kv_heads == 1 — KVH/shard is small everywhere
    we serve, and the alternative (a transposed [KVH, L*NB, ...] view)
    would materialize a full pool copy per step, the exact copy-traffic
    the custom kernel's in-kernel layer select exists to avoid.

    Numerics note (documented, A/B-relevant): the stock kernel casts
    K/V tiles to bf16 in-kernel regardless of pool dtype, so fp32
    pools see one extra rounding vs the custom kernel.  Returns
    [B, 1, H, d] in q's dtype.
    """
    _maybe_fault_stock()
    if k_pool.ndim == 4:
        k_pool, v_pool = k_pool[None], v_pool[None]
        layer = None
    if k_pool.shape[0] != 1 and layer is None:
        raise ValueError(
            "multi-layer pool requires the `layer` index (a 5-D pool "
            "with layer=None would attend layer 0 for every layer)"
        )
    B, T, H, d = q.shape
    if T != 1:
        raise NotImplementedError(
            "stock-paged decode is T == 1 only; multi-token (speculative "
            "verify) dispatches use the custom paged kernel"
        )
    L, KVH, NB, BLK, _ = k_pool.shape
    MB = table.shape[1]
    G = H // KVH
    interpret = _resolve_interpret(interpret)
    ppcb = _pages_per_compute_block(MB)
    scale = 1.0 / (d ** 0.5)

    # Free flat views: [L, KVH, NB, BLK, d] -> [1, L*KVH*NB, BLK, d]
    # (row-major reshape; plane (l, h) starts at page (l*KVH + h)*NB).
    k_flat = k_pool.reshape(1, L * KVH * NB, BLK, d)
    v_flat = v_pool.reshape(1, L * KVH * NB, BLK, d)
    layer_idx = (
        jnp.zeros((), jnp.int32) if layer is None
        else jnp.asarray(layer, jnp.int32).reshape(())
    )
    lengths = jnp.maximum(q_pos.astype(jnp.int32), 0)
    # The kernel pre-applies no softmax scale: fold 1/sqrt(d) into q
    # once (scores-level; the new-slot merge below scales explicitly).
    q3 = (q[:, 0] * scale).astype(q.dtype)  # [B, H, d]

    outs, lses = [], []
    for h in range(KVH):
        flat_tbl = jnp.where(
            table < NB,
            table.astype(jnp.int32) + (layer_idx * KVH + h) * NB,
            0,
        )
        o_h, m_h, l_h = _stock_launch(
            q3[:, h * G:(h + 1) * G, :], k_flat, v_flat,
            lengths, flat_tbl,
            pages_per_compute_block=ppcb, interpret=interpret,
        )
        # lse = m + log(l); length-0 rows keep m=-inf/l=0 -> lse=-inf,
        # so the merge weight exp(lse - m_tot) is exactly 0 (no NaN:
        # the new-slot score below is always finite).
        lse_h = jnp.where(
            l_h > 0.0,
            m_h + jnp.log(jnp.where(l_h > 0.0, l_h, 1.0)),
            -jnp.inf,
        )
        outs.append(o_h.astype(jnp.float32))
        lses.append(lse_h)
    out_pool = jnp.stack(outs, axis=1)   # [B, KVH, G, d] normalized
    lse = jnp.stack(lses, axis=1)        # [B, KVH, G]

    # Softmax-level merge of the step's own slot (token attends itself;
    # same math as _paged_decode_local's T=1 case).
    q4 = q[:, 0].reshape(B, KVH, G, d).astype(jnp.float32)
    s_new = jnp.einsum(
        "bkgd,bkd->bkg", q4, k_new[:, 0].astype(jnp.float32)
    ) * scale
    m_tot = jnp.maximum(lse, s_new)
    w_pool = jnp.exp(lse - m_tot)
    p_new = jnp.exp(s_new - m_tot)
    denom = w_pool + p_new
    out = (
        out_pool * w_pool[..., None]
        + p_new[..., None] * v_new[:, 0].astype(jnp.float32)[:, :, None, :]
    ) / denom[..., None]
    return out.reshape(B, 1, H, d).astype(q.dtype)


def stock_paged_decode_attention(
    q: jnp.ndarray,        # [B, 1, H, d]
    k_new: jnp.ndarray,    # [B, 1, KVH, d]
    v_new: jnp.ndarray,    # [B, 1, KVH, d]
    k_pool: jnp.ndarray,   # [L, KVH, NB, BLK, d]
    v_pool: jnp.ndarray,
    table: jnp.ndarray,    # [B, MB]
    q_pos: jnp.ndarray,    # [B]
    layer: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Mesh-aware entry point for the stock paged decode kernel —
    the drop-in twin of ``paged_decode_attention`` (minus int8, minus
    T > 1).  Under a mesh the KV heads split over "tensor" and rows
    over the batch axes inside shard_map, the KV-head-over-"tensor"
    layout serve_mesh.py already places, so the flat-page offsets
    inside ``stock_paged_decode`` see the LOCAL head count.  The
    divisibility requirements (and the error text) match the custom
    kernel's — serving's ``_kernel_eligible`` host check already vets
    exactly these before enabling either paged kernel."""
    B = q.shape[0]
    KVH = k_new.shape[2]
    from ..parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        tp = mesh.shape.get("tensor", 1)
        row_axes = tuple(
            a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1
        )
        rp = (
            int(np.prod([mesh.shape[a] for a in row_axes]))
            if row_axes else 1
        )
        if tp > 1 or rp > 1:
            if KVH % tp != 0 or B % rp != 0:
                raise NotImplementedError(
                    f"paged kernel sharding needs kv_heads % tensor == 0 "
                    f"and n_slots % (data*fsdp) == 0 (got KVH={KVH}, "
                    f"tp={tp}, B={B}, rows={rp}); use a compatible mesh "
                    f"or the gathered-view path"
                )
            rows = row_axes if row_axes else None
            tens = "tensor" if tp > 1 else None
            head4 = P(rows, None, tens, None)
            pooled = (
                P(None, tens, None, None, None) if k_pool.ndim == 5
                else P(tens, None, None, None)
            )
            layer_op = (
                jnp.zeros((), jnp.int32) if layer is None
                else jnp.asarray(layer, jnp.int32).reshape(())
            )

            def body(q, k_new, v_new, k_pool, v_pool, table, q_pos, layer):
                # audit: trace-domain(interpret is platform-derived
                # and ctor-stable — one value per process)
                return stock_paged_decode(
                    q, k_new, v_new, k_pool, v_pool, table, q_pos,
                    layer, interpret=interpret,
                )

            from ..parallel.mesh import shard_map_compat

            fn = shard_map_compat(
                body, mesh=mesh,
                in_specs=(
                    head4, head4, head4, pooled, pooled,
                    P(rows, None), P(rows), P(),
                ),
                out_specs=head4, check_vma=False,
            )
            return fn(
                q, k_new, v_new, k_pool, v_pool, table, q_pos, layer_op
            )

    # audit: trace-domain(interpret is platform-derived and
    # ctor-stable — one value per process)
    return stock_paged_decode(
        q, k_new, v_new, k_pool, v_pool, table, q_pos, layer,
        interpret=interpret,
    )
