"""Weight-only int8 quantization for serving.

The reference has no quantization story (it serves fp32/bf16 weights,
``/root/reference/jax_llama/model.py`` throughout).  On TPU, autoregressive
decode is HBM-bandwidth-bound: every step streams the full weight set
through the MXU, so weight bytes ~= step time.  Storing projections as int8
(+ per-output-channel fp32 scales) halves that traffic vs bf16 and roughly
doubles steady-state decode throughput, at <0.5% typical quality cost.

Scheme: symmetric per-output-channel.  For a weight ``W`` contracted over
its input dims, ``scale[c] = max|W[:, c]| / 127`` and ``Wq = round(W /
scale)``.  The matmul computes ``(x @ Wq) * scale`` — exact algebra, because
the scale is constant along every contracted dim — so the int8→bf16 convert
is the only op XLA must fuse into the dot's operand read, and the fp32
rescale touches only the (small) output.

A ``QuantizedTensor`` is a pytree node, so quantized param trees flow
through ``jax.jit`` / ``lax.scan`` / Orbax / ``shard_map`` untouched; the
scale leaf keeps the weight's rank (contracted dims squeezed to 1) so a
stacked-layer scan can slice both leaves along the leading L axis.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["q", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class QuantizedTensor:
    """int8 weight + fp32 per-output-channel scale.

    q:     int8, original weight shape.
    scale: fp32, same rank; contracted (input) dims are size 1.
    """

    q: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _quantize_impl(w: jnp.ndarray, contract_axes: Tuple[int, ...]) -> QuantizedTensor:
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=contract_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


_quantize_jit = jax.jit(_quantize_impl, static_argnames=("contract_axes",))
_quantize_jit_donate = jax.jit(
    _quantize_impl, static_argnames=("contract_axes",), donate_argnums=(0,)
)


def quantize(
    w: jnp.ndarray, contract_axes: Tuple[int, ...], *, donate: bool = False
) -> QuantizedTensor:
    """Symmetric int8 quantization, per-channel over non-contracted dims.

    Runs under jit so XLA streams abs/max/round/clip into the int8 output
    without materializing full-size fp32 temporaries — eager execution
    would hold ~3x the weight in fp32 at peak, which OOMs a 70B
    quantize-on-load.  ``donate=True`` additionally releases the source
    buffer (the original array becomes invalid) so peak memory during a
    quantize-on-load never holds both precisions of the full model.
    """
    fn = _quantize_jit_donate if donate else _quantize_jit
    return fn(jnp.asarray(w), tuple(contract_axes))


def matmul(
    x: jnp.ndarray,
    w: Any,
    eq: str,
    dtype: Optional[jnp.dtype] = None,
    preferred_element_type: Optional[jnp.dtype] = None,
) -> jnp.ndarray:
    """``einsum(eq, x, w)`` that transparently handles QuantizedTensor.

    The einsum must list the weight's non-contracted dims in the output in
    the same relative order they hold in the weight (true for every
    projection in this model), so the scale broadcasts over the leading
    batch/seq dims of the output.

    Profile-attribution note: the model's hot path
    (``models.llama.qeinsum``) calls this only for QuantizedTensor
    weights and runs the plain-array einsum in its own frame — so a
    ``quant.py`` bucket in an xplane source breakdown (bench.py
    ``step_breakdown_us``) now measures real int8 dequant work, not the
    bf16 weight stream it used to swallow.
    """
    dtype = dtype or x.dtype
    if isinstance(w, QuantizedTensor):
        y = jnp.einsum(
            eq, x, w.q.astype(dtype),
            preferred_element_type=preferred_element_type or jnp.float32,
        )
        out_scale = w.scale.reshape(
            tuple(d for d in w.scale.shape if d != 1) or (1,)
        )
        y = y.astype(jnp.float32) * out_scale
        return y.astype(preferred_element_type or dtype)
    y = jnp.einsum(
        eq, x, w.astype(dtype),
        preferred_element_type=preferred_element_type,
    )
    return y if preferred_element_type else y.astype(dtype)


# Contraction axes of each quantizable projection, in the *per-layer* shape
# (the stacked tree adds a leading L axis — axes shift by one):
#   qkv [KVH, G+2, D, hd] contract D; o [H, hd, D] contract (H, hd);
#   gate_up [2, D, F] contract D; down [F, D] contract F; lm_head [D, V]
#   contract D.
_LAYER_CONTRACT = {
    "qkv": (2,), "o": (0, 1),
    "gate_up": (1,), "down": (0,),
}


def quantize_params(params: Any, *, donate: bool = False) -> Any:
    """Quantize every projection matrix in a model param tree to int8.

    Norm scales and the token embedding stay in their original dtype (the
    embedding is a gather, not a matmul; when it is tied as the LM head the
    tied path stays unquantized too).  ``donate=True`` frees each source
    weight as it is quantized — use for quantize-on-load, where the full-
    precision tree is not needed afterwards.
    """
    out = dict(params)
    lp = dict(params["layers"])
    for name, axes in _LAYER_CONTRACT.items():
        stacked_axes = tuple(a + 1 for a in axes)  # leading L axis
        lp[name] = quantize(lp[name], stacked_axes, donate=donate)
    out["layers"] = lp
    if "lm_head" in params:
        out["lm_head"] = quantize(params["lm_head"], (0,), donate=donate)
    return out


def is_quantized(params: Any) -> bool:
    return any(
        isinstance(l, QuantizedTensor)
        for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        )
    )
