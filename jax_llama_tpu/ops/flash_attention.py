"""Pallas TPU flash attention — blockwise online-softmax, GQA-aware.

This is the long-context answer to the reference's O(S²) attention (the
reference materializes a ``[1,1,S,S]`` causal mask at module setup,
``/root/reference/jax_llama/model.py:154``, and full ``[B,H,S,S]`` attention
weights, model.py:277-288).  Here scores only ever exist one
``[block_q, block_k]`` tile at a time in VMEM; masking is recomputed from
absolute positions inside the kernel, so memory is O(S·d) and sequence
length is bounded by HBM, not by the S×S buffer.

Algorithm: standard flash attention (online softmax).  Grid is
``(batch, q_heads, q_blocks, k_blocks)`` with the k axis innermost — TPU
executes the grid sequentially, so VMEM scratch (running max ``m``, running
denominator ``l``, fp32 accumulator ``acc``) persists across the k-block
sweep of each q block.  The output tile is written once, on the last
k step.

Masking is positional, matching ``ops.attention.attention_bias``:
a kv slot is attendable iff ``kv_pos <= q_pos`` (causality) and
``kv_pos >= 0`` (-1 marks padding / unwritten cache slots).  GQA is folded
into the index map — query head ``h`` reads KV head ``h // group`` — so KV
blocks are never replicated in memory (parity with the reference's
repeat-after-cache semantics, model.py:269-270, with zero copies).

Chunk-windowed prefill contract (fused prefill-decode scheduling,
``serving._fused_chunk``): because masking is purely positional, the
kernel needs NO special case to prefill a WINDOW of a prompt into an
existing cache row at a nonzero base offset — the queries arrive as a
[1, C] chunk whose positions start at ``base + off`` (``base`` = fill0
for prefix-cache hit rows, which begin their chunk walk there), and the
kv side is the row's gathered view where slots below the write offset
carry earlier chunks' (or the reused prefix's) real positions and
everything above carries -1.  Causality + the -1 rule then yield
exactly the window's attention set; the only caller obligation is the
scalar cache index (the per-row-index vector form routes to the XLA
path before reaching this kernel) and the view-capacity clamp on the
write window (``serving.ContinuousBatcher._pf_chunk``).  The serving
fault drills exercise this path through the same ``_maybe_fault``
trace hook as ordinary prefill.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept
# either so the kernels run across the version skew (same fields).
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

# Finite stand-in for -inf: fully-masked tiles then accumulate a bogus-but-
# finite (l, acc) that the online-softmax rescale zeroes out the moment a
# real score arrives (exp(MASK - real) == 0), and rows that stay fully
# masked divide by a nonzero l instead of producing NaN.
MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

_LANES = 128  # TPU lane width
_SUBLANES = 8  # TPU sublane width (fp32/int32)
# Forward-kernel k-tile sub-tiling factor (software pipeline: sub-tile
# i+1's MXU dot overlaps sub-tile i's VPU exp/mask work).  Swept on chip;
# tiles not divisible by this fall back to a single sub-tile.
_KSUB = 4

def _maybe_fault() -> None:
    """Chaos-drill hook: fires faults.py's trace-time registry (site
    "flash_kernel") at the kernel entry points' trace time — where a
    Mosaic compile failure would surface on real hardware."""
    from ..faults import fire_trace

    fire_trace("flash_kernel")


def _mix32(x):
    """splitmix32 finalizer: a bijective avalanche mix on uint32.

    The dropout mask must be regenerated bit-identically in THREE kernels
    (forward, dQ sweep, dK/dV sweep) whose grids visit tiles in different
    orders, and must run both compiled (Mosaic) and interpreted (CPU test
    meshes) — ``pltpu.prng_seed`` has no interpret-mode lowering in this
    JAX version, so the mask comes from a counter-based hash of the global
    (row, column) indices instead of hardware PRNG state.  uint32 wraparound
    is the modular arithmetic the constants were designed for.
    """
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _dropout_keep(seed_lo, seed_hi, b, h, row0, col0, bq, bk, rate):
    """Deterministic keep-mask tile [bq, bk] for probability dropout.
    ``row0``/``col0`` are the tile's GLOBAL element offsets (callers pass
    tile_index * tile_size — plus any sub-tile offset), so the hash is a
    pure function of global (row, column) and every tiling of the same
    plane draws identical bits.

    Keyed on (seed, batch, head, global row, global column) so any kernel
    that knows its tile coordinates rebuilds the exact same Bernoulli draw;
    element (r, c) keeps with probability 1 - rate.  The per-call seed is
    TWO uint32 words (64 bits): a single word birthday-collides across
    ~65k training steps per layer, silently reusing whole mask planes.
    Crucially the two words are NOT folded into one 32-bit base (that
    would re-create the same 32-bit birthday horizon, just decorrelated
    across planes): ``seed_lo`` keys the per-ROW words and ``seed_hi``
    the per-COLUMN words, so a repeated mask plane needs both 32-bit
    bases to collide simultaneously — a 64-bit event.  Cost: one extra
    per-column mix [1, bk]; the elementwise [bq, bk] hash is unchanged.

    Row and column enter the element hash JOINTLY (xor of two
    independently mixed words, not ``mix(row_word + col)``): an additive
    column would make every row a shifted window into one 1-D keep
    sequence, so row pairs whose mixed words land within S of each other
    would share diagonal runs of mask bits.
    """
    plane = _mix32(
        b.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
        + h.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
        + jnp.uint32(1)
    )
    base_lo = _mix32(seed_lo ^ plane)
    # The lane constant keeps base_hi independent of base_lo when
    # seed_hi == seed_lo (e.g. a widened legacy seed of 0).
    base_hi = _mix32(seed_hi ^ plane ^ jnp.uint32(0x85EBCA6B))
    rows = jax.lax.broadcasted_iota(jnp.uint32, (bq, 1), 0) + jnp.asarray(
        row0
    ).astype(jnp.uint32)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (1, bk), 1) + jnp.asarray(
        col0
    ).astype(jnp.uint32)
    bits = _mix32(
        _mix32(base_lo ^ rows)
        ^ _mix32(base_hi ^ (cols * jnp.uint32(0x9E3779B9)))
    )
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return bits >= threshold


def _tri_gate(qp, kp, bq_s, bk_s, quantized=False):
    """Shared gate for the three kernels' ragged diagonal bodies:
    ``(tri_ok, safe)`` where ``tri_ok`` is the STATIC shape check (sub-
    tilable both axes, and both sub-tile granularities sublane-aligned —
    the ragged bodies slice k/q tiles and store scratch row/column
    blocks at those granularities) and ``safe`` the DYNAMIC triangle-
    safety fold, ``None`` when ``tri_ok`` is False.

    One predicate serves all three kernels: the forward/dQ bodies skip
    (row block j) × (k sub-tile i) for j < i and the dK/dV body skips
    (q sub-tile i) × (column suffix past i) — both skip sets reduce to
    the same pairwise condition max(qp[block j]) < min(kp[block c]) for
    every j < c, which the prefix-max fold below checks exactly.
    (+INT_MAX padding slots never lower a block min, so padding can
    never unsoundly enable a skip.)
    """
    tri_ok = (
        not quantized
        and _KSUB >= 2  # the safety fold is vacuous at 1 sub-tile
        and bk_s % _KSUB == 0 and bk_s > _KSUB
        and bq_s % _KSUB == 0 and bq_s > _KSUB
        and (bq_s // _KSUB) % _SUBLANES == 0
        and (bk_s // _KSUB) % _SUBLANES == 0
    )
    if not tri_ok:
        return False, None
    rq = bq_s // _KSUB
    ksub = bk_s // _KSUB
    safe = None
    for i in range(1, _KSUB):
        cond = jnp.max(qp[: i * rq]) < jnp.min(
            kp[:, i * ksub:(i + 1) * ksub]
        )
        safe = cond if safe is None else (safe & cond)
    return True, safe


def _flash_tri_tile_update(
    q_ref, k_ref, v_ref, seed_ref,
    m_ref, l_ref, acc_ref, qp, kp, bi, hi, qi, ki,
    *, scale, dropout_rate,
):
    """Diagonal-crossing tile update with RAGGED sub-tile dots: k sub-tile
    ``i`` computes only query rows ``[i·rq:]`` — ``_KSUB`` shrinking dots
    (bq, bq−rq, … rows) whose union is exactly the live trapezoid plus
    the sub-diagonal halves, skipping the 37.5% of the tile's MXU work
    that the uniform body burned on fully-masked rows.  Correct only
    when the skipped (row-block j < sub-tile i) regions are provably
    dead — the caller guards with a dynamic triangle-safety predicate
    (ascending positions make it true for every causal crossing tile)
    and falls back to the full masked body otherwise.  State lands
    per row-block through static scratch slices (no ragged concat of
    the accumulator).  bf16-only (the quantized path keeps the
    single-tile body).
    """
    q = q_ref[0, 0]  # [bq, d]
    bq = q.shape[0]
    bk = k_ref.shape[2]
    nsub = _KSUB
    ksub = bk // nsub
    rq = bq // nsub
    allowed = kp <= qp  # [bq, bk]
    m_prev = m_ref[:, :1]  # [bq, 1]

    s_parts = []  # s_i: [bq - i*rq, ksub]
    m_parts = []  # row maxes, ragged
    for i in range(nsub):
        cols = slice(i * ksub, (i + 1) * ksub)
        kb = k_ref[0, 0, cols, :]
        s_i = jax.lax.dot_general(
            q[i * rq:], kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [(bq - i*rq), ksub], base-2 domain
        s_i = jnp.where(allowed[i * rq:, cols], s_i, MASK_VALUE)
        s_parts.append(s_i)
        m_parts.append(s_i.max(axis=-1, keepdims=True))

    # Per-row-block joint max: row block j is touched by sub-tiles
    # i <= j; m_parts[i]'s rows start at global row i*rq.
    m_blocks = []
    for j in range(nsub):
        mj = m_prev[j * rq:(j + 1) * rq]
        for i in range(j + 1):
            mj = jnp.maximum(
                mj, m_parts[i][(j - i) * rq:(j - i + 1) * rq]
            )
        m_blocks.append(mj)

    inv = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else None
    # exp2 + rowsum + PV per sub-tile (rows [i*rq:] only), then land
    # each row block's state once.
    r_parts = []  # [bq - i*rq, 1] rowsums
    d_parts = []  # [bq - i*rq, d] fp32 PV partials
    for i in range(nsub):
        cols = slice(i * ksub, (i + 1) * ksub)
        m_rows = jnp.concatenate(m_blocks[i:], axis=0)
        p = jnp.exp2(s_parts[i] - m_rows)
        r_parts.append(jnp.sum(p, axis=-1, keepdims=True))
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                seed_ref[0], seed_ref[1], bi, hi,
                qi * bq + i * rq, ki * bk + i * ksub,
                bq - i * rq, ksub, dropout_rate,
            )
            p_acc = jnp.where(keep, p, 0.0) * inv
        else:
            p_acc = p
        d_parts.append(jax.lax.dot_general(
            p_acc.astype(v_ref.dtype), v_ref[0, 0, cols, :],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ))

    for j in range(nsub):
        rows = slice(j * rq, (j + 1) * rq)
        alpha_j = jnp.exp2(m_prev[rows] - m_blocks[j])
        l_j = alpha_j * l_ref[rows, :1]
        acc_j = alpha_j * acc_ref[rows]
        for i in range(j + 1):
            sub = slice((j - i) * rq, (j - i + 1) * rq)
            l_j = l_j + r_parts[i][sub]
            acc_j = acc_j + d_parts[i][sub]
        acc_ref[rows] = acc_j
        m_ref[rows] = jnp.broadcast_to(m_blocks[j], (rq, m_ref.shape[1]))
        l_ref[rows] = jnp.broadcast_to(l_j, (rq, l_ref.shape[1]))


def _flash_kernel(
    kv_bound_ref,  # [B * nq] int32 scalar-prefetch: kv-block grid bound
    *args,  # [seed_ref] when dropout; q_pos/kv_pos/q/k/v refs;
    #         [k_scale_ref, v_scale_ref] when quantized; o_ref;
    #         (lse_ref,) when with_lse; then m/l/acc scratch
    scale: float,
    with_lse: bool,
    quantized: bool = False,
    dropout_rate: float = 0.0,
):
    if dropout_rate > 0.0:
        seed_ref, *args = args  # [2] uint32 scalar-prefetch (64-bit seed)
    else:
        seed_ref = None
    q_pos_ref, kv_pos_ref, q_ref, k_ref, v_ref, *rest = args
    # q_pos_ref: [1, bq, 1] int32 (narrow-lane view)
    # kv_pos_ref: [1, 1, bk] int32 (narrow-sublane view)
    # q_ref: [1, 1, bq, d]; k_ref/v_ref: [1, 1, bk, d] (int8 when quantized)
    if quantized:
        k_scale_ref, v_scale_ref, *rest = rest  # [1, 1, SUBLANES, bk] fp32
    else:
        k_scale_ref = v_scale_ref = None
    o_ref, *rest = rest
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        (m_ref, l_ref, acc_ref), lse_ref = rest, None
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    # program_id must be read OUTSIDE pl.when bodies (no interpret-mode
    # lowering inside the cond branch); the dropout hash closes over these.
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Narrow-sublane/lane position views (1-row tiles compile fine on
    # Mosaic — no replicated copies, no extra HBM traffic).
    qp = q_pos_ref[0, :, :1]  # [bq, 1]
    kp = kv_pos_ref[0, :1, :]  # [1, bk]

    # Grid-level dead-block skip: past this q block's kv bound the index
    # maps clamp to the boundary block (already-fetched — no new DMA) and
    # the tile must not be processed again.
    in_bound = ki < kv_bound_ref[
        pl.program_id(0) * pl.num_programs(2) + pl.program_id(2)
    ]
    # Block-level causal skip: if the smallest kv position in this block
    # exceeds every query position, no (q, kv) pair is attendable and
    # both dots + the softmax update can be skipped — for standard causal
    # prefill that halves the MXU work (every block above the diagonal).
    # Padding slots carry +INT_MAX here (the wrappers remap the public -1
    # convention before the kernel), so they exclude themselves from this
    # min AND from the single `kp <= qp` compare below — the kernel's
    # per-element mask chain is one compare + one select, not two
    # compares + and + select.  An all-padding block is skipped too (the
    # finalize guards l == 0 for rows that never attend).
    block_live = in_bound & (jnp.min(kp) <= jnp.max(qp))

    # r5: diagonal-crossing tiles take a RAGGED body that skips the dead
    # upper-triangle MXU work (see _flash_tri_tile_update) — the one
    # lever that moved after r4's sub-tile pipeline.  Gated statically
    # on shapes (sub-tilable, row blocks sublane-aligned, bf16) and
    # dynamically on triangle safety: the ragged body skips row block
    # j < sub-tile i entirely, sound iff max(qp[:i·rq]) < min(kp of
    # sub-tile i) for every i — true on every crossing tile of an
    # ascending position layout (causal prefill, cache layouts), false
    # for interior tiles and exotic layouts, which take the uniform
    # masked body below.  (+INT_MAX padding slots never lower the min.)
    # Negative results, xplane kernel-only at 16k vs the 8.35 ms / 66.8%
    # r4 baseline: a maskless interior-tile body variant measured
    # SLOWER (8.52 ms — the per-element mask select was already
    # overlapped; three bodies cost more than the select), as did
    # per-sub-tile exp bases with a correction tail (13.98 ms — holding
    # nsub [bq, d] fp32 PV partials wrecks Mosaic's schedule) and
    # hoisting the row-max reduces into the dot loop (exactly neutral —
    # the r4 "joint-max barrier" hypothesis is closed: it never cost
    # anything).
    tri_ok, safe = _tri_gate(
        qp, kp, q_ref.shape[2], k_ref.shape[2], quantized=quantized
    )
    if tri_ok:
        tri_live = block_live & safe
        full_live = block_live & jnp.logical_not(safe)

        @pl.when(tri_live)
        def _compute_tri():
            _flash_tri_tile_update(
                q_ref, k_ref, v_ref, seed_ref,
                m_ref, l_ref, acc_ref, qp, kp, bi, hi, qi, ki,
                scale=scale, dropout_rate=dropout_rate,
            )
    else:
        full_live = block_live

    @pl.when(full_live)
    def _compute():
        q = q_ref[0, 0]  # [bq, d]
        bq = q.shape[0]
        bk = k_ref.shape[2]
        # Software pipeline: the k tile is processed as ``nsub`` sub-tiles
        # so VPU softmax work and MXU dots of DIFFERENT sub-tiles are
        # dataflow-independent and Mosaic can overlap them — the r3
        # single-tile body serialized dot -> mask/max/exp -> dot, idling
        # the MXU through every exp sweep (kernel-only ~56% MXU at 16k
        # while the model's plain matmuls run ~90%).  Structure: all QK
        # dots issue first (each sub-tile's mask/scale select overlaps the
        # NEXT sub-tile's dot), one joint row max (same m as the
        # single-tile form — the online-softmax state update stays
        # once-per-tile), then each sub-tile's exp2 overlaps the previous
        # sub-tile's PV dot.
        # Quantized keeps the single-tile body: the per-sub-tile [1, ksub]
        # dequant-scale slices hit the same unsupported Mosaic layout as
        # narrow position slices, and the int8 path is inference
        # long-context decode — the pipeline win targets bf16
        # prefill/training.
        nsub = (
            _KSUB
            if (bk % _KSUB == 0 and bk > _KSUB and not quantized)
            else 1
        )
        ksub = bk // nsub
        if quantized:
            # int8 KV: cast the payload tile to the compute dtype in VMEM
            # (int8 magnitudes <= 127 are exact in bf16) and fold the
            # per-slot dequant scale into the SCORES — constant along d,
            # it commutes with the contraction, so HBM only ever streams
            # the int8 bytes (half the cache traffic of bf16).
            # NB: folding the scale into q outside the kernel was tried
            # and measured ~15% SLOWER on v5e (A/B, min-of-5
            # differencing) — the fused multiply here rides the MXU
            # output for free.
            ksc = k_scale_ref[0, 0, :1, :]  # [1, bk] fp32
        else:
            ksc = None
        # The online softmax runs in BASE 2: log2(e) is pre-folded into
        # `scale` (see _flash_forward), so the per-element transcendental
        # is a bare exp2 — the VPU's native exponent — instead of exp's
        # exp2(x·log2e) with its extra wide multiply.  exp2(s2 - m2)
        # equals exp(s - m) exactly in the mask limit too (MASK_VALUE is
        # a huge negative in either base).
        # Full-width mask compare once (narrow sub-tile broadcasts of the
        # 1-row position plane hit unsupported Mosaic layouts), sliced
        # per sub-tile below.
        allowed = kp <= qp  # [bq, bk]
        s_parts = []
        for i in range(nsub):
            cols = slice(i * ksub, (i + 1) * ksub)
            kb = k_ref[0, 0, cols, :]
            if quantized:
                kb = kb.astype(q.dtype)
            s_i = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [bq, ksub], base-2 domain
            if quantized:
                s_i = s_i * ksc[:, cols]
            s_parts.append(
                jnp.where(allowed[:, cols], s_i, MASK_VALUE)
            )

        m_prev = m_ref[:, :1]  # [bq, 1]
        m_cur = s_parts[0].max(axis=-1, keepdims=True)
        for s_i in s_parts[1:]:
            m_cur = jnp.maximum(m_cur, s_i.max(axis=-1, keepdims=True))
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp2(m_prev - m_new)  # [bq, 1] rescale of old state

        l_add = None
        acc_add = None
        for i in range(nsub):
            cols = slice(i * ksub, (i + 1) * ksub)
            p = jnp.exp2(s_parts[i] - m_new)  # [bq, ksub]
            ps = jnp.sum(p, axis=-1, keepdims=True)
            l_add = ps if l_add is None else l_add + ps
            if dropout_rate > 0.0:
                # Probability dropout (training): the final output is
                # acc / l, so zeroing entries of the acc-side p while
                # keeping the denominator's p intact is EXACTLY inverted
                # dropout applied to the post-softmax weights w = p / l —
                # the xla path's semantics (ops.attention.sdpa),
                # blockwise.  Global element offsets key the hash, so the
                # sub-tiling draws the identical bits the (untiled)
                # backward kernels rebuild.
                keep = _dropout_keep(
                    seed_ref[0], seed_ref[1], bi, hi,
                    qi * bq, ki * bk + i * ksub, bq, ksub, dropout_rate,
                )
                p_acc = jnp.where(keep, p, 0.0) * (
                    1.0 / (1.0 - dropout_rate)
                )
            else:
                p_acc = p
            if quantized:
                # v_scale folds into the (tiny) probabilities, mirroring
                # sdpa_cached's weights-level folding on the XLA path.
                pv = (p_acc * v_scale_ref[0, 0, :1, cols]).astype(q.dtype)
                vb = v_ref[0, 0, cols, :].astype(q.dtype)
            else:
                pv = p_acc.astype(v_ref.dtype)
                vb = v_ref[0, 0, cols, :]
            d_i = jax.lax.dot_general(
                pv, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_add = d_i if acc_add is None else acc_add + d_i

        l_new = alpha * l_ref[:, :1] + l_add
        acc_ref[:] = alpha * acc_ref[:] + acc_add
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        # l == 0 iff the row never saw a live kv slot (every block skipped);
        # emit 0 instead of 0/0 NaN.
        o_ref[0, 0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype
        )
        if with_lse:
            # Row logsumexp of the (scaled, masked) scores — the backward
            # kernels rebuild P = exp(s - lse) from it without storing
            # any S×S tensor.  Narrow-lane [bq, 1] (the lane-replicated
            # form cost 128x the lse bytes at long context).  m/l live in
            # the base-2 domain (see _compute); convert once per row so
            # the backward kernels stay in natural log.
            lse_ref[0, 0] = (
                m_ref[:, :1] + jnp.log2(
                    jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
                )
            ) * float(np.log(2.0))


def _normalize_seed(dropout_seed) -> jnp.ndarray:
    """Widen a scalar / [1] / [2] uint32 seed to the kernels' [2]-word
    (64-bit) layout; legacy single-word callers get a zero high word."""
    seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(-1)
    if seed.size == 1:
        return jnp.concatenate([seed, jnp.zeros((1,), jnp.uint32)])
    if seed.size != 2:
        raise ValueError(
            f"dropout_seed must hold 1 or 2 uint32 words, got {seed.size}"
        )
    return seed


def _pad_to(x: jnp.ndarray, axis: int, mult: int, value=0) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret", "dropout_rate"),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    block_q: int = 2048,
    block_k: int = 2048,
    interpret: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Blockwise attention; drop-in for ``ops.attention.sdpa`` + bias.

    Differentiable end-to-end in O(S·d) memory: the forward kernel saves
    the per-row logsumexp, and the backward runs two Pallas kernels
    (dQ sweep and dK/dV sweep) that rebuild probabilities tile-by-tile —
    no [T, S] score matrix exists in either direction, so 32k+ training
    contexts fit.

    Args:
      q: [B, T, H, d].
      k, v: [B, S, KVH, d], H % KVH == 0 (GQA).
      q_pos: [B, T] int32 absolute query positions (pre-clamped >= 0).
      kv_pos: [B, S] int32 kv slot positions, -1 for padding/unwritten.
      block_q, block_k: tile sizes (clamped to T / S).  Swept on a v5e
        with xplane device-time measurement (r4): with the sub-tiled
        software pipeline (_KSUB) and the 64 MiB scoped-vmem budget,
        (2048, 2048) runs the 16k forward at 66% MXU vs 56.5% for the r3
        (1024, 2048) default, and wins the fwd+bwd step too; larger
        tiles ((1024, 4096)+) lose it again — diagonal dead work and DMA
        overtake the per-step saving.
      dropout_rate: attention-probability dropout (training; parity with
        the reference's attn_pdrop, model.py:276-288, and with
        ``ops.attention.sdpa``'s inverted-dropout semantics).  The mask is
        generated *inside* the kernels from a counter-based hash — never
        materialized at [T, S] — and the backward kernels rebuild the
        identical mask, so gradients see exactly the forward's draw.
      dropout_seed: [2] uint32 seed words (64 bits; scalar / [1] inputs
        are widened with a zero high word); required when
        dropout_rate > 0.  Derive per call site, e.g. via
        ``jax.random.bits(key, (2,), "uint32")``.
    Returns:
      [B, T, H, d] in q.dtype.
    """
    _maybe_fault()
    H, KVH = q.shape[2], k.shape[2]
    assert H % KVH == 0, (H, KVH)
    group = H // KVH
    if not 0.0 <= dropout_rate < 1.0:
        # Validate BEFORE the >0 branch: a negative rate must raise, not
        # silently train without dropout.
        raise ValueError(f"dropout_rate={dropout_rate} not in [0, 1)")
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        seed = _normalize_seed(dropout_seed)
    else:
        seed = jnp.zeros((2,), jnp.uint32)
    if group > 1:
        # GQA query packing: fold the `group` query heads of each KV head
        # into the query-row axis, so the kernel grid runs over KV heads
        # and each KV block streams from HBM *once* per KV head instead of
        # once per query head (group x less KV-cache traffic — dominant in
        # long-context decode).  Masking is purely positional, so packing
        # is just a relayout: row r = g*T + t keeps position q_pos[t].
        # Dropout keys off the PACKED row index, so each (head, query)
        # pair still draws independently.
        B, T = q.shape[:2]
        qp = jnp.moveaxis(
            q.reshape(B, T, KVH, group, -1), 3, 1
        ).reshape(B, group * T, KVH, -1)
        pos_p = jnp.tile(q_pos, (1, group))
        out = _flash(
            qp, k, v, pos_p, kv_pos, seed, block_q, block_k, interpret,
            dropout_rate,
        )
        out = jnp.moveaxis(
            out.reshape(B, group, T, KVH, -1), 1, 3
        ).reshape(B, T, H, -1)
        return out
    return _flash(
        q, k, v, q_pos, kv_pos, seed, block_q, block_k, interpret,
        dropout_rate,
    )


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_attention_quantized(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_scale: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    block_q: int = 1024,
    block_k: int = 2048,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over an int8 KV cache (inference-only, no VJP).

    Same semantics as ``flash_attention`` with
    ``k[b,s,h,:] * k_scale[b,s,h]`` / ``v * v_scale`` as the effective
    keys/values — but the dequantization happens inside the kernel
    (scores-level for K, probability-level for V, matching
    ``ops.attention.sdpa_cached``'s folding), so HBM streams the int8
    payload, never a dequantized copy.

    Default tiles stay at the r3-swept (1024, 2048): the int8 body is
    excluded from the bf16 path's sub-tiled software pipeline (narrow
    scale slices hit an unsupported Mosaic layout), so the (2048, 2048)
    default that pipeline justified does not transfer — larger q tiles
    only add diagonal dead work to the unpipelined body.

    Args:
      q: [B, T, H, d] activation dtype.
      k, v: [B, S, KVH, d] int8.
      k_scale, v_scale: [B, S, KVH] fp32 per-slot-per-head scales.
      q_pos, kv_pos, block_q, block_k: as in ``flash_attention``.
    """
    _maybe_fault()
    H, KVH = q.shape[2], k.shape[2]
    assert H % KVH == 0, (H, KVH)
    group = H // KVH
    if group > 1:
        # Same GQA query packing as flash_attention: scales are per KV
        # head, so they need no relayout.
        B, T = q.shape[:2]
        qp = jnp.moveaxis(
            q.reshape(B, T, KVH, group, -1), 3, 1
        ).reshape(B, group * T, KVH, -1)
        pos_p = jnp.tile(q_pos, (1, group))
        out = _flash_forward(
            qp, k, v, pos_p, kv_pos, block_q, block_k, interpret,
            k_scale=k_scale, v_scale=v_scale,
        )
        out = jnp.moveaxis(
            out.reshape(B, group, T, KVH, -1), 1, 3
        ).reshape(B, T, H, -1)
        return out
    return _flash_forward(
        q, k, v, q_pos, kv_pos, block_q, block_k, interpret,
        k_scale=k_scale, v_scale=v_scale,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash(q, k, v, q_pos, kv_pos, seed, block_q, block_k, interpret,
           dropout_rate=0.0):
    return _flash_forward(
        q, k, v, q_pos, kv_pos, block_q, block_k, interpret,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )


def _flash_fwd(q, k, v, q_pos, kv_pos, seed, block_q, block_k, interpret,
               dropout_rate=0.0):
    out, lse = _flash_forward(
        q, k, v, q_pos, kv_pos, block_q, block_k, interpret, need_lse=True,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )
    return out, (q, k, v, q_pos, kv_pos, seed, out, lse)


def _flash_bwd(block_q, block_k, interpret, dropout_rate, res, g):
    q, k, v, q_pos, kv_pos, seed, out, lse = res
    dq, dk, dv = _flash_backward(
        q, k, v, q_pos, kv_pos, out, lse, g, block_q, block_k, interpret,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )
    # Integer primals take float0 cotangents.
    zq = np.zeros(q_pos.shape, jax.dtypes.float0)
    zk = np.zeros(kv_pos.shape, jax.dtypes.float0)
    zs = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, zq, zk, zs


_flash.defvjp(_flash_fwd, _flash_bwd)


def _resolve_interpret(interpret):
    if interpret is None:
        # Mosaic only targets TPU; everywhere else (CPU test meshes) run the
        # kernel interpreted.  default_backend() is concrete at trace time.
        interpret = jax.default_backend() != "tpu"
    return interpret


def _clamp_blocks(T, S, block_q, block_k, interpret):
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    if not interpret:
        # Mosaic tiling: a non-full block's last dim must be a multiple of
        # 128 and its second-to-last a multiple of 8.  block_q only ever
        # appears as a sublane dim (q/o/q_pos tiles) — 8-align it; block_k
        # is the lane dim of the kv_pos tile — 128-align it.
        if block_q < T:
            block_q = -(-block_q // _SUBLANES) * _SUBLANES
        if block_k < S:
            block_k = -(-block_k // _LANES) * _LANES
        block_q, block_k = min(block_q, T), min(block_k, S)
    return block_q, block_k


def _flash_forward(
    q, k, v, q_pos, kv_pos, block_q, block_k, interpret, need_lse=False,
    k_scale=None, v_scale=None, dropout_rate=0.0, dropout_seed=None,
):
    B, T, H, d = q.shape
    S, KVH = k.shape[1], k.shape[2]
    assert H % KVH == 0, (H, KVH)
    group = H // KVH
    quantized = k_scale is not None
    with_dropout = dropout_rate > 0.0
    assert not (with_dropout and quantized), (
        "dropout is training-only; the int8-KV path is inference-only"
    )
    # log2(e) folded into the score scale: the kernel's online softmax
    # runs in base 2 (bare VPU exp2 per element, no hidden wide multiply).
    scale = (1.0 / (d ** 0.5)) * float(np.log2(np.e))
    interpret = _resolve_interpret(interpret)
    block_q, block_k = _clamp_blocks(T, S, block_q, block_k, interpret)

    # Pad sequence axes up to tile multiples OUTSIDE the kernel: Pallas
    # out-of-bounds tile reads are undefined, so padded kv slots must carry
    # a real sentinel position for the in-kernel mask to exclude them.
    # Invalid slots (public contract: -1) are remapped to +INT_MAX here so
    # the kernel's per-element mask is ONE compare (`kp <= qp` excludes
    # padding by magnitude) instead of two compares + and.
    qt = _pad_to(jnp.swapaxes(q, 1, 2), 2, block_q)  # [B, H, Tp, d]
    kt = _pad_to(jnp.swapaxes(k, 1, 2), 2, block_k)  # [B, KVH, Sp, d]
    vt = _pad_to(jnp.swapaxes(v, 1, 2), 2, block_k)
    q_pos_p = _pad_to(q_pos.astype(jnp.int32), 1, block_q)
    kv_pos_p = _pad_to(kv_pos.astype(jnp.int32), 1, block_k, value=-1)
    kv_pos_p = jnp.where(
        kv_pos_p < 0, jnp.iinfo(jnp.int32).max, kv_pos_p
    )
    Tp, Sp = qt.shape[2], kt.shape[2]
    nq, nk = Tp // block_q, Sp // block_k
    # Narrow-lane/sublane position views (free expand_dims, no copies).
    q_pos_r = q_pos_p[:, :, None]
    kv_pos_r = kv_pos_p[:, None, :]

    grid = (B, H, nq, nk)

    # Per-(batch, q-block) kv grid bound: 1 + the last kv block holding any
    # live slot some query in the q block may attend.  Blocks at/after the
    # bound are clamped in the index maps below — consecutive grid steps
    # then request the SAME tile, and the Pallas pipeline skips the DMA —
    # and the kernel skips their compute via the prefetched bound.  For
    # causal prefill this removes the dead upper-triangle K/V traffic that
    # the in-kernel block_live check alone still paid bandwidth for.
    qmax = jnp.max(q_pos_p.reshape(B, nq, block_q), axis=2)
    kmin = jnp.min(kv_pos_p.reshape(B, nk, block_k), axis=2)
    attendable = kmin[:, None, :] <= qmax[:, :, None]  # [B, nq, nk]
    kv_bound = 1 + jnp.max(
        jnp.where(
            attendable, jnp.arange(nk, dtype=jnp.int32)[None, None, :], -1
        ),
        axis=2,
    )  # [B, nq], values in [0, nk]
    kv_bound_flat = kv_bound.reshape(B * nq)

    # Index maps take trailing *_ so the same lambdas serve both prefetch
    # layouts (kv_bound alone, or kv_bound + dropout seed).
    def _clamp_ki(b, qi, ki, bound):
        return jnp.minimum(ki, jnp.maximum(bound[b * nq + qi] - 1, 0))

    def q_row(b, h, qi, ki, bound, *_):
        return (b, h, qi, 0)

    out_shape = jax.ShapeDtypeStruct((B, H, Tp, d), q.dtype)
    out_spec = pl.BlockSpec((1, 1, block_q, d), q_row)
    if need_lse:
        # Narrow-lane row logsumexp for the backward kernels.
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((B, H, Tp, 1), jnp.float32),
        )
        out_spec = (
            out_spec,
            pl.BlockSpec((1, 1, block_q, 1), q_row),
        )
    in_specs = [
        pl.BlockSpec(
            (1, block_q, 1), lambda b, h, qi, ki, bound, *_: (b, qi, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_k),
            lambda b, h, qi, ki, bound, *_: (
                b, 0, _clamp_ki(b, qi, ki, bound)
            ),
        ),
        pl.BlockSpec((1, 1, block_q, d), q_row),
        pl.BlockSpec(
            (1, 1, block_k, d),
            lambda b, h, qi, ki, bound, *_: (
                b, h // group, _clamp_ki(b, qi, ki, bound), 0
            ),
        ),
        pl.BlockSpec(
            (1, 1, block_k, d),
            lambda b, h, qi, ki, bound, *_: (
                b, h // group, _clamp_ki(b, qi, ki, bound), 0
            ),
        ),
    ]
    operands = [q_pos_r, kv_pos_r, qt, kt, vt]
    if quantized:
        # Narrow-sublane per-slot scale views [B, KVH, 1, Sp] — free
        # expand_dims, blocked along the kv axis like kv_pos.
        def _scale_plane(s):
            st = _pad_to(jnp.moveaxis(s, 2, 1).astype(jnp.float32), 2, block_k)
            return st[:, :, None, :]

        scale_spec = pl.BlockSpec(
            (1, 1, 1, block_k),
            lambda b, h, qi, ki, bound, *_: (
                b, h // group, 0, _clamp_ki(b, qi, ki, bound)
            ),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [_scale_plane(k_scale), _scale_plane(v_scale)]
    prefetch = [kv_bound_flat]
    if with_dropout:
        prefetch.append(_normalize_seed(dropout_seed))
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, with_lse=need_lse,
            quantized=quantized, dropout_rate=dropout_rate,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(prefetch),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        # batch/head/q-block are independent ("parallel"); only the k sweep
        # carries state through scratch ("arbitrary").  Without this hint
        # Mosaic treats the whole grid as sequential and cannot pipeline
        # block DMA against compute — measured ~4x slower at 16k context.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
            # The default 16 MiB scoped-vmem budget blocks the larger
            # tiles (s lives at [block_q, block_k] fp32); v5e VMEM is
            # 128 MiB, and 64 MiB leaves ample room for the pipeline's
            # double buffers while unlocking (1024, 4096)-class tiles —
            # fewer grid steps, less per-step overhead.
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(*prefetch, *operands)
    if need_lse:
        out, lse = out
        return jnp.swapaxes(out[:, :, :T, :], 1, 2), lse
    return jnp.swapaxes(out[:, :, :T, :], 1, 2)  # [B, T, H, d]


# ---------------------------------------------------------------------------
# Backward: blockwise dQ / dK / dV with recomputed probabilities.
#
# Standard flash-attention backward split into two kernels so each output
# has a clean accumulation sweep (never an S×S tensor in memory):
#   * dQ kernel: grid (B, H, nq, nk) — for each q block, sweep kv blocks,
#     accumulating dQ_i += scale · dS_ij · K_j.
#   * dK/dV kernel: grid (B, H, nk, nq) — for each kv block, sweep q
#     blocks, accumulating dV_j += P_ijᵀ · dO_i and
#     dK_j += scale · dS_ijᵀ · Q_i.
# with P = exp(S − lse) rebuilt per tile from the forward's saved row
# logsumexp, dP = dO · Vᵀ, D = rowsum(dO ∘ O), dS = P ∘ (dP − D).
#
# GQA needs no extra handling: the public wrapper packs the `group` query
# heads of each KV head into the row axis before the custom_vjp boundary,
# so these kernels always see H == KVH and the sum over a KV head's query
# group happens naturally in the q-row sweep of the dK/dV kernel.
# ---------------------------------------------------------------------------


def _flash_dq_kernel(
    *args, scale: float, dropout_rate: float = 0.0,
):
    # With dropout a [2] uint32 seed_ref leads; lse_ref/delta_ref are
    # narrow-lane [1, 1, bq, 1] rows.
    if dropout_rate > 0.0:
        seed_ref, *args = args
    (q_pos_ref, kv_pos_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
     dq_ref, dq_acc) = args
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    qp = q_pos_ref[0, :, :1]  # [bq, 1]
    kp = kv_pos_ref[0, :1, :]  # [1, bk] (+INT_MAX on padding slots)
    block_live = jnp.min(kp) <= jnp.max(qp)

    def _dq_body(ragged):
        """Sub-tiled dQ tile update (r5).  Unlike the forward there is no
        online-softmax state between sub-tiles — lse is FIXED — so the
        nsub chains (dot -> exp -> ds -> dot) are fully independent and
        Mosaic overlaps sub-tile i's VPU work with i±1's dots.  With
        ``ragged`` (diagonal-crossing tiles, triangle-safety-guarded by
        the caller like the forward's tri body), k sub-tile i computes
        only query rows [i·rq:] — on a causal crossing tile the uniform
        body burned ~50% of its dots on fully-masked rows, which capped
        useful MXU at ~45% at training scale (S=2048) even though the
        MXU was ~90% busy; tile-size sweeps could not fix it (smaller
        tiles hit a ~4.5 µs/step grid-overhead floor).
        """
        qb, gb = q_ref[0, 0], g_ref[0, 0]
        bq = qb.shape[0]
        bk = k_ref.shape[2]
        nsub = _KSUB if (bk % _KSUB == 0 and bk > _KSUB) else 1
        ksub = bk // nsub
        rq = bq // nsub if ragged else 0
        # Full-width mask compare once — narrow [1, ksub] sub-slices of
        # the 1-row position plane hit unsupported Mosaic layouts (the
        # same trap the forward documents); 2-D slices of the [bq, bk]
        # compare are fine.
        allowed = kp <= qp
        lse_row = lse_ref[0, 0][:, :1]
        delta_row = delta_ref[0, 0][:, :1]
        inv = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else None
        c_parts = []
        for i in range(nsub):
            cols = slice(i * ksub, (i + 1) * ksub)
            r0 = i * rq  # 0 when not ragged
            kb_i = k_ref[0, 0, cols, :]
            s_i = jax.lax.dot_general(
                qb[r0:], kb_i, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            p_i = jnp.where(
                allowed[r0:, cols], jnp.exp(s_i - lse_row[r0:]), 0.0
            )
            dp_i = jax.lax.dot_general(
                gb[r0:], v_ref[0, 0, cols, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout_rate > 0.0:
                # Forward: out = (D ∘ w) V with w = softmax(s), D the
                # inverted-dropout mask.  Chain rule gives dw = D ∘ dp,
                # and the softmax Jacobian's weighted sum
                # Σ_k w_k (D_k dp_k) is exactly rowsum(dO ∘ O) — the
                # SAME delta as the no-dropout case — so only dp needs
                # masking.  The mask is rebuilt bit-identically from
                # GLOBAL element offsets (tiling-independent hash).
                keep = _dropout_keep(
                    seed_ref[0], seed_ref[1], bi, hi,
                    qi * bq + r0, ki * bk + i * ksub,
                    bq - r0, ksub, dropout_rate,
                )
                dp_i = jnp.where(keep, dp_i, 0.0) * inv
            ds_i = p_i * (dp_i - delta_row[r0:]) * scale
            c_parts.append(jax.lax.dot_general(
                ds_i.astype(kb_i.dtype), kb_i, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))
        if not ragged:
            acc = c_parts[0]
            for c_i in c_parts[1:]:
                acc = acc + c_i
            dq_acc[:] += acc
        else:
            # Row block j collects contributions from sub-tiles i <= j
            # (c_parts[i] starts at global row i*rq).
            for j in range(nsub):
                rows = slice(j * rq, (j + 1) * rq)
                add = None
                for i in range(j + 1):
                    piece = c_parts[i][(j - i) * rq:(j - i + 1) * rq]
                    add = piece if add is None else add + piece
                dq_acc[rows] += add

    tri_ok, safe = _tri_gate(qp, kp, q_ref.shape[2], k_ref.shape[2])
    if tri_ok:
        @pl.when(block_live & safe)
        def _compute_tri():
            _dq_body(ragged=True)

        @pl.when(block_live & jnp.logical_not(safe))
        def _compute():
            _dq_body(ragged=False)
    else:
        @pl.when(block_live)
        def _compute():
            _dq_body(ragged=False)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(
    *args, scale: float, dropout_rate: float = 0.0,
):
    if dropout_rate > 0.0:
        seed_ref, *args = args
    (q_pos_ref, kv_pos_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dk_acc, dv_acc) = args
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    bi, hi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qp = q_pos_ref[0, :, :1]  # [bq, 1]
    kp = kv_pos_ref[0, :1, :]  # [1, bk] (+INT_MAX on padding slots)
    block_live = jnp.min(kp) <= jnp.max(qp)

    def _dkv_body(ragged):
        """Sub-tiled dK/dV tile update (r5), over the Q-ROW axis (the
        kernel's within-tile reduction axis): lse is fixed, so the nsub
        chains are fully independent and their dots/VPU work pipeline —
        see the dQ kernel note.  With ``ragged`` (diagonal-crossing
        tiles), q-row sub-tile i computes only kv columns
        [0:(i+1)·csub] — GROWING widths, the column-side mirror of the
        dQ kernel's shrinking rows — and contributions land per column
        block through static scratch slices."""
        kb, vb = k_ref[0, 0], v_ref[0, 0]
        bq = q_ref.shape[2]
        bk = kb.shape[0]
        nsub = (
            _KSUB
            if (bq % _KSUB == 0 and bq > _KSUB
                and (bq // _KSUB) % _SUBLANES == 0)
            else 1
        )
        qsub = bq // nsub
        csub = bk // nsub if ragged else 0
        # Full-width compare + full narrow-lane loads once; 2-D row
        # slices of them are Mosaic-safe (see the dQ kernel note).
        allowed = kp <= qp
        lse_rows = lse_ref[0, 0][:, :1]
        delta_rows = delta_ref[0, 0][:, :1]
        inv = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else None
        dv_parts = []  # [(i+1)*csub, d] when ragged, else [bk, d]
        dk_parts = []
        for i in range(nsub):
            rows = slice(i * qsub, (i + 1) * qsub)
            cols = slice(0, (i + 1) * csub) if ragged else slice(0, bk)
            wk = (i + 1) * csub if ragged else bk
            qb_i = q_ref[0, 0, rows, :]
            gb_i = g_ref[0, 0, rows, :]
            s_i = jax.lax.dot_general(
                qb_i, kb[cols], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [qsub, wk]
            p_i = jnp.where(
                allowed[rows, cols], jnp.exp(s_i - lse_rows[rows]), 0.0
            )
            if dropout_rate > 0.0:
                # Same global element offsets as the forward/dQ kernels —
                # NOTE the grid here is (B, H, nk, nq), so qi/ki swap
                # program ids.
                keep = _dropout_keep(
                    seed_ref[0], seed_ref[1], bi, hi,
                    qi * bq + i * qsub, ki * bk, qsub, wk, dropout_rate,
                )
                p_v = jnp.where(keep, p_i, 0.0) * inv
                dp_mask = lambda dp, _k=keep: jnp.where(_k, dp, 0.0) * inv
            else:
                p_v = p_i
                dp_mask = lambda dp: dp
            # dV_j += (D ∘ P)_ijᵀ dO_i: contract the q-row axis.
            dv_parts.append(jax.lax.dot_general(
                p_v.astype(gb_i.dtype), gb_i, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))
            dp_i = dp_mask(jax.lax.dot_general(
                gb_i, vb[cols], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))
            ds_i = p_i * (dp_i - delta_rows[rows]) * scale
            dk_parts.append(jax.lax.dot_general(
                ds_i.astype(qb_i.dtype), qb_i, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))
        if not ragged:
            dv_add = dv_parts[0]
            dk_add = dk_parts[0]
            for dv_i, dk_i in zip(dv_parts[1:], dk_parts[1:]):
                dv_add = dv_add + dv_i
                dk_add = dk_add + dk_i
            dv_acc[:] += dv_add
            dk_acc[:] += dk_add
        else:
            # Column block c collects contributions from q sub-tiles
            # i >= c (sub-tile i's parts cover columns [0:(i+1)*csub]).
            for c in range(nsub):
                cols_c = slice(c * csub, (c + 1) * csub)
                dv_add = None
                dk_add = None
                for i in range(c, nsub):
                    dv_p = dv_parts[i][cols_c]
                    dk_p = dk_parts[i][cols_c]
                    dv_add = dv_p if dv_add is None else dv_add + dv_p
                    dk_add = dk_p if dk_add is None else dk_add + dk_p
                dv_acc[cols_c] += dv_add
                dk_acc[cols_c] += dk_add

    # One shared gate: the dK/dV skip set (q sub-tile i × column suffix
    # past i) reduces to the same pairwise max(qp block) < min(kp block)
    # condition as the forward/dQ row-skips — see _tri_gate.
    tri_ok, safe = _tri_gate(qp, kp, q_ref.shape[2], k_ref.shape[2])
    if tri_ok:
        @pl.when(block_live & safe)
        def _compute_tri():
            _dkv_body(ragged=True)

        @pl.when(block_live & jnp.logical_not(safe))
        def _compute():
            _dkv_body(ragged=False)
    else:
        @pl.when(block_live)
        def _compute():
            _dkv_body(ragged=False)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, q_pos, kv_pos, out, lse, g, block_q, block_k, interpret,
    dropout_rate=0.0, dropout_seed=None,
):
    """Blockwise VJP.  Memory is O(S·d) per head (plus narrow-lane
    lse/Δ rows) — replacing the r1 dense-recompute fallback whose backward
    materialized the full [B, H, T, S] score matrix."""
    B, T, H, d = q.shape
    S = k.shape[1]
    assert k.shape[2] == H, "custom_vjp operates on GQA-packed operands"
    scale = 1.0 / (d ** 0.5)
    interpret = _resolve_interpret(interpret)
    block_q, block_k = _clamp_blocks(T, S, block_q, block_k, interpret)
    with_dropout = dropout_rate > 0.0
    seed_ops = (
        (_normalize_seed(dropout_seed),) if with_dropout else ()
    )

    # Δ = rowsum(dO ∘ O): tiny elementwise pass outside the kernels.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, T, H]

    qt = _pad_to(jnp.swapaxes(q, 1, 2), 2, block_q)  # [B, H, Tp, d]
    kt = _pad_to(jnp.swapaxes(k, 1, 2), 2, block_k)  # [B, H, Sp, d]
    vt = _pad_to(jnp.swapaxes(v, 1, 2), 2, block_k)
    gt = _pad_to(jnp.swapaxes(g, 1, 2), 2, block_q)  # dO; pad rows are 0 so
    #   padded-q contributions to every gradient vanish (Δ is 0 there too).
    q_pos_p = _pad_to(q_pos.astype(jnp.int32), 1, block_q)
    # Same +INT_MAX invalid-slot remap as the forward (single-compare mask).
    kv_pos_p = _pad_to(kv_pos.astype(jnp.int32), 1, block_k, value=-1)
    kv_pos_p = jnp.where(
        kv_pos_p < 0, jnp.iinfo(jnp.int32).max, kv_pos_p
    )
    Tp, Sp = qt.shape[2], kt.shape[2]
    nq, nk = Tp // block_q, Sp // block_k
    q_pos_r = q_pos_p[:, :, None]
    kv_pos_r = kv_pos_p[:, None, :]
    delta_r = _pad_to(jnp.moveaxis(delta, 2, 1), 2, block_q)[..., None]
    # lse comes from the forward already padded, narrow-lane [B, H, Tp, 1].

    pos_specs = [
        pl.BlockSpec((1, block_q, 1), lambda b, h, qi, ki, *_: (b, qi, 0)),
        pl.BlockSpec((1, 1, block_k), lambda b, h, qi, ki, *_: (b, 0, ki)),
    ]
    q_row_specs = [
        pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h, qi, ki, *_: (b, h, qi, 0)
        ),
    ]
    kv_specs = [
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki, *_: (b, h, ki, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki, *_: (b, h, ki, 0)
        ),
    ]
    row_aux_specs = [
        pl.BlockSpec(
            (1, 1, block_q, 1), lambda b, h, qi, ki, *_: (b, h, qi, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_q, 1), lambda b, h, qi, ki, *_: (b, h, qi, 0)
        ),
    ]

    def _call(kernel, grid, in_specs, out_specs, out_shape, scratch_shapes):
        # Dropout threads the [1] uint32 seed as a scalar-prefetch operand
        # (the mask hash needs it before tile compute); the no-dropout
        # trace is unchanged.
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=len(seed_ops),
                grid=grid,
                in_specs=in_specs,
                out_specs=out_specs,
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            compiler_params=_CompilerParams(
                dimension_semantics=(
                    "parallel", "parallel", "parallel", "arbitrary"
                ),
                # Same raised scoped-vmem budget as the forward: the
                # (2048, 2048) default tiles exceed the 16 MiB default
                # here too (s/p intermediates at [block_q, block_k] fp32).
                vmem_limit_bytes=64 * 1024 * 1024,
            ),
            interpret=interpret,
        )

    dq = _call(
        functools.partial(
            _flash_dq_kernel, scale=scale, dropout_rate=dropout_rate
        ),
        (B, H, nq, nk),
        pos_specs + q_row_specs + kv_specs + q_row_specs + row_aux_specs,
        pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h, qi, ki, *_: (b, h, qi, 0)
        ),
        jax.ShapeDtypeStruct((B, H, Tp, d), q.dtype),
        [pltpu.VMEM((block_q, d), jnp.float32)],
    )(*seed_ops, q_pos_r, kv_pos_r, qt, kt, vt, gt, lse, delta_r)

    # dK/dV kernel: kv blocks third, q sweep innermost.
    def qrow(b, h, ki, qi, *_):
        return (b, h, qi, 0)

    def kvrow(b, h, ki, qi, *_):
        return (b, h, ki, 0)

    dkv_specs = [
        pl.BlockSpec((1, block_q, 1), lambda b, h, ki, qi, *_: (b, qi, 0)),
        pl.BlockSpec((1, 1, block_k), lambda b, h, ki, qi, *_: (b, 0, ki)),
        pl.BlockSpec((1, 1, block_q, d), qrow),
        pl.BlockSpec((1, 1, block_k, d), kvrow),
        pl.BlockSpec((1, 1, block_k, d), kvrow),
        pl.BlockSpec((1, 1, block_q, d), qrow),
        pl.BlockSpec((1, 1, block_q, 1), qrow),
        pl.BlockSpec((1, 1, block_q, 1), qrow),
    ]
    dk, dv = _call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, dropout_rate=dropout_rate
        ),
        (B, H, nk, nq),
        dkv_specs,
        (
            pl.BlockSpec((1, 1, block_k, d), kvrow),
            pl.BlockSpec((1, 1, block_k, d), kvrow),
        ),
        (
            jax.ShapeDtypeStruct((B, H, Sp, d), k.dtype),
            jax.ShapeDtypeStruct((B, H, Sp, d), v.dtype),
        ),
        [
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )(*seed_ops, q_pos_r, kv_pos_r, qt, kt, vt, gt, lse, delta_r)

    dq = jnp.swapaxes(dq[:, :, :T, :], 1, 2)
    dk = jnp.swapaxes(dk[:, :, :S, :], 1, 2)
    dv = jnp.swapaxes(dv[:, :, :S, :], 1, 2)
    return dq, dk, dv
