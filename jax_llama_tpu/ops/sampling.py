"""Token sampling: greedy, temperature, nucleus (top-p), top-k.

The reference delegates sampling to HF ``FlaxGenerationMixin`` (its
``generation.py:28-41`` passes ``GenerationConfig(do_sample=temperature!=0,
temperature, top_p)``).  Here sampling is owned natively and fully jittable:
all ops are shape-static so they live happily inside the decode
``lax.while_loop``.

Greedy-vs-sampled is decided at *trace* time (temperature is a Python float
in the generation config, like the reference's ``do_sample`` derivation), so
the greedy path compiles to a pure argmax with no RNG traffic.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .attention import NEG_INF


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax over the vocab. logits: [..., V] -> int32 [...]."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def top_p_filter(logits: jnp.ndarray, top_p: float) -> jnp.ndarray:
    """Mask logits outside the nucleus: smallest set with cum-prob >= top_p.

    Keeps at least one token.  logits: [..., V] fp32.
    """
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # A sorted position is kept while the cumulative mass *before* it is < p.
    keep_sorted = (cum - sorted_probs) < top_p
    # Threshold logit = smallest kept logit; everything >= it is in the
    # nucleus in original index space (ties conservatively included).  The
    # minimum with the max logit guarantees the best token survives even at
    # top_p == 0.0 (where keep_sorted is all-False and the min is +inf).
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    threshold = jnp.minimum(threshold, jnp.max(logits, axis=-1, keepdims=True))
    return jnp.where(logits >= threshold, logits, NEG_INF)


def top_k_filter(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Mask all but the top_k logits. top_k is static."""
    kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
    return jnp.where(logits >= kth, logits, NEG_INF)


def warped_probs(
    logits: jnp.ndarray,
    temperature: float,
    top_p: Optional[float] = None,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """The exact distribution ``sample`` draws from, as probabilities.

    Speculative decoding's accept/resample math needs p (target) and q
    (draft) as full distributions under the SAME warping the sampler uses —
    acceptance ``min(1, p/q)`` and the residual ``norm(relu(p - q))`` are
    only distribution-preserving if both sides are post-warp.
    """
    assert temperature != 0.0, "greedy has no sampling distribution"
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p is not None and top_p < 1.0:
        logits = top_p_filter(logits, top_p)
    return jax.nn.softmax(logits, axis=-1)


def stop_token_hits(
    tokens: jnp.ndarray, stop_table: jnp.ndarray
) -> jnp.ndarray:
    """Per-row stop-token membership — the ON-DEVICE half of serving's
    stop detection, so a fused multi-token decode chunk can fold
    finished rows out of its active mask without a host round-trip.

    tokens: [B] int32 pending tokens, or [B, T] token blocks (the fused
    speculative chunk checks a whole round's accepted drafts at once).
    Negative values (the serving layer's non-finite sentinel, or stale
    inactive-row state) never match — the guard below keeps them from
    colliding with the table's -1 padding.
    stop_table: [B, S] int32, each row's stop set right-padded with -1
    (rows with fewer than S stops, or none at all).
    Returns bool of ``tokens``' shape, True where the token is one of
    its row's stops.
    """
    tab = stop_table[:, None, :] if tokens.ndim == 2 else stop_table
    return jnp.any(
        (tokens[..., None] >= 0) & (tokens[..., None] == tab), axis=-1
    )


def sample(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_p: Optional[float] = None,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """Sample next tokens from [..., V] logits.

    temperature/top_p/top_k are Python scalars (static): temperature == 0.0
    selects the greedy path at trace time.
    """
    if temperature == 0.0:
        return greedy(logits)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p is not None and top_p < 1.0:
        logits = top_p_filter(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
