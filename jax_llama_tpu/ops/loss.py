"""Fused (chunked) softmax cross-entropy over the LM head.

The dense loss path materializes the full ``[B, T-1, V]`` fp32
log-softmax on top of the forward's logits — ~1 GB at the bench training
geometry (B=4, S=2048, V=32000) plus the VJP's recompute, all of it HBM
round-trips that bound training MFU.  The reference has no training loop
at all (SURVEY.md §5); this framework claims training as first-class, so
the loss has to be TPU-shaped too: take the head matmul CHUNKWISE, fold
the row logsumexp + target-logit gather into each chunk, and never hold
more than one ``[chunk, V]`` logits tile.

Memory: O(chunk · V) instead of O(B · T · V) — with the default chunk,
~65 MB of transient fp32 per step instead of ~1.5 GB of materialized
logits + log-softmax.  Backward: each chunk is ``jax.checkpoint``ed, so
the VJP recomputes the chunk's logits and XLA derives the standard
``(softmax − onehot) · g`` cotangent per chunk — the extra recompute is
one head matmul (~2% of the step's matmul FLOPs at bench geometry),
bought against the gigabyte of saved residuals.

The chunk axis is the FLATTENED (batch · position) row axis: loss rows
are independent, so chunking needs no alignment with batch or sequence
structure, and padding to a chunk multiple is a weight-0 row that
contributes exactly nothing to the value or any gradient.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .quant import matmul as qeinsum

# Rows per chunk.  [chunk, V] fp32 transient = 512·32000·4 ≈ 65 MB at the
# bench vocab.  Swept on chip (xplane device time, fwd+grad at bench
# geometry N=8188, V=32000): 256 → 36.0 ms (per-chunk overhead × 32
# steps), 512 → 27.1 ms, 1024/2048/4096 → ~30 ms; 512 wins and also
# keeps the transient smallest of the plateau.
CE_CHUNK = 512


def chunked_softmax_xent(
    h: jnp.ndarray,
    head,
    targets: jnp.ndarray,
    weights: jnp.ndarray,
    *,
    head_transposed: bool = False,
    chunk: int = CE_CHUNK,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted next-token NLL without materializing [N, V] logits.

    Args:
      h: [N, D] post-final-norm hidden rows (activation dtype).
      head: LM head weights — [D, V], or [V, D] with
        ``head_transposed=True`` (the tied-embedding layout; the
        transpose is folded into the einsum, never materialized).
        QuantizedTensor is handled via ``ops.quant.matmul``.
      targets: [N] int32 target token ids.
      weights: [N] fp32 per-row loss weights (0 = ignore row).
      chunk: rows per scan step.

    Returns:
      (total_nll, total_weight) — both fp32 scalars;
      ``total_nll / max(total_weight, 1)`` is the masked mean the dense
      path computes.
    """
    N, D = h.shape
    nc = -(-N // chunk)
    pad = nc * chunk - N
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        weights = jnp.pad(weights, (0, pad))
    hs = h.reshape(nc, chunk, D)
    ts = targets.reshape(nc, chunk)
    ws = weights.reshape(nc, chunk).astype(jnp.float32)
    eq = "td,vd->tv" if head_transposed else "td,dv->tv"

    def body(carry, xs):
        hc, tc, wc = xs
        # fp32 accumulation in the MXU output — the same islanding as
        # lm_head_logits, so the fused loss matches the dense path to
        # reduction-order noise.
        logits = qeinsum(
            hc, head, eq, preferred_element_type=jnp.float32
        )
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        tot, wsum = carry
        return (
            tot + jnp.sum((lse - tgt) * wc),
            wsum + jnp.sum(wc),
        ), None

    (tot, wsum), _ = lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.float32(0.0)),
        (hs, ts, ws),
    )
    return tot, wsum
