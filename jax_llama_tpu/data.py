"""Training input pipeline: document packing + sharded host→device feed.

The reference has no training and therefore no data loader (SURVEY.md: the
repo is inference-only).  Training here is first-class, so the input side
is too — the TPU-idiomatic shape: fixed-size [B, T] batches (static shapes
keep one compiled train_step), greedy document packing with EOS separators
(no padding waste), a loss mask that excludes padding targets, and
`jax.device_put` with the batch sharded over the mesh's data axes so each
host/device group receives only its slice.

    tok = LLaMA3Tokenizer("tokenizer.model")
    docs = (tok.encode(line, bos=True, eos=True) for line in corpus)
    for batch in batches(docs, batch_size=8, seq_len=2048, pad_id=tok.pad_id):
        state, loss = train_step(state, shard_batch(batch, mesh).tokens, ...)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Iterator, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Batch:
    """One packed training batch.

    tokens:    [B, T] int32.
    loss_mask: [B, T] bool, query-position-indexed — loss_mask[t] gates
               the loss term predicting token t+1 from position t; False
               where that target would be padding.  Cross-document
               EOS→BOS transitions are trained on (the standard packed-LM
               convention); `train.lm_loss` consumes this same indexing.
    """

    tokens: np.ndarray
    loss_mask: np.ndarray


def pack_documents(
    docs: Iterable[Sequence[int]],
    seq_len: int,
    pad_id: int = 0,
) -> Iterator[Batch]:
    """Greedily pack token sequences into fixed [seq_len] rows.

    Documents are concatenated back-to-back; a document longer than
    ``seq_len`` spans multiple rows (its continuation keeps contributing
    loss).  The final partial row is padded with ``pad_id`` and those
    positions are masked out of the loss.  Yields one row at a time;
    callers batch them (see ``batches``).
    """
    if seq_len < 2:
        raise ValueError("seq_len must be >= 2 (need a target per position)")
    buf: List[int] = []
    for doc in docs:
        buf.extend(int(t) for t in doc)
        while len(buf) >= seq_len:
            row = np.asarray(buf[:seq_len], dtype=np.int32)
            del buf[:seq_len]
            yield Batch(
                tokens=row,
                loss_mask=np.ones((seq_len,), dtype=bool),
            )
    if buf:
        row = np.full((seq_len,), pad_id, dtype=np.int32)
        row[: len(buf)] = buf
        mask = np.zeros((seq_len,), dtype=bool)
        # Positions 0..len(buf)-1 are real; the loss target of position i
        # is token i+1, so the last real position's target is padding —
        # mask it too.
        mask[: max(len(buf) - 1, 0)] = True
        del buf[:]
        yield Batch(tokens=row, loss_mask=mask)


def batches(
    docs: Iterable[Sequence[int]],
    batch_size: int,
    seq_len: int,
    pad_id: int = 0,
    drop_remainder: bool = True,
    seed: Optional[int] = None,
    shuffle_buffer: int = 0,
) -> Iterator[Batch]:
    """Assemble packed rows into [batch_size, seq_len] batches.

    ``shuffle_buffer > 0`` enables buffered shuffling of packed rows with a
    deterministic RNG (``seed``) — streaming-friendly (bounded memory),
    reproducible across runs.
    """
    rows = pack_documents(docs, seq_len, pad_id)
    if shuffle_buffer > 0:
        rows = _buffered_shuffle(rows, shuffle_buffer, seed or 0)

    toks: List[np.ndarray] = []
    masks: List[np.ndarray] = []
    for row in rows:
        toks.append(row.tokens)
        masks.append(row.loss_mask)
        if len(toks) == batch_size:
            yield Batch(tokens=np.stack(toks), loss_mask=np.stack(masks))
            toks, masks = [], []
    if toks and not drop_remainder:
        # Static shapes: pad the last batch up to batch_size with fully
        # masked rows rather than emitting a ragged batch.
        pad_rows = batch_size - len(toks)
        toks.extend(
            np.full((seq_len,), pad_id, dtype=np.int32) for _ in range(pad_rows)
        )
        masks.extend(np.zeros((seq_len,), dtype=bool) for _ in range(pad_rows))
        yield Batch(tokens=np.stack(toks), loss_mask=np.stack(masks))


def _buffered_shuffle(rows: Iterator[Batch], buffer: int, seed: int) -> Iterator[Batch]:
    rng = np.random.RandomState(seed)
    pool: List[Batch] = []
    for row in rows:
        pool.append(row)
        if len(pool) >= buffer:
            i = rng.randint(len(pool))
            pool[i], pool[-1] = pool[-1], pool[i]
            yield pool.pop()
    rng.shuffle(pool)
    yield from pool


def shard_batch(batch: Batch, mesh: Any) -> Batch:
    """Place a host batch onto the mesh, batch dim over the data axes.

    Under multi-host JAX each process passes its *global* batch here;
    device_put with a NamedSharding hands every device only its shard.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(("data", "fsdp"), None))
    return Batch(
        tokens=jax.device_put(batch.tokens, sharding),
        loss_mask=jax.device_put(batch.loss_mask, sharding),
    )
