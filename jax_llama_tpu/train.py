"""Training: LM loss + optax train step, mesh-shardable.

The reference is inference-only (SURVEY.md: no optimizer, no training loop;
its ``gradient_checkpointing`` flag exists but nothing exercises it).  This
framework makes training a first-class capability: a masked next-token
cross-entropy loss and a jitted ``train_step`` that runs under any
data/fsdp/tensor mesh — gradients and optimizer states inherit the param
shardings, XLA inserts the DP/FSDP collectives.  ``config.remat=True``
enables per-block rematerialization (jax.checkpoint) for memory-bound
training.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from .config import LLaMAConfig
from .models.llama import forward


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["params", "opt_state", "step"],
    meta_fields=[],
)
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 0,
    total_steps: Optional[int] = None,
) -> optax.GradientTransformation:
    """AdamW with the usual LLM hyperparameters: global-norm clipping and an
    optional linear-warmup + cosine-decay schedule."""
    if warmup_steps or total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=learning_rate,
            warmup_steps=max(warmup_steps, 1),
            decay_steps=max(total_steps or warmup_steps * 10, 2),
        )
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def init_train_state(params: Any, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(
        params=params,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
    )


def lm_loss(
    params: Any,
    tokens: jnp.ndarray,
    config: LLaMAConfig,
    loss_mask: Optional[jnp.ndarray] = None,
    dropout_rng: Optional[jnp.ndarray] = None,
    fused: bool = True,
) -> jnp.ndarray:
    """Masked next-token cross-entropy.

    tokens: [B, T] int32; position t predicts token t+1.
    loss_mask: optional [B, T] bool, query-position-indexed: mask[:, t]
      gates the loss term predicting token t+1 from position t (the
      convention `data.pack_documents` emits; the final position has no
      in-row target, so mask[:, -1] is never consumed).  Defaults to all
      positions.
    fused: take the LM head + softmax cross-entropy CHUNKWISE
      (``ops.loss.chunked_softmax_xent``) over the forward's last hidden
      state — never materializing the [B, T, V] logits or the fp32
      log-softmax (~1.5 GB at B=4 × S=2048 × V=32000) the dense path
      holds.  False runs the dense reference path (same value to
      reduction-order noise; kept as the parity oracle).
    """
    B, T = tokens.shape
    targets = tokens[:, 1:]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    # Forward over the full T (not T-1): sequence-parallel meshes need the
    # model-visible length to stay divisible by the seq axis; the final
    # position's loss rows are simply dropped.
    if fused:
        from .ops.loss import chunked_softmax_xent

        _, _, aux = forward(
            params, tokens, positions, config, dropout_rng=dropout_rng,
            compute_logits=False, output_last_hidden=True,
        )
        h = aux.last_hidden_state[:, :-1]  # [B, T-1, D] post-final-norm
        if config.tie_word_embeddings:
            head, head_t = params["embed"]["embedding"], True
        else:
            head, head_t = params["lm_head"], False
        w = (
            loss_mask[:, :-1].astype(jnp.float32)
            if loss_mask is not None
            else jnp.ones((B, T - 1), jnp.float32)
        )
        tot, wsum = chunked_softmax_xent(
            h.reshape(B * (T - 1), -1),
            head,
            targets.reshape(-1),
            w.reshape(-1),
            head_transposed=head_t,
        )
        return tot / jnp.maximum(wsum, 1.0)
    logits, _ = forward(
        params, tokens, positions, config, dropout_rng=dropout_rng
    )
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[:, :, None], axis=-1)[..., 0]
    if loss_mask is not None:
        # Query-indexed: mask[:, t] aligns with nll[:, t] (the loss for
        # target tokens[:, t+1]); drop the final, target-less position.
        m = loss_mask[:, :-1].astype(jnp.float32)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)


@functools.partial(
    jax.jit,
    static_argnames=("config", "optimizer", "mesh"),
    donate_argnames=("state",),
)
def train_step(
    state: TrainState,
    tokens: jnp.ndarray,
    config: LLaMAConfig,
    optimizer: optax.GradientTransformation,
    loss_mask: Optional[jnp.ndarray] = None,
    mesh=None,
    dropout_rng: Optional[jnp.ndarray] = None,
) -> Tuple[TrainState, jnp.ndarray]:
    """One optimizer step.  `optimizer` must be a hashable static (module-
    level) GradientTransformation; under a mesh the donated state keeps
    params/opt-state sharded in place.

    `mesh` must be passed explicitly (it is part of the jit cache key):
    sharding constraints and ring attention read the active mesh at trace
    time, so relying on the caller's thread-local ``use_mesh`` would bake
    whatever mesh was active at first call into the cached executable.
    """
    from .parallel.mesh import current_mesh, use_mesh

    if mesh is None and current_mesh() is not None:
        # Entering use_mesh(None) here would silently disable every
        # sharding constraint the ambient mesh was meant to drive; fail
        # loudly instead of training unsharded.
        raise ValueError(
            "train_step: pass mesh= explicitly (it is part of the jit "
            "cache key); an ambient use_mesh(...) context is not seen by "
            "the compiled executable on later calls"
        )
    with use_mesh(mesh):
        # One base key serves the whole run: folding in the step count
        # gives every step fresh masks without the caller re-splitting.
        step_rng = (
            jax.random.fold_in(dropout_rng, state.step)
            if dropout_rng is not None else None
        )
        loss, grads = jax.value_and_grad(lm_loss)(
            state.params, tokens, config, loss_mask, step_rng
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss
