"""Continuous batching: slot-based serving over per-row cache offsets.

Beyond the reference's capability surface (its only serving mode is one
batch of same-length prompts through `LLaMA.generate`, reference
``generation.py:22-45``) — a production decode loop where requests enter
and leave a fixed pool of batch slots independently, vLLM-style, so the
TPU never idles waiting for the longest generation in a batch.

TPU-native mechanics:
  * **Static shapes everywhere.**  The pool is ``n_slots`` rows; every
    decode step is one jitted [B=n_slots, T=1] forward.  Admission runs a
    B=1 prefill whose length is bucketed to powers of two, so the jit
    cache holds O(log max_prompt) prefill programs + 1 decode program.
  * **Per-row cache offsets.**  Each slot writes its KV at its own
    ``cache.index[b]`` (scatter, not dynamic-update-slice) and masking is
    purely positional, so rows at different sequence lengths coexist in
    one cache with no synchronization (models.llama KVCache.per_row_index).
  * **Idle slots cost nothing semantically**: they decode garbage that is
    positionally masked (pos -1) and their buffered tokens are never
    surfaced; their cache writes drop once they hit capacity.

Sampling policy (temperature/top-p/top-k) is pool-wide; per-request
policies are future work.  Use `engine.generate` for classic lockstep
batch generation and `spec_decode` for draft-accelerated decode.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .config import LLaMAConfig
from .engine import next_pow2, prompt_positions
from .models.llama import KVCache, forward, init_cache
from .ops.sampling import sample
from .parallel.mesh import use_mesh


@functools.partial(
    jax.jit,
    static_argnames=("config", "mesh", "temperature", "top_p", "top_k"),
    donate_argnames=("cache",),
)
def _decode_step(params, cache, tau, pos, active, rng, *, config,
                 temperature=0.0, top_p=None, top_k=None, mesh=None):
    """One [n_slots, 1] decode step (greedy or pool-wide sampling policy).

    tau: [B] current token per slot; pos: [B] its absolute position;
    active: [B] bool.  Inactive rows run masked (their writes carry pos -1
    and their sampled token is ignored by the host).
    """
    with use_mesh(mesh):
        positions = jnp.where(active, pos, -1)[:, None]
        logits, cache = forward(
            params, tau[:, None], positions, config, cache=cache,
            attn_mask=active[:, None],
        )
        nxt = sample(rng, logits[:, -1], temperature, top_p, top_k)
        return nxt.astype(jnp.int32), cache


@functools.partial(
    jax.jit,
    static_argnames=("config", "mesh", "temperature", "top_p", "top_k",
                     "prefill_chunk"),
    donate_argnames=("cache",),
)
def _insert_row(params, cache, row, prompt_tokens, prompt_mask, rng, *,
                config, temperature=0.0, top_p=None, top_k=None,
                prefill_chunk=None, mesh=None):
    """Prefill one request into slot ``row`` of the pool cache.

    prompt_tokens/prompt_mask: [1, P] left-padded (P bucketed by caller).
    Runs a B=1 prefill against a fresh single-row cache of the pool's
    capacity (optionally in fixed chunks, bounding activation memory for
    long prompts), then splices the row back — slot state never leaks
    between requests.  Returns (first sampled token, its position,
    updated cache).
    """
    with use_mesh(mesh):
        S = cache.max_len
        sub = init_cache(config, 1, max_len=S)
        positions = prompt_positions(prompt_mask)
        P = prompt_tokens.shape[1]
        chunk = prefill_chunk if prefill_chunk and prefill_chunk < P else P
        for start in range(0, P, chunk):
            end = min(start + chunk, P)
            logits, sub = forward(
                params, prompt_tokens[:, start:end],
                positions[:, start:end], config, cache=sub,
                attn_mask=prompt_mask[:, start:end],
                compute_logits=end >= P,
            )
        tau = sample(rng, logits[:, -1], temperature, top_p, top_k)
        tau = tau.astype(jnp.int32)[0]
        plen = jnp.sum(prompt_mask.astype(jnp.int32))

        def splice(dst, src, axis_b):
            start = (0,) * axis_b + (row,) + (0,) * (dst.ndim - axis_b - 1)
            return lax.dynamic_update_slice(dst, src, start)

        new = dataclasses.replace(
            cache,
            k=splice(cache.k, sub.k, 1),
            v=splice(cache.v, sub.v, 1),
            pos=splice(cache.pos, sub.pos, 0),
            index=cache.index.at[row].set(prompt_tokens.shape[1]),
        )
        if cache.quantized:
            new = dataclasses.replace(
                new,
                k_scale=splice(cache.k_scale, sub.k_scale, 1),
                v_scale=splice(cache.v_scale, sub.v_scale, 1),
            )
        return tau, plen, new


@dataclasses.dataclass
class _Slot:
    request_id: int
    emitted: List[int]
    max_new: int
    stop_tokens: frozenset


class ContinuousBatcher:
    """Host-side slot manager around the jitted step/insert programs.

    Usage:
        cb = ContinuousBatcher(params, config, n_slots=8, max_len=2048)
        rid = cb.submit([1, 5, 9, ...], max_new_tokens=128)
        while cb.pending():
            for request_id, token, done in cb.step():
                ...stream token to the caller...
    """

    def __init__(
        self,
        params: Any,
        config: LLaMAConfig,
        n_slots: int = 8,
        max_len: Optional[int] = None,
        stop_tokens: Tuple[int, ...] = (),
        temperature: float = 0.0,
        top_p: Optional[float] = None,
        top_k: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        seed: int = 0,
        mesh=None,
    ):
        if config.attn_impl not in ("xla", "auto"):
            raise ValueError(
                "continuous batching requires attn_impl 'xla' or 'auto' "
                "(per-row cache offsets run on the xla path)"
            )
        self.params = params
        self.config = config
        self.mesh = mesh
        self.n_slots = n_slots
        self.max_len = max_len or config.max_seq_len
        self.default_stop = frozenset(int(s) for s in stop_tokens)
        self.temperature = float(temperature)
        self.top_p = top_p
        self.top_k = top_k
        self.prefill_chunk = prefill_chunk
        self._rng = jax.random.PRNGKey(seed)

        base = init_cache(config, n_slots, max_len=self.max_len)
        self.cache = dataclasses.replace(
            base, index=jnp.zeros((n_slots,), jnp.int32)
        )
        self.tau = jnp.zeros((n_slots,), jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.active = jnp.zeros((n_slots,), bool)

        self.slots: Dict[int, Optional[_Slot]] = {
            b: None for b in range(n_slots)
        }
        self.queue: List[Tuple[int, List[int], int, frozenset]] = []
        self._next_id = 0

    # -- public API ---------------------------------------------------------

    def submit(
        self,
        prompt_tokens: List[int],
        max_new_tokens: int = 256,
        stop_tokens: Optional[Tuple[int, ...]] = None,
    ) -> int:
        """Queue a request; returns its id.  Tokens only — tokenize first."""
        if not prompt_tokens:
            raise ValueError("empty prompt")
        # Capacity must cover the BUCKETED prompt length: _admit pads the
        # prompt to the next power of two and the row's write offset starts
        # there, so checking the raw length would let bucketing silently
        # push decode writes past capacity (where they drop).
        bucketed = next_pow2(len(prompt_tokens))
        if bucketed + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt_tokens)}, padded to {bucketed}) + "
                f"max_new ({max_new_tokens}) exceeds pool capacity "
                f"{self.max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        stops = (
            self.default_stop if stop_tokens is None
            else frozenset(int(s) for s in stop_tokens)
        )
        self.queue.append((rid, list(prompt_tokens), max_new_tokens, stops))
        self._admit()
        return rid

    def pending(self) -> bool:
        return bool(self.queue) or any(
            s is not None for s in self.slots.values()
        )

    def step(self) -> List[Tuple[int, int, bool]]:
        """One decode step for every active slot.

        Returns [(request_id, token, done)] for tokens emitted this step.
        Finished slots free up and queued requests are admitted for the
        NEXT step.
        """
        self._admit()
        if not any(s is not None for s in self.slots.values()):
            return []

        # Emit each active slot's current tau; free finished slots BEFORE
        # the decode so a completing request doesn't pay for one more
        # forward whose output would be discarded.
        out: List[Tuple[int, int, bool]] = []
        taus = np.asarray(self.tau)
        for b, slot in self.slots.items():
            if slot is None:
                continue
            tok = int(taus[b])
            slot.emitted.append(tok)
            done = (
                tok in slot.stop_tokens
                or len(slot.emitted) >= slot.max_new
            )
            out.append((slot.request_id, tok, done))
            if done:
                self.slots[b] = None
                self.active = self.active.at[b].set(False)

        if any(s is not None for s in self.slots.values()):
            self._rng, sub = jax.random.split(self._rng)
            nxt, self.cache = _decode_step(
                self.params, self.cache, self.tau, self.pos, self.active,
                sub, config=self.config, temperature=self.temperature,
                top_p=self.top_p, top_k=self.top_k, mesh=self.mesh,
            )
            self.tau = nxt
            self.pos = self.pos + self.active.astype(jnp.int32)
        self._admit()
        return out

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drain everything; returns {request_id: emitted tokens}."""
        results: Dict[int, List[int]] = {}
        while self.pending():
            for rid, tok, done in self.step():
                results.setdefault(rid, []).append(tok)
        return results

    # -- internals ----------------------------------------------------------

    def _admit(self) -> None:
        for b, slot in self.slots.items():
            if slot is not None or not self.queue:
                continue
            rid, toks, max_new, stops = self.queue.pop(0)
            P = next_pow2(len(toks))
            pt = np.zeros((1, P), np.int32)
            pm = np.zeros((1, P), bool)
            pt[0, P - len(toks):] = toks
            pm[0, P - len(toks):] = True
            self._rng, sub = jax.random.split(self._rng)
            tau, plen, self.cache = _insert_row(
                self.params, self.cache, jnp.int32(b),
                jnp.asarray(pt), jnp.asarray(pm), sub,
                config=self.config, temperature=self.temperature,
                top_p=self.top_p, top_k=self.top_k,
                prefill_chunk=self.prefill_chunk, mesh=self.mesh,
            )
            self.tau = self.tau.at[b].set(tau)
            self.pos = self.pos.at[b].set(plen)
            self.active = self.active.at[b].set(True)
            self.slots[b] = _Slot(
                request_id=rid, emitted=[], max_new=max_new,
                stop_tokens=stops,
            )
