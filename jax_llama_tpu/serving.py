"""Continuous batching over a paged (block-table) KV cache.

Beyond the reference's capability surface (its only serving mode is one
batch of same-length prompts through `LLaMA.generate`, reference
``generation.py:22-45``) — a production decode loop where requests enter
and leave a fixed pool of batch slots independently, vLLM-style, so the
TPU never idles waiting for the longest generation in a batch.

TPU-native mechanics:
  * **Static shapes everywhere.**  The pool is ``n_slots`` rows; every
    decode step is one jitted [B=n_slots, T=1] forward.  A burst of k
    admissible requests is admitted as ONE [k', Pmax] batched prefill
    (k' = k rounded to a power of two with inactive pad rows, Pmax the
    group's max block-padded prompt length), so the jit cache holds
    O(log2(n_slots) · max_len / block_size) prefill programs + 1 decode
    program, and a k-request burst pays one dispatch instead of k.
  * **Paged KV.**  KV lives in a pool of fixed-size blocks
    ([L, KVH, n_blocks, block_size, hd], KV-head-major — the paged
    kernel's layout); each slot holds a block table
    (physical block ids in sequence order).  Admission *reserves* the
    blocks a request can ever need (ceil((prompt_padded + max_new) /
    block_size)); completion frees them.  The pool may be sized smaller
    than n_slots × max_len (overcommit): requests whose reservation does
    not fit wait in the queue, giving natural backpressure instead of the
    per-slot contiguous regions + power-of-two bucketing this replaces.
  * **Decode via the Pallas paged-attention kernel.**  Each step runs
    ``models.paged_forward``: the kernel's BlockSpec index maps chase the
    block table directly (scalar prefetch), so the pool is read ONCE per
    step and no contiguous view is ever materialized (int8 pools fold
    their dequant scales in-kernel).  Speculative rounds run the same
    kernel, always at the verify shape: every draft-chain step replays
    the growing block through one T = n_draft+1 multi-token pass over
    the base pool, and the verify is one more.  A gathered-view
    fallback (per-row virtually-contiguous cache + the model's
    per-row-offset forward) remains for kernel-incompatible meshes
    (kv_heads % tensor != 0, n_slots % (data*fsdp) != 0, or active
    seq/stage axes) and non-8-multiple block sizes.
  * **Per-request sampling.**  temperature/top-p/top-k and the PRNG
    chain are per-slot device arrays; each row samples with its own key
    (same warp math as ``ops.sampling.sample``, dynamic per-row), so a
    slot reproduces exactly what a standalone seeded ``engine.generate``
    of its request would emit.
  * **Idle slots cost nothing semantically**: their gathered positions
    are -1 (masked), their sampled token is ignored by the host, and
    their cache write-back is dropped (sentinel block id, scatter mode
    "drop").
  * **Chunked decode (Orca-style iteration batching).**  With
    ``decode_chunk`` > 1 the non-speculative step fuses K decode
    iterations into ONE jitted ``lax.scan`` program
    (``_paged_decode_chunk``): stop-token sets, per-row max_new budgets
    and the non-finite -1 sentinel are evaluated ON DEVICE (finished
    rows fold out of the active mask mid-chunk — they stop attending and
    writing), and the host gets the whole [B, K] token block (+ bitcast
    [B, K] logprobs when enabled) back in ONE ``np.asarray``.  Batcher
    state (block table, fills, positions, active mask, sampling
    policies, budgets, stop sets) is device-resident: admission / free /
    cancel mark rows dirty and one ``_scatter_rows`` dispatch syncs them
    before the next chunk — steady-state decode performs zero
    host->device state uploads and one device->host fetch per K tokens
    per slot, instead of the five uploads + one fetch PER TOKEN the
    K=1 loop pays.  K adapts (1 right after an admission, clamped small
    while the queue holds capacity-blocked requests, pow2 up to
    ``decode_chunk`` once slots are steady) so admission latency and
    time-to-first-token match the K=1 loop while saturated load keeps
    amortizing dispatches.  Chunked output is
    token-identical to K=1 under greedy and seeded sampling — per-row
    key chains split once per iteration exactly as one K=1 dispatch
    would (pinned by tests/test_serving_chunked.py).
  * **Chunked speculative serving.**  With ``spec_rounds`` > 1 the
    speculative path gets the same treatment: R draft+verify rounds
    fuse into ONE jitted ``lax.scan`` program (``_spec_rounds_chunk``,
    sharing ``_spec_round_core`` with the kept single-round program),
    with the per-round host work moved on device — the pending-tau
    emit, the accepted-prefix emit scan with stop-token / max_new /
    non-finite folding (``spec_decode.accepted_emit_counts``), the
    fill rewind to ``+acc+1`` after each verify, and mid-chunk
    fold-out of finished rows.  Host-boundary accounting: the classic
    loop paid 2-3 device->host fetches (tau, outs/acc, logprobs) plus
    FIVE mirror uploads (table/n_alloc/fill/pos/active + policies)
    PER ROUND; the fused path pays ONE packed [B, R, G+2(+G+1)] fetch
    per R rounds and zero steady-state uploads — both the target and
    draft pools and all per-slot decode state are device-resident via
    the same ``d_*`` twins / dirty-row ``_scatter_rows`` sync the
    plain chunked path uses.  R adapts exactly like K (1 after an
    admission, clamped while capacity-blocked, pow2 up to
    ``spec_rounds``), and chunked output is token-identical to the
    classic per-round path — including the acceptance pattern and
    per-token logprobs (pinned by tests/test_serving_spec.py).
  * **Fused prefill-decode scheduling (Sarathi-style stall-free
    admission).**  With ``prefill_budget`` > 0 (run.py
    ``--prefill-budget``, on by default there) the batched-prefill
    bullet above only describes the COLD pool: once any row is
    mid-decode, an admission no longer runs as a separate whole-prompt
    dispatch at a step boundary — it moves through queued ->
    prefilling(offset) -> decoding, advancing up to ``prefill_budget``
    prompt tokens per chunk dispatch INSIDE ``_fused_chunk`` (the
    K-iteration decode scan plus one bounded prefill chunk over the
    row's gathered view: flash when the chunk exceeds 8 tokens,
    gathered-XLA as the quarantine fallback; prefix-cache hit rows
    start their chunk walk at fill0).  At most one admission is in
    flight; its row rides the scan masked until the dispatch its last
    prompt chunk lands, where it samples its first token (one key
    split, exactly the classic insert's) and folds INTO the decode
    mask mid-dispatch — first token out of the same dispatch.  Host
    boundary: the whole prefill pays ONE admission-time upload (the
    dirty-row sync + the one-off suffix/walk-scalar buffers) and the
    usual one packed fetch per chunk — no per-prefill-chunk host
    syncs; decode rows never stall and ``_pick_chunk`` no longer
    collapses K to 1 on (fused) admissions.  Output is token- and
    logprob-identical to the classic admit-then-decode path (pinned by
    tests/test_serving_fused.py; on int8-KV pools the oracle is the
    classic path at the SAME prefill chunking — chunk boundaries
    decide where prompt KV quantizes, so identity to a single-shot
    classic prefill holds only up to quantization noise there);
    ``prefill_budget=0`` (the ctor default) and speculative batchers
    keep classic admission everywhere.
  * **KV capacity: radix prefix index + host-DRAM block tier**
    (``kvcache.py``).  The prefix cache's index is a block-granular
    radix/trie over token chains (``prefix_index="radix"``, the
    default; ``"exact"`` keeps the legacy flat chain map as the
    behavioral oracle, ``"off"`` disables matching): an admission
    claims the longest shared block prefix across ALL cached chains,
    divergent chains share their common prefix nodes by construction,
    and eviction is leaves-first.  With ``host_kv_blocks`` > 0 cold
    (refcount-0, LRU-expired) blocks demote INTO a bounded host-DRAM
    tier instead of being freed, staying matchable; admitting a
    session whose matched prefix includes demoted blocks parks it in
    a new ``restoring`` state: the slabs ``jax.device_put`` into
    staging buffers (async H2D, deliberately OFF the pool's
    dependency chain so in-flight decode chunks never wait on PCIe),
    readiness is polled non-blockingly at step boundaries, and one
    jitted scatter (``kvcache.adopt_into_pool`` — the block-migration
    generalization of the dirty-row ``_scatter_rows`` sync) lands the
    blocks before the session admits as a plain prefix hit.  Host
    boundary of the swap path: demotion pays one D2H slab fetch per
    evicted block (admission-time, off the decode hot path; counted
    in ``swap_out_blocks_total``, never in ``host_syncs_total``),
    swap-in pays one async H2D staging transfer + one adoption
    dispatch per restored session and ZERO per-chunk traffic — decode
    rows never stall while a swap-in is in flight, and a restored
    admission pays the same ≤ 1 dirty-row state upload as any fused
    admission (asserted by ``make perf-smoke``).  A swap-in failure
    (fault site ``kv_swap``) fails only the restoring request with
    its blocks unpinned; the index/tier rebuild empty on crash
    recovery and replayed requests re-prefill cold, token-identically.
  * **Serving-mesh sharding** (``parallel/serve_mesh.py``; run.py
    ``--serve-mesh dp,tp``).  On a data x tensor serving mesh inside
    the placement envelope (tensor divides KV heads, data*fsdp
    divides ``n_slots``, no seq/stage axes) the batcher places its
    state SHARDED at construction — the KV pool(s) split their
    KV-head axis over ``tensor`` (the paged kernel's own shard_map
    layout), the per-slot device twins split rows over the batch
    axes — and every chunk program re-constrains its outputs to the
    same specs, so each donated leaf aliases shard-locally from the
    first dispatch (no per-dispatch GSPMD reshard, no silent
    donation copy; proven per program by the lowering auditor's mesh
    pass).  Host boundary under sharding: the packed per-chunk fetch
    is replicated-out (one [1-2, B, K] block regardless of mesh
    size — ``np.asarray`` gathers the addressable shards), dirty-row
    ``_scatter_rows`` uploads are small host arrays GSPMD scatters to
    the row shards, and host-tier swap slabs stage PRE-SHARDED with
    the pool's layout (``kvcache.stage_restore`` placements) so the
    adoption scatter is shard-local.  The radix prefix index stays
    host-global: block ids are global, only the KV-head slice
    differs per shard.  Sharded chunk output is token-identical to
    single-chip (logprobs to cross-shard-reduction tolerance),
    pinned by tests/test_serve_mesh.py.  Data parallelism ACROSS
    batchers — replica routing, health-driven re-route, and the
    prefill/decode disaggregation handoff (``export_prefix`` /
    ``import_prefix``: the host-tier fetch/adopt primitives pointed
    across replicas) — lives in ``router.py``.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .config import LLaMAConfig
from .engine import (
    finite_rows, pow2_bucket, prompt_positions, window_positions,
)
from .faults import FaultInjector, InjectedFault
from .kvcache import (
    MatchResult,
    adopt_into_pool,
    adopt_lower,
    fetch_slab,
    make_prefix_store,
    pool_block_bytes,
    restore_ready,
    stage_restore,
)
from . import obs as _obs_mod
from .obs import CostModelCache, Observability
from .models.llama import (
    FLASH_MIN_SEQ,
    KVCache,
    PagedKVCache,
    forward,
    init_cache,
    lm_head_logits,
    paged_pool_write,
    paged_write_indices,
)
from .ops import kernels as _kernels_mod
from .ops.attention import NEG_INF
from .ops.sampling import stop_token_hits
from .parallel.mesh import use_mesh
from .parallel import serve_mesh as smesh
from .router import chain_keys as _router_chain_keys
from .spec_decode import (
    accepted_emit_counts,
    draft_categorical,
    leviathan_verify,
    place_extra,
)


# ---------------------------------------------------------------------------
# Paged KV pool
# ---------------------------------------------------------------------------

@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["k", "v", "pos", "k_scale", "v_scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class BlockPool:
    """Paged KV storage shared by all slots.

    k, v: [L, KVH, n_blocks, block_size, hd] (activation dtype or int8) —
          KV-head-major, the Pallas paged-attention kernel's layout (one
          (head, block) tile is a clean (block_size, hd) VMEM page).
    pos:  [n_blocks, block_size] int32 absolute position per cache slot;
          -1 marks invalid (free block / unwritten / rolled back).
    k_scale, v_scale: [L, KVH, n_blocks, block_size] fp32 (int8 pool only).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def n_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_pool(
    config: LLaMAConfig, n_blocks: int, block_size: int
) -> BlockPool:
    config.validate()
    int8_kv = config.kv_cache_dtype == "int8"
    dtype = jnp.int8 if int8_kv else config.activation_dtype
    shape = (
        config.n_layers, config.kv_heads, n_blocks, block_size,
        config.head_dim,
    )
    return BlockPool(
        k=jnp.zeros(shape, dtype=dtype),
        v=jnp.zeros(shape, dtype=dtype),
        pos=jnp.full((n_blocks, block_size), -1, jnp.int32),
        k_scale=jnp.zeros(shape[:-1], jnp.float32) if int8_kv else None,
        v_scale=jnp.zeros(shape[:-1], jnp.float32) if int8_kv else None,
    )


def _gather_cache(
    pool: BlockPool,
    table: jnp.ndarray,     # [B, MB] int32 physical block ids (NB = invalid)
    n_alloc: jnp.ndarray,   # [B] int32 allocated blocks per row
    fill: jnp.ndarray,      # [B] int32 per-row write offset (tokens)
    placed: bool = False,   # pin the view's KVH axis (serving mesh)
) -> KVCache:
    """Materialize the per-row virtually-contiguous cache view.

    Out-of-range table entries (sentinel n_blocks) clip on gather; their
    positions are forced to -1 via n_alloc so the garbage is never
    attended.
    """
    L, KVH, NB, BLK, hd = pool.k.shape
    B, MB = table.shape
    # mode="clip": sentinel (out-of-range) table entries gather a real
    # block's finite values — the default "fill" mode would inject NaN,
    # which survives the additive -inf mask (NaN + -inf = NaN) and poisons
    # the softmax.  Clipped garbage is masked via n_alloc below.
    take = functools.partial(jnp.take, mode="clip")

    def g(a):  # [L, KVH, NB, BLK, ...] -> [L, B, MB*BLK, KVH, ...]
        out = take(a, table, axis=2)  # [L, KVH, B, MB, BLK, ...]
        out = out.reshape(a.shape[:2] + (B, MB * BLK) + a.shape[4:])
        return jnp.moveaxis(out, 1, 3)

    kg, vg = g(pool.k), g(pool.v)
    posg = take(pool.pos, table, axis=0).reshape(B, MB * BLK)
    valid = jnp.arange(MB, dtype=jnp.int32)[None, :] < n_alloc[:, None]
    posg = jnp.where(jnp.repeat(valid, BLK, axis=1), posg, -1)
    ks = vs = None
    if pool.quantized:
        ks, vs = g(pool.k_scale), g(pool.v_scale)
    view = KVCache(
        k=kg, v=vg, pos=posg, index=fill, k_scale=ks, v_scale=vs
    )
    if placed:
        # Pin the gathered view to the pool's own KV-head sharding:
        # left unconstrained, GSPMD may satisfy the block gather by
        # REPLICATING the pool first — a full-pool all-gather inside
        # every scan iteration, which the comms-budget contracts
        # (analysis/comms.py) treat as a hard finding.
        view = smesh.constrain_view(view)
    return view


def _scatter_back(
    pool: BlockPool,
    view: KVCache,
    table: jnp.ndarray,
    fill: jnp.ndarray,
    active: jnp.ndarray,
    T: int,
) -> BlockPool:
    """Write the T new entries per row from the gathered view back into
    their physical blocks.  Inactive rows and out-of-reservation columns
    resolve to the sentinel block id and are dropped."""
    NB, BLK = pool.pos.shape
    B, MB = table.shape
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    # Shared write-back contract (same function paged_forward uses);
    # safe_cols is the matching clamped view column for each slot.
    blk, off, safe_cols = paged_write_indices(
        table, fill, active, T, NB, BLK
    )
    # view slices are [L, B, T, KVH, ...]; the pool wants KVH-major.
    nk = jnp.moveaxis(view.k[:, rows, safe_cols], 3, 1)   # [L, KVH, B, T, hd]
    nv = jnp.moveaxis(view.v[:, rows, safe_cols], 3, 1)
    npos = view.pos[rows, safe_cols]       # [B, T]
    # paged_pool_write = unrolled in-place dynamic_update_slices; the
    # batched scatter form forced four full-pool layout copies per step
    # (see its docstring).
    new = dataclasses.replace(
        pool,
        k=paged_pool_write(pool.k, nk, blk, off),
        v=paged_pool_write(pool.v, nv, blk, off),
        pos=paged_pool_write(pool.pos, npos, blk, off),
    )
    if pool.quantized:
        new = dataclasses.replace(
            new,
            k_scale=paged_pool_write(
                pool.k_scale,
                jnp.moveaxis(view.k_scale[:, rows, safe_cols], 3, 1),
                blk, off,
            ),
            v_scale=paged_pool_write(
                pool.v_scale,
                jnp.moveaxis(view.v_scale[:, rows, safe_cols], 3, 1),
                blk, off,
            ),
        )
    return new


# ---------------------------------------------------------------------------
# Per-row sampling (dynamic policies)
# ---------------------------------------------------------------------------

def _warp_rows(
    logits: jnp.ndarray,       # [B, V] or [B, T, V]
    temperature: jnp.ndarray,  # [B] fp32 (> 0 rows meaningful)
    top_p: jnp.ndarray,        # [B] fp32; 1.0 = off
    top_k: jnp.ndarray,        # [B] int32; V (or 0) = off
) -> jnp.ndarray:
    """Per-row warped LOGITS — the single source of truth for the warp
    math shared by ``sample_rows`` (which draws from it) and
    ``warped_probs_rows`` (which softmaxes it).  Row-wise identical to
    ``ops.sampling``'s static filters: scale by temperature, threshold at
    the k-th largest, nucleus threshold (same tie handling).  The
    speculative bit-identity contract depends on every consumer warping
    through THIS function.
    """
    V = logits.shape[-1]
    lg = logits.astype(jnp.float32)
    bshape = (logits.shape[0],) + (1,) * (lg.ndim - 1)
    t = jnp.maximum(temperature, 1e-6).reshape(bshape)
    scaled = lg / t
    # top-k: threshold at the k-th largest (k==V keeps everything, matching
    # the static filter's no-op when top_k is None).
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V).reshape(bshape)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.broadcast_to(k - 1, lg.shape[:-1] + (1,)), axis=-1
    )
    scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    # top-p: same construction as ops.sampling.top_p_filter, p per-row.
    sorted2 = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted2, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p.reshape(bshape)
    thr = jnp.min(
        jnp.where(keep, sorted2, jnp.inf), axis=-1, keepdims=True
    )
    thr = jnp.minimum(thr, jnp.max(scaled, axis=-1, keepdims=True))
    nucleus = jnp.where(top_p.reshape(bshape) < 1.0, thr, -jnp.inf)
    return jnp.where(scaled >= nucleus, scaled, NEG_INF)


def sample_rows(
    keys: jnp.ndarray,         # [B, 2] uint32 PRNG keys (one per row)
    logits: jnp.ndarray,       # [B, V]
    temperature: jnp.ndarray,  # [B] fp32; 0 = greedy
    top_p: jnp.ndarray,        # [B] fp32; 1.0 = off
    top_k: jnp.ndarray,        # [B] int32; V (or 0) = off
) -> jnp.ndarray:
    """Per-row ``ops.sampling.sample`` with *traced* per-row policies.

    Applies the identical warp math (``_warp_rows``) row-wise so a row
    with policy (t, p, k) and its own key chain draws bit-identically to
    ``sample(key, row[None], t, p, k)``.
    """
    lg = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    scaled = _warp_rows(logits, temperature, top_p, top_k)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(keys, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled)


def _split_rows(keys: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, 2] keys -> (carried [B, 2], subkeys [B, 2]) — the row-wise
    mirror of ``rng, sub = jax.random.split(rng)``."""
    out = jax.vmap(lambda key: jax.random.split(key))(keys)  # [B, 2, 2]
    return out[:, 0], out[:, 1]


def warped_probs_rows(
    logits: jnp.ndarray,       # [B, V] or [B, T, V]
    temperature: jnp.ndarray,  # [B] fp32 (> 0 rows meaningful)
    top_p: jnp.ndarray,        # [B] fp32; 1.0 = off
    top_k: jnp.ndarray,        # [B] int32; V (or 0) = off
) -> jnp.ndarray:
    """Per-row ``ops.sampling.warped_probs`` with *traced* policies.

    Identical warp math to ``sample_rows`` (shared ``_warp_rows``),
    returning the full post-warp distribution instead of a draw — the p
    and q of speculative accept/resample.  A row with policy (t, p, k)
    gets bit-identically ``warped_probs(row, t, p, k)``.
    """
    return jax.nn.softmax(
        _warp_rows(logits, temperature, top_p, top_k), axis=-1
    )


# ---------------------------------------------------------------------------
# Jitted step programs
# ---------------------------------------------------------------------------

def _kernel_eligible(block_size, mesh, kv_heads, n_rows, draft_config=None):
    """THE paged-kernel eligibility predicate, shared by the in-jit decode
    step and the host-side speculative gate so the two cannot drift:
    Mosaic's 8-sublane tiling on the block axis, and (under a mesh) KV
    heads dividing `tensor`, rows dividing data*fsdp, no seq/stage axes.
    """
    ok = block_size % 8 == 0
    if mesh is not None:
        rows = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
        ok = ok and (
            kv_heads % mesh.shape.get("tensor", 1) == 0
            and n_rows % rows == 0
            and mesh.shape.get("seq", 1) == 1
            and mesh.shape.get("stage", 1) == 1
        )
        if draft_config is not None:
            ok = ok and (
                draft_config.kv_heads % mesh.shape.get("tensor", 1) == 0
            )
    return bool(ok)


def _decode_step_core(
    params, pool, table, n_alloc, fill, tau, pos, active, keys,
    temperature, top_p, top_k, *, config, all_greedy, use_kernel,
    with_logprobs, placed=False,
):
    """One [n_slots, 1] decode iteration over the paged pool — the shared
    body of the single-step program (``_paged_decode_step``) and each
    ``lax.scan`` iteration of the fused chunk program
    (``_paged_decode_chunk``), so the two cannot drift numerically.

    Returns (next token [B] with the -1 non-finite sentinel folded in,
    its model logprob or None, carried keys, updated pool)."""
    positions = jnp.where(active, pos, -1)[:, None]
    if use_kernel:
        pcache = PagedKVCache(
            k=pool.k, v=pool.v, pos=pool.pos,
            table=table, fill=fill,
            k_scale=pool.k_scale, v_scale=pool.v_scale,
        )
        logits, pcache = forward(
            params, tau[:, None], positions, config, cache=pcache,
            attn_mask=active[:, None],
        )
        pool = dataclasses.replace(
            pool, k=pcache.k, v=pcache.v, pos=pcache.pos,
            k_scale=pcache.k_scale, v_scale=pcache.v_scale,
        )
    else:
        view = _gather_cache(pool, table, n_alloc, fill, placed=placed)
        logits, view = forward(
            params, tau[:, None], positions, config, cache=view,
            attn_mask=active[:, None],
        )
        pool = _scatter_back(pool, view, table, fill, active, T=1)
    if all_greedy:
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    else:
        keys, subs = _split_rows(keys)
        nxt = sample_rows(subs, logits[:, -1], temperature, top_p, top_k)
    # with_logprobs is static (trace-time specialization, like
    # all_greedy): without it the fp32 [B, V] cast + logsumexp never
    # enter the compiled program.
    lp = _token_logprob(logits[:, -1], nxt) if with_logprobs else None
    # Non-finite guard: a row whose raw logits contain NaN/Inf gets
    # the -1 token sentinel instead of a draw from garbage; the host
    # emit scan fails just that request (tokens are never negative,
    # so the sentinel cannot collide).  Folding the flag into tau
    # keeps the guard free of extra device->host fetches.
    nxt = jnp.where(finite_rows(logits[:, -1]), nxt, -1)
    return nxt, lp, keys, pool


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "mesh", "all_greedy", "allow_kernel", "with_logprobs",
        "placed",
    ),
    donate_argnames=("pool",),
)
def _paged_decode_step(
    params, pool, table, n_alloc, fill, tau, pos, active, keys,
    temperature, top_p, top_k, *, config, all_greedy=False, mesh=None,
    allow_kernel=True, with_logprobs=False, placed=False,
):
    """One [n_slots, 1] decode step over the paged pool.

    tau: [B] current token per slot; pos: [B] its absolute position;
    active: [B] bool.  Inactive rows run masked (position -1, write-back
    dropped, sampled token ignored by the host).

    ``all_greedy`` is static: when every active slot is greedy the step
    compiles to a pure argmax — no sorts/softmax/key-splits on the hot
    path (the host flips to the sampling variant the moment a sampled
    request is admitted; greedy rows' key chains are never consumed, so
    skipping the split here is unobservable).

    Attention path: the Pallas paged kernel walks the block table
    in-kernel (pool read once per step; int8 pools fold their dequant
    scales in-kernel).  Under a mesh the op itself shard_maps over the
    tensor (KV heads) and data (rows) axes.  Fallbacks to the gathered
    contiguous view: block sizes that break Mosaic's 8-sublane tiling,
    and meshes the kernel sharding cannot cover (kv_heads % tensor != 0,
    n_slots % data != 0, or active seq/stage axes).
    """
    with use_mesh(mesh):
        # Sub-128 (narrow-lane) block sizes are verified compiled on
        # hardware — bf16 and int8 kernels match interpret mode exactly at
        # BLK 8/16/32/64/128 on a v5e chip (regression-tested in
        # tests/test_tpu_compiled.py).
        use_kernel = allow_kernel and _kernel_eligible(
            pool.block_size, mesh, config.kv_heads, tau.shape[0]
        )
        nxt, lp, keys, pool = _decode_step_core(
            params, pool, table, n_alloc, fill, tau, pos, active, keys,
            temperature, top_p, top_k, config=config,
            all_greedy=all_greedy, use_kernel=use_kernel,
            with_logprobs=with_logprobs, placed=placed,
        )
        if placed:
            keys, = smesh.constrain_rows(keys)
            pool = smesh.constrain_pool(pool)
        return nxt, lp, keys, pool


# "No token emitted this chunk column" marker in the [B, K] token block
# (the row was already inactive).  Distinct from the -1 non-finite
# sentinel: real tokens are never negative, so both are unambiguous.
_CHUNK_PAD = -2


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "n_iter", "mesh", "all_greedy", "allow_kernel",
        "with_logprobs", "placed",
    ),
    donate_argnames=(
        "pool", "fill", "tau", "tau_lp", "pos", "active", "remaining",
        "keys",
    ),
)
def _paged_decode_chunk(
    params, pool, table, n_alloc, fill, tau, tau_lp, pos, active,
    remaining, stops, keys, temperature, top_p, top_k, *,
    config, n_iter, all_greedy=False, mesh=None, allow_kernel=True,
    with_logprobs=False, placed=False,
):
    """``n_iter`` fused decode iterations in ONE jitted program — the
    chunked-decode hot path.  Each ``lax.scan`` iteration replays the
    host's K=1 contract exactly, ON DEVICE:

      1. *emit* the pending token ``tau`` into the output block
         (column i), recording -1 for a non-finite-sentinel row and
         ``_CHUNK_PAD`` for rows that were already inactive;
      2. *stop-detect*: a row whose emitted token is in its stop set
         (``stops``, a [B, S] -1-padded per-row table) or whose
         ``remaining`` generation budget is exhausted (or whose tau
         carries the -1 sentinel) folds out of ``active`` — it stops
         attending and writing for the REST of the chunk, exactly as the
         host frees the slot before the next K=1 dispatch;
      3. run one ``_decode_step_core`` iteration for the surviving rows
         (same keys-split topology per iteration as one K=1 dispatch, so
         sampled streams are bit-identical) and advance fill/pos.

    The host touches the device once per CHUNK, not per token: the token
    block (and, under ``with_logprobs``, the per-token logprobs,
    bitcast to int32) comes back as ONE packed int32 array
    [1 or 2, B, n_iter], and all decode state (fill/pos/active/remaining/
    tau/tau_lp/keys + the pool) stays resident — returned as fresh
    donated buffers, never re-uploaded from numpy.

    Token-identity with K=1 (pinned by tests/test_serving_chunked.py):
    iteration i's sample sees exactly the state a K=1 dispatch sequence
    would have, and key chains split once per iteration regardless of
    liveness — the same [B]-wide split a K=1 dispatch performs.

    Iterations after every row has folded out run MASKED rather than
    being lax.cond-skipped: guarding a cached decode forward with a
    cond was measured to cost more than the wasted forward (the
    branch-merge forced full-cache relayout copies — see the engine
    while-loop's note, engine.py).  The host bounds the waste anyway:
    ``_pick_chunk`` clamps K to the largest remaining budget, so a
    fully-dead tail only arises from stop tokens landing early.
    """
    with use_mesh(mesh):
        use_kernel = allow_kernel and _kernel_eligible(
            pool.block_size, mesh, config.kv_heads, tau.shape[0]
        )
        return _chunk_scan(
            params, pool, table, n_alloc, fill, tau, tau_lp, pos,
            active, remaining, stops, keys, temperature, top_p, top_k,
            config=config, n_iter=n_iter, all_greedy=all_greedy,
            use_kernel=use_kernel, with_logprobs=with_logprobs,
            placed=placed,
        )


def _chunk_scan(
    params, pool, table, n_alloc, fill, tau, tau_lp, pos, active,
    remaining, stops, keys, temperature, top_p, top_k, *,
    config, n_iter, all_greedy, use_kernel, with_logprobs,
    placed=False,
):
    """The shared K-iteration fused decode scan — the body of
    ``_paged_decode_chunk`` AND the decode half of ``_fused_chunk`` (the
    fused prefill-decode program), factored out so the two cannot drift
    (the same discipline ``_decode_step_core`` enforces one level down).
    See ``_paged_decode_chunk``'s docstring for the full contract;
    callers resolve ``use_kernel`` and enter the mesh."""

    def body(carry, _):
        pool, tau, tau_lp, fill, pos, active, remaining, keys = carry
        # --- the host emit scan, on device ---
        nonfinite = tau < 0
        hit_stop = stop_token_hits(tau, stops)
        out_tok = jnp.where(
            active,
            jnp.where(nonfinite, -1, tau),
            _CHUNK_PAD,
        ).astype(jnp.int32)
        out_lp = tau_lp
        done = active & (nonfinite | hit_stop | (remaining <= 1))
        remaining = remaining - active.astype(jnp.int32)
        active = active & ~done
        # --- one decode iteration for the surviving rows ---
        nxt, lp, keys, pool = _decode_step_core(
            params, pool, table, n_alloc, fill, tau, pos, active,
            keys, temperature, top_p, top_k, config=config,
            all_greedy=all_greedy, use_kernel=use_kernel,
            with_logprobs=with_logprobs, placed=placed,
        )
        tau = jnp.where(active, nxt, tau)
        if with_logprobs:
            tau_lp = jnp.where(active, lp, tau_lp)
        fill = fill + active
        pos = pos + active
        return (
            (pool, tau, tau_lp, fill, pos, active, remaining, keys),
            (out_tok, out_lp),
        )

    carry, (toks, lps) = lax.scan(
        body,
        (pool, tau, tau_lp, fill, pos, active, remaining, keys),
        None,
        length=n_iter,
    )
    pool, tau, tau_lp, fill, pos, active, remaining, keys = carry
    # Serving-mesh placement (parallel/serve_mesh.py): pin the carried
    # state and pool outputs to their canonical shardings so the
    # donated inputs (placed the same way at construction) alias
    # shard-locally instead of resharding per dispatch.  ``placed``
    # is the CTOR's placement decision threaded through as a static
    # arg — every program a batcher dispatches constrains (or not)
    # consistently, so pool sharding can never ping-pong between an
    # insert and a chunk dispatch.  Trace-time no-op when False.
    if placed:
        (tau, tau_lp, fill, pos, active, remaining,
         keys) = smesh.constrain_rows(
            tau, tau_lp, fill, pos, active, remaining, keys
        )
        pool = smesh.constrain_pool(pool)
    toks = jnp.swapaxes(toks, 0, 1)  # [B, K]
    if with_logprobs:
        # One packed transfer: fp32 logprobs ride bitcast to int32
        # alongside the tokens, so logprobs mode still pays exactly
        # one device->host fetch per chunk.
        lp_bits = lax.bitcast_convert_type(
            jnp.swapaxes(lps, 0, 1).astype(jnp.float32), jnp.int32
        )
        packed = jnp.stack([toks, lp_bits])  # [2, B, K]
    else:
        packed = toks[None]  # [1, B, K]
    return (
        packed, tau, tau_lp, fill, pos, active, remaining, keys, pool
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "n_iter", "pf_chunk", "all_greedy", "mesh",
        "allow_kernel", "with_logprobs", "placed",
    ),
    donate_argnames=(
        "pool", "fill", "tau", "tau_lp", "pos", "active", "remaining",
        "keys", "pf_off",
    ),
)
def _fused_chunk(
    params, pool, table, n_alloc, fill, tau, tau_lp, pos, active,
    remaining, stops, keys, temperature, top_p, top_k,
    pf_row, pf_toks, pf_len, pf_base, pf_off, pf_key, *,
    config, n_iter, pf_chunk, all_greedy=False, mesh=None,
    allow_kernel=True, with_logprobs=False, placed=False,
):
    """The fused prefill-decode program: ONE jitted dispatch that
    advances up to ``pf_chunk`` prompt tokens of the single in-flight
    admission AND runs the standard ``n_iter``-iteration decode scan —
    so admissions never stall decode (Sarathi-style stall-free chunked
    prefill, piggybacked on the device-resident decode chunk).

    Prefill half: the admitted row's gathered view is cut from the pool
    (``_gather_cache`` over its table row) with a SCALAR write index
    ``pf_base + pf_off`` — scalar, not per-row, so ``forward``'s "auto"
    resolution may run the Pallas flash kernel over the chunk
    (pf_chunk > 8) with the gathered XLA path as the quarantine/debug
    fallback; prefix-cache-hit rows start their chunk walk at
    fill0 = ``pf_base`` and attend the reused KV through the same view.
    The chunk's KV lands in the row's reserved blocks via the shared
    ``_scatter_back`` write contract.  The last prompt token's hidden
    state is gathered every chunk (O(D); the [1, V] head matmul is
    noise), but only the dispatch where ``pf_off + pf_chunk >= pf_len``
    CONSUMES it: the row's key chain splits exactly once (the
    ``_paged_insert`` split the classic path performs), the first token
    is sampled with the row's own policy (non-finite guard folds the -1
    sentinel exactly as admission does), and the row folds INTO the
    decode state mid-dispatch — active/fill/pos/tau/tau_lp/keys all
    flip on device — so the decode scan below emits its first sampled
    token from THIS dispatch, not a later one.  Non-final chunks
    discard the sample and leave the key chain untouched (``pf_key`` is
    the same device array every dispatch, so the chain starts exactly
    where a classic ``_paged_insert`` of the request would).

    Decode half: the unchanged ``_chunk_scan`` (shared with
    ``_paged_decode_chunk``, so the fused program cannot drift from the
    plain one).  The prefilling row rides the scan masked (position -1,
    writes dropped) until its activation dispatch.

    Host boundary: identical to ``_paged_decode_chunk`` — ONE packed
    [1 or 2, B, K] fetch, zero steady-state uploads.  All prefill state
    (``pf_toks`` uploaded once at admission; ``pf_off`` a donated
    device carry advanced in-program) stays resident: a 32-chunk 16k
    prefill costs zero per-chunk host->device transfers beyond the
    dispatch itself.

    Returns ``_chunk_scan``'s tuple + the advanced ``pf_off``.
    """
    with use_mesh(mesh):
        B = tau.shape[0]
        C = pf_chunk
        NB, BLK = pool.pos.shape
        # ---- one bounded prefill chunk for the in-flight admission ----
        table_r = lax.dynamic_slice_in_dim(table, pf_row, 1, axis=0)
        n_alloc_r = lax.dynamic_slice_in_dim(n_alloc, pf_row, 1, axis=0)
        write_at = (pf_base + pf_off).astype(jnp.int32)
        view = _gather_cache(
            pool, table_r, n_alloc_r, write_at[None], placed=placed
        )
        # Scalar index (ONE prefilling row): keeps the view off the
        # per-row-index must-xla path, so "auto" runs flash over the
        # chunk; the host-side _pf_chunk clamp guarantees
        # write_at + C <= MB * BLK (dynamic_update_slice would otherwise
        # clamp its start and scribble over the reused prefix KV — the
        # _suffix_pad hazard).
        view = dataclasses.replace(view, index=write_at)
        toks_c = lax.dynamic_slice_in_dim(pf_toks, pf_off, C)[None]
        positions, real = window_positions(pf_base, pf_off, C, pf_len)
        _, view, aux = forward(
            params, toks_c, positions, config, cache=view,
            attn_mask=real, compute_logits=False, output_last_hidden=True,
        )
        idx = pf_len - 1 - pf_off  # in [0, C) iff this is the last chunk
        h_last = jnp.take_along_axis(
            aux.last_hidden_state,
            jnp.clip(idx, 0, C - 1)[None, None, None], axis=1,
        )[:, 0]
        logits_last = lm_head_logits(
            params, h_last[:, None], config, normed=True
        )[:, 0]
        pool = _scatter_back(
            pool, view, table_r, write_at[None], jnp.ones((1,), bool),
            T=C,
        )
        # The admission sample — only persisted below when the prompt
        # completes this dispatch (the split/sample topology is exactly
        # _paged_insert's, so the row's stream is bit-identical to the
        # classic admit-then-decode path).
        kc, sub = _split_rows(pf_key[None])
        t_r = lax.dynamic_slice_in_dim(temperature, pf_row, 1, axis=0)
        p_r = lax.dynamic_slice_in_dim(top_p, pf_row, 1, axis=0)
        k_r = lax.dynamic_slice_in_dim(top_k, pf_row, 1, axis=0)
        first = sample_rows(sub, logits_last, t_r, p_r, k_r)
        first_lp = (
            _token_logprob(logits_last, first) if with_logprobs else None
        )
        # Non-finite guard (see _paged_insert): the -1 sentinel rides
        # tau into the scan's emit, which fails just this request.
        first = jnp.where(finite_rows(logits_last), first, -1)
        done = pf_off + C >= pf_len
        fold = (jnp.arange(B, dtype=jnp.int32) == pf_row) & done
        active = active | fold
        tau = jnp.where(fold, first[0], tau)
        if with_logprobs:
            tau_lp = jnp.where(fold, first_lp[0], tau_lp)
        fill_done = pf_base + ((pf_len + BLK - 1) // BLK) * BLK
        fill = jnp.where(fold, fill_done, fill)
        pos = jnp.where(fold, pf_base + pf_len, pos)
        keys = jnp.where(fold[:, None], kc, keys)
        pf_off = pf_off + C
        # ---- the standard K-iteration decode scan ----
        use_kernel = allow_kernel and _kernel_eligible(
            pool.block_size, mesh, config.kv_heads, B
        )
        out = _chunk_scan(
            params, pool, table, n_alloc, fill, tau, tau_lp, pos,
            active, remaining, stops, keys, temperature, top_p, top_k,
            config=config, n_iter=n_iter, all_greedy=all_greedy,
            use_kernel=use_kernel, with_logprobs=with_logprobs,
            placed=placed,
        )
        return out + (pf_off,)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(state, idx, rows):
    """Update per-slot device-resident decode state for the (padded,
    pow2-bucketed) row indices ``idx`` in ONE dispatch — the admission/
    free/cancel sync primitive of the chunked path.  Pad entries carry
    the out-of-range index n_slots and drop."""
    return tuple(
        a.at[idx].set(v.astype(a.dtype), mode="drop")
        for a, v in zip(state, rows)
    )


def _token_logprob(logits: jnp.ndarray, tok: jnp.ndarray) -> jnp.ndarray:
    """Model log-probability of ``tok`` under fp32 log-softmax of the raw
    logits — temperature/top-p independent (the standard serving-API
    definition), identical to what ``engine.score`` reports for the same
    position.  logits: [B, V]; tok: [B] -> [B] fp32."""
    lg = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    return jnp.take_along_axis(lg, tok[:, None].astype(jnp.int32), axis=1)[
        :, 0
    ] - lse


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "mesh", "prefill_chunk", "with_logprobs", "placed",
    ),
    donate_argnames=("pool",),
)
def _paged_insert(
    params, pool, block_ids, prompt_tokens, prompt_mask, keys,
    temperature, top_p, top_k, *,
    config, prefill_chunk=None, mesh=None, with_logprobs=False,
    placed=False,
):
    """Prefill a batch of k admitted requests and land their KV in their
    reserved blocks.

    prompt_tokens/prompt_mask: [k, P] RIGHT-padded to the GROUP's max
    block-multiple length (a burst of admissions shares ONE prefill
    dispatch — previously each request paid its own B=1 prefill, and a
    burst of k paid k serialized dispatches).  Right padding (r5; was
    left) places every row's token j at view column j, so a prompt's
    block CONTENT is a pure function of its tokens — the invariant the
    prefix cache keys on; padding is masked either way, so each row
    emits bit-identically to a standalone B=1 insert of its request.
    block_ids: [k, P // block_size] physical blocks per row, TRAILING
    entries set to the sentinel (n_blocks) for rows with P_b < P — the
    pool scatter drops them, so only the row's own P_b-span lands (P and
    every P_b are block multiples, so the alignment is exact).
    Inactive (padding) rows, if any, carry all-sentinel block_ids and an
    all-False mask.
    Returns (sampled tokens [k], their model logprobs [k], prompt
    lengths [k], carried keys [k, 2], updated pool).
    """
    with use_mesh(mesh):
        k_rows, P = prompt_tokens.shape
        BLK = pool.block_size
        sub = init_cache(config, k_rows, max_len=P)
        positions = prompt_positions(prompt_mask)
        plen = jnp.sum(prompt_mask.astype(jnp.int32), axis=-1)
        chunk = prefill_chunk if prefill_chunk and prefill_chunk < P else P
        # Right padding means a row's LAST real token can sit in any
        # chunk, so instead of taking the final chunk's [k, chunk, V]
        # logits, gather each row's last-token HIDDEN state as chunks
        # stream by (output_last_hidden is head-free and O(k·D)) and run
        # ONE [k, D] head matmul at the end — cheaper than the old full
        # final-chunk head at every geometry.
        h_last = None
        for start in range(0, P, chunk):
            end = min(start + chunk, P)
            _, sub, aux = forward(
                params, prompt_tokens[:, start:end],
                positions[:, start:end], config, cache=sub,
                attn_mask=prompt_mask[:, start:end],
                compute_logits=False, output_last_hidden=True,
                # start is a PYTHON int (this loop is unrolled at trace
                # time), so the splash prefill kernel — whose causal
                # mask needs a static offset — can engage per chunk
                # when config.prefill_kernel selects it.
                chunk_offset=start,
            )
            idx = plen - 1 - start  # [k] last-token offset in this chunk
            in_chunk = (idx >= 0) & (idx < end - start)
            g = jnp.take_along_axis(
                aux.last_hidden_state,
                jnp.clip(idx, 0, end - start - 1)[:, None, None],
                axis=1,
            )[:, 0]
            h_last = (
                g if h_last is None
                else jnp.where(in_chunk[:, None], g, h_last)
            )
        logits_last = lm_head_logits(
            params, h_last[:, None], config, normed=True
        )[:, 0]
        keys, subkeys = _split_rows(keys)
        tau = sample_rows(subkeys, logits_last, temperature, top_p, top_k)
        tau_lp = (
            _token_logprob(logits_last, tau) if with_logprobs else None
        )
        # Non-finite guard (see _paged_decode_step): -1 sentinel rows are
        # failed by the host at the next emit boundary.
        tau = jnp.where(finite_rows(logits_last), tau, -1)

        L, KVH, _, _, hd = pool.k.shape
        nb = P // BLK

        def to_blocks(a):  # [L, k, P, KVH, ...] -> [L, KVH, k, nb, BLK, ...]
            return jnp.moveaxis(a, 3, 1).reshape(
                (L, KVH, k_rows, nb, BLK) + a.shape[4:]
            )

        # block_ids is [k, nb]; sentinel entries (NB) drop their update.
        pool = dataclasses.replace(
            pool,
            k=pool.k.at[:, :, block_ids].set(
                to_blocks(sub.k), mode="drop"
            ),
            v=pool.v.at[:, :, block_ids].set(
                to_blocks(sub.v), mode="drop"
            ),
            pos=pool.pos.at[block_ids].set(
                sub.pos.reshape(k_rows, nb, BLK), mode="drop"
            ),
        )
        if pool.quantized:
            pool = dataclasses.replace(
                pool,
                k_scale=pool.k_scale.at[:, :, block_ids].set(
                    to_blocks(sub.k_scale), mode="drop"
                ),
                v_scale=pool.v_scale.at[:, :, block_ids].set(
                    to_blocks(sub.v_scale), mode="drop"
                ),
            )
        # Serving-mesh placement: the donated pool leaves the insert
        # with the same canonical sharding it arrived with (``placed``
        # is the ctor's decision — the SAME predicate every other
        # program uses, so insert and chunk dispatches can never
        # disagree about the pool's sharding).
        if placed:
            pool = smesh.constrain_pool(pool)
        return tau, tau_lp, plen, keys, pool


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "mesh", "prefill_chunk", "with_logprobs", "placed",
    ),
    donate_argnames=("pool",),
)
def _paged_suffix_insert(
    params, pool, table_row, n_alloc_row, fill0, suffix_tokens,
    suffix_mask, keys, temperature, top_p, top_k, *,
    config, prefill_chunk=None, mesh=None, with_logprobs=False,
    placed=False,
):
    """Prefill k requests' prompt SUFFIXES over the paged pool — the
    prefix-cache admission path: the leading ``fill0[i]`` positions of
    each row's table already hold a reused cached prefix, so only the
    suffixes run through the model, attending the prefix KV through the
    rows' gathered views (``paged_forward``'s multi-token kernel
    contract requires uniform activity along T, which right-padded
    suffixes violate — the gather/scatter cost is the rows'
    reservations, paid once per admission).  Hit requests sharing a
    padded suffix length are admitted as ONE call (per-row fill0
    offsets differ freely); this environment charges ~100 ms of tunnel
    latency per dispatch, so bursts of identical /chat prompts would
    otherwise serialize.

    table_row: [k, MB]; n_alloc_row, fill0: [k] int32 (fill0 = shared
    prefix length in tokens, a block multiple); suffix_tokens/mask:
    [k, T] right-padded to a block multiple.
    Returns (tau [k], tau logprobs, carried keys, updated pool).
    """
    with use_mesh(mesh):
        B1, T = suffix_tokens.shape
        view = _gather_cache(
            pool, table_row, n_alloc_row, fill0, placed=placed
        )
        slen = jnp.sum(suffix_mask.astype(jnp.int32), axis=1)  # [k]
        positions = jnp.where(
            suffix_mask,
            fill0[:, None]
            + jnp.cumsum(suffix_mask.astype(jnp.int32), axis=1) - 1,
            -1,
        )
        chunk = prefill_chunk if prefill_chunk and prefill_chunk < T else T
        h_last = None
        for start in range(0, T, chunk):
            end = min(start + chunk, T)
            _, view, aux = forward(
                params, suffix_tokens[:, start:end],
                positions[:, start:end], config, cache=view,
                attn_mask=suffix_mask[:, start:end],
                compute_logits=False, output_last_hidden=True,
            )
            idx = slen - 1 - start
            in_chunk = (idx >= 0) & (idx < end - start)
            g = jnp.take_along_axis(
                aux.last_hidden_state,
                jnp.clip(idx, 0, end - start - 1)[:, None, None],
                axis=1,
            )[:, 0]
            h_last = (
                g if h_last is None
                else jnp.where(in_chunk[:, None], g, h_last)
            )
        logits_last = lm_head_logits(
            params, h_last[:, None], config, normed=True
        )[:, 0]
        pool = _scatter_back(
            pool, view, table_row, fill0, jnp.ones((B1,), bool), T
        )
        keys, sub = _split_rows(keys)
        tau = sample_rows(sub, logits_last, temperature, top_p, top_k)
        lp = _token_logprob(logits_last, tau) if with_logprobs else None
        # Non-finite guard (see _paged_decode_step): -1 sentinel rows are
        # failed by the host at the next emit boundary.
        tau = jnp.where(finite_rows(logits_last), tau, -1)
        # Serving-mesh placement: see _paged_insert's epilogue.
        if placed:
            pool = smesh.constrain_pool(pool)
        return tau, lp, keys, pool


@functools.partial(jax.jit, donate_argnames=("pos",))
def _release_blocks(pos, block_ids):
    """Invalidate freed blocks' positions (block_ids padded with the
    out-of-range sentinel; those drop)."""
    return pos.at[block_ids].set(-1, mode="drop")


def _pool_as_cache(pool: BlockPool, table, fill) -> PagedKVCache:
    return PagedKVCache(
        k=pool.k, v=pool.v, pos=pool.pos, table=table, fill=fill,
        k_scale=pool.k_scale, v_scale=pool.v_scale,
    )


def _cache_into_pool(pool: BlockPool, pcache: PagedKVCache) -> BlockPool:
    return dataclasses.replace(
        pool, k=pcache.k, v=pcache.v, pos=pcache.pos,
        k_scale=pcache.k_scale, v_scale=pcache.v_scale,
    )


def _spec_round_core(
    t_params, d_params, t_pool, d_pool, table, n_alloc, fill, tau, pos,
    active, keys, temperature, top_p, top_k, *,
    t_config, d_config, n_draft, all_greedy, use_kernel, mesh=None,
    with_logprobs=False, placed=False,
):
    """One speculative round for every active slot — greedy or sampled
    verification, per-row policies.  The shared row-wise draft/verify
    body of the single-round program (``_spec_round``) and each
    ``lax.scan`` iteration of the fused R-round chunk program
    (``_spec_rounds_chunk``), so the two cannot drift numerically (the
    same discipline ``_decode_step_core`` enforces for plain decode).

    Draft proposes ``n_draft`` tokens autoregressively, the target
    verifies them in ONE [B, n_draft+1] forward (weights stream once per
    round — the whole point on HBM-bound TPU decode), and the accepted
    prefix is committed.  Both models share the block geometry, so one
    table/fill serves the two pools.

    ``use_kernel`` (static) routes every forward through the Pallas
    paged-attention kernel, always at the verify shape: each draft-chain
    step is one T=G+1 multi-token kernel pass replaying the growing
    block over the BASE pool, and the verify is one more — so neither
    pool is ever gathered into a contiguous view (the gathered path
    moved both pools' bytes 3× per round).  The gathered fallback
    remains for kernel-incompatible meshes / block sizes.

    ``all_greedy`` (static) compiles the pure-argmax verification with no
    RNG traffic.  Otherwise verification is per-row Leviathan rejection
    sampling — the SAME ``spec_decode.leviathan_verify`` /
    ``draft_categorical`` / ``place_extra`` implementation the standalone
    engine traces, with traced per-row policies and per-row key chains
    (vmapped draws): each sampled row consumes its keys exactly as a
    standalone B=1 seeded ``generate_speculative`` of that request would
    — same split topology, same warp math — so its emitted tokens are
    bit-identical (pinned by tests/test_serving_spec.py); greedy rows
    (temperature 0) take the exact-argmax path inside the same program.

    Returns (outs [B, G+1], acc [B], lps, carried keys [B, 2], pools):
    the host emits ``outs[:acc+1]`` per row and rewinds fill to +acc+1,
    so rejected drafts cost no pool capacity.  ``with_logprobs`` (static)
    additionally returns lps [B, G+1] — the fp32 log-softmax of the raw
    TARGET logits at each emitted offset (``_token_logprob``'s
    definition; the verify pass already computes every position's
    logits, so this is one gather + logsumexp, no extra forward) —
    otherwise lps is None.
    """
    G = n_draft
    B = tau.shape[0]
    V = t_config.vocab_size
    with use_mesh(mesh):
        NB, BLK = t_pool.pos.shape
        if all_greedy:
            keys_out = keys
            k_draft = k_accept = k_extra = keys  # unused
        else:
            # Row-wise mirror of _spec_impl's per-round
            # ``rng, k_draft, k_accept, k_extra = jax.random.split(rng, 4)``.
            splits = jax.vmap(lambda k: jax.random.split(k, 4))(keys)
            keys_out, k_draft, k_accept, k_extra = (
                splits[:, 0], splits[:, 1], splits[:, 2], splits[:, 3]
            )

        if not use_kernel:
            t_view = _gather_cache(
                t_pool, table, n_alloc, fill, placed=placed
            )
            d_view = _gather_cache(
                d_pool, table, n_alloc, fill, placed=placed
            )

        # --- 1. draft chain: propose d_1 .. d_G by REPLAYING the block ---
        # Every chain step re-processes the growing block
        # [tau, d_1..d_j, pads] through ONE verify-shaped T=G+1 forward
        # over the BASE pool (read-only — fill unchanged, returned cache
        # discarded, so the writes are dead code XLA eliminates): token
        # j's logits come from the same program shape and the same
        # softmax source split (pool slots via the kernel ∪ in-step
        # tokens via the merge) as the target verify below.  In
        # self-draft the chain is then the SAME compiled function of the
        # same pool bytes as the verify, so greedy acceptance is exact —
        # the r3 T=1 incremental chain's tile shapes wobbled ~1 bf16
        # ulp/layer against the T=G+1 verify (shape-dependent merge
        # einsum tilings; the pool kernel itself is bit-exact across T),
        # flipping near-tie argmaxes: measured 0.92-0.95 kernel-path
        # acceptance vs 0.97-0.99 gathered.  Cost is a wash: G drafting
        # forwards + one KV-landing pass (below) replaces G incremental
        # steps + the d_G catch-up step, and the kernel's padded query
        # tile (TG8) is the same geometry for T=1 and T=G+1.
        jj = jnp.arange(G + 1, dtype=jnp.int32)[None, :]
        block_pos = jnp.where(
            active[:, None], pos[:, None] + jj, -1
        ).astype(jnp.int32)
        block0 = jnp.concatenate(
            [tau[:, None], jnp.zeros((B, G), jnp.int32)], axis=1
        )

        def draft_step(carry, j):
            buf, kd = carry
            # The WHOLE block runs live every step (positions consecutive,
            # mask uniform — paged_forward's T>1 contract; mixed-liveness
            # rows would be folded to inactive).  Correctness: row j
            # attends only tokens 0..j (causal), so the not-yet-drafted
            # placeholder tokens beyond j cannot reach row j's logits —
            # and the uniform mask makes each chain step the literally
            # identical program to the verify pass below.
            step_mask = jnp.broadcast_to(active[:, None], buf.shape)
            if use_kernel:
                pcache = _pool_as_cache(d_pool, table, fill)
                lg, _ = forward(
                    d_params, buf, block_pos, d_config, cache=pcache,
                    attn_mask=step_mask,
                )
            else:
                lg, _ = forward(
                    d_params, buf, block_pos, d_config, cache=d_view,
                    attn_mask=step_mask,
                )
            lgj = lax.dynamic_slice_in_dim(lg, j, 1, axis=1)[:, 0]  # [B, V]
            greedy_nxt = jnp.argmax(lgj, axis=-1).astype(jnp.int32)
            if all_greedy:
                nxt = greedy_nxt
                q = jnp.zeros((B, V), jnp.float32)  # unused
            else:
                # Row-wise _spec_impl.draft_one: key, sub = split(key);
                # draft_categorical(sub, q).
                kd, sub = _split_rows(kd)
                q = warped_probs_rows(lgj, temperature, top_p, top_k)
                sampled_nxt = jax.vmap(draft_categorical)(sub, q)
                nxt = jnp.where(temperature <= 0.0, greedy_nxt, sampled_nxt)
            buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, j + 1))
            return (buf, kd), q

        (block, _), qprobs = jax.lax.scan(
            draft_step, (block0, k_draft), jnp.arange(G, dtype=jnp.int32)
        )
        drafts = block[:, 1:]                 # [B, G]
        qprobs = jnp.swapaxes(qprobs, 0, 1)   # [B, G, V]
        # Land the block's KV in the draft pool: one verify-shaped pass
        # (replaces the old per-step writes + d_G catch-up step).
        if use_kernel:
            pcache = _pool_as_cache(d_pool, table, fill)
            _, pcache = forward(
                d_params, block, block_pos, d_config, cache=pcache,
                attn_mask=jnp.broadcast_to(active[:, None], block.shape),
                compute_logits=False,
            )
            d_pool = _cache_into_pool(d_pool, pcache)
        else:
            _, d_view = forward(
                d_params, block, block_pos, d_config, cache=d_view,
                attn_mask=jnp.broadcast_to(active[:, None], block.shape),
                compute_logits=False,
            )

        # --- 2. one target pass over [tau, d_1 .. d_G] ---
        j = jj
        if use_kernel:
            # The T=G+1 multi-token kernel pass: the target pool streams
            # ONCE for the whole verify.
            pcache = _pool_as_cache(t_pool, table, fill)
            t_logits, pcache = forward(
                t_params, block, block_pos, t_config, cache=pcache,
                attn_mask=jnp.broadcast_to(active[:, None], block.shape),
            )
            t_pool = _cache_into_pool(t_pool, pcache)
        else:
            t_logits, t_view = forward(
                t_params, block, block_pos, t_config, cache=t_view,
                attn_mask=jnp.broadcast_to(active[:, None], block.shape),
            )

        # --- 3. verification ---
        greedy_outs = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        greedy_match = drafts == greedy_outs[:, :G]
        greedy_acc = jnp.sum(
            jnp.cumprod(greedy_match.astype(jnp.int32), axis=1), axis=1
        )
        if all_greedy:
            outs, acc = greedy_outs, greedy_acc
        else:
            # Per-row Leviathan rejection sampling — the shared
            # spec_decode core with traced policies and vmapped draws;
            # greedy rows selected per-row below.
            pprobs = warped_probs_rows(t_logits, temperature, top_p, top_k)
            u = jax.vmap(lambda k: jax.random.uniform(k, (G,)))(k_accept)
            acc_s, dist = leviathan_verify(pprobs, qprobs, drafts, u)
            extra = jax.vmap(draft_categorical)(k_extra, dist)
            outs_s = place_extra(drafts, acc_s, extra)
            is_greedy = temperature <= 0.0
            outs = jnp.where(is_greedy[:, None], greedy_outs, outs_s)
            acc = jnp.where(is_greedy, greedy_acc, acc_s)
        # Non-finite guard: a row whose target logits contain NaN/Inf
        # anywhere in the verify block gets acc = -1 — the commit below
        # then invalidates every slot this round wrote for the row, and
        # the host fails just that request (acc is never negative
        # otherwise, so the sentinel cannot collide).
        acc = jnp.where(jnp.all(finite_rows(t_logits), axis=-1), acc, -1)

        if with_logprobs:
            # t_logits[:, j] is the target's raw distribution the token
            # emitted at offset j was drawn/verified from.
            lps = _token_logprob(
                t_logits.reshape(B * (G + 1), V), outs.reshape(-1)
            ).reshape(B, G + 1)
        else:
            lps = None

        # --- 4. commit: invalidate rejected slots.  Slot j holds
        # block[j] (= tau for j=0, d_j after), valid iff j <= acc; the
        # host rewinds fill to +acc+1 so rejected slots are reused, not
        # wasted.
        valid = j <= acc[:, None]
        patched = jnp.where(valid, block_pos, -1)
        if use_kernel:
            blk_i, off_i, _ = paged_write_indices(
                table, fill, active, G + 1, NB, BLK
            )
            t_pool = dataclasses.replace(
                t_pool,
                pos=paged_pool_write(t_pool.pos, patched, blk_i, off_i),
            )
            d_pool = dataclasses.replace(
                d_pool,
                pos=paged_pool_write(d_pool.pos, patched, blk_i, off_i),
            )
        else:
            rows = jnp.arange(B, dtype=jnp.int32)[:, None]
            cols = fill[:, None] + j
            t_view = dataclasses.replace(
                t_view,
                pos=t_view.pos.at[rows, cols].set(patched, mode="drop"),
            )
            d_view = dataclasses.replace(
                d_view,
                pos=d_view.pos.at[rows, cols].set(patched, mode="drop"),
            )
            t_pool = _scatter_back(
                t_pool, t_view, table, fill, active, T=G + 1
            )
            d_pool = _scatter_back(
                d_pool, d_view, table, fill, active, T=G + 1
            )
        return outs, acc, lps, keys_out, t_pool, d_pool


@functools.partial(
    jax.jit,
    static_argnames=(
        "t_config", "d_config", "n_draft", "all_greedy", "use_kernel",
        "mesh", "with_logprobs", "placed",
    ),
    donate_argnames=("t_pool", "d_pool"),
)
def _spec_round(
    t_params, d_params, t_pool, d_pool, table, n_alloc, fill, tau, pos,
    active, keys, temperature, top_p, top_k, *,
    t_config, d_config, n_draft, all_greedy, use_kernel, mesh=None,
    with_logprobs=False, placed=False,
):
    """One jitted speculative round — the classic one-dispatch-per-round
    program (``spec_rounds=1``); a thin jit wrapper over
    ``_spec_round_core`` (see its docstring for the full contract)."""
    outs, acc, lps, keys, t_pool, d_pool = _spec_round_core(
        t_params, d_params, t_pool, d_pool, table, n_alloc, fill, tau,
        pos, active, keys, temperature, top_p, top_k,
        t_config=t_config, d_config=d_config, n_draft=n_draft,
        all_greedy=all_greedy, use_kernel=use_kernel, mesh=mesh,
        with_logprobs=with_logprobs, placed=placed,
    )
    with use_mesh(mesh):
        if placed:
            t_pool = smesh.constrain_pool(t_pool)
            d_pool = smesh.constrain_pool(d_pool)
    return outs, acc, lps, keys, t_pool, d_pool


@functools.partial(
    jax.jit,
    static_argnames=(
        "t_config", "d_config", "n_draft", "n_rounds", "all_greedy",
        "use_kernel", "mesh", "with_logprobs", "placed",
    ),
    donate_argnames=(
        "t_pool", "d_pool", "fill", "tau", "tau_lp", "pos", "active",
        "remaining", "keys",
    ),
)
def _spec_rounds_chunk(
    t_params, d_params, t_pool, d_pool, table, n_alloc, fill, tau,
    tau_lp, pos, active, remaining, stops, keys, temperature, top_p,
    top_k, *, t_config, d_config, n_draft, n_rounds, all_greedy,
    use_kernel, mesh=None, with_logprobs=False, placed=False,
):
    """``n_rounds`` fused speculative rounds in ONE jitted program — the
    speculative twin of ``_paged_decode_chunk``.  Each ``lax.scan``
    iteration replays the host's classic per-round contract
    (``_step_spec`` + ``_spec_tail``) exactly, ON DEVICE:

      1. *emit* the pending token ``tau`` into the round's output row
         (column 0), recording -1 for a non-finite-sentinel row and
         ``_CHUNK_PAD`` for rows already inactive; a row whose tau hits
         its stop set / exhausts its budget folds out of ``active``
         before the round runs (the host freed the slot BEFORE the
         round in the classic loop, so it never paid for a discarded
         draft+verify);
      2. run one ``_spec_round_core`` draft+verify for the surviving
         rows (identical per-round key-split topology, warp math, and
         commit/rewind as the classic program — it IS the same traced
         function);
      3. fold the host's accepted-prefix emit scan on device
         (``spec_decode.accepted_emit_counts``): tokens ``outs[:acc]``
         emit into columns 1..acc until a stop token or the max_new
         budget lands mid-prefix, the fill/pos rewind to ``+acc+1``
         happens in-carry for rows that continue, ``outs[acc]`` becomes
         the next pending tau, and finished / non-finite rows fold out
         of the active mask for the REST of the chunk.

    The host touches the device once per CHUNK of R rounds, not once
    per round: the packed int32 block [B, R, W] carries each round's
    G+1 token columns, its acceptance count (-1 = the verify's
    non-finite sentinel, ``_CHUNK_PAD`` = row inactive that round) and,
    under ``with_logprobs``, the G+1 bitcast fp32 target logprobs —
    ONE ``np.asarray`` fetch replaces the classic loop's 2-3 fetches +
    five mirror uploads PER ROUND.  All speculative decode state
    (tau/tau_lp/fill/pos/active/remaining/keys + BOTH pools) stays
    device-resident between chunks.

    Token-identity with the classic per-round path — including the
    acceptance pattern and per-token logprobs — is pinned by
    tests/test_serving_spec.py; rounds after every row has folded out
    run masked rather than cond-skipped (same trade as
    ``_paged_decode_chunk`` — the host clamps R to the largest
    remaining budget, which bounds the dead tail)."""
    G = n_draft
    with use_mesh(mesh):

        def body(carry, _):
            (t_pool, d_pool, tau, tau_lp, fill, pos, active, remaining,
             keys) = carry
            # --- the host's step-start emit of the pending tau ---
            nonfinite = tau < 0
            hit_stop = stop_token_hits(tau, stops)
            out0 = jnp.where(
                active, jnp.where(nonfinite, -1, tau), _CHUNK_PAD
            ).astype(jnp.int32)
            out0_lp = tau_lp
            done0 = active & (nonfinite | hit_stop | (remaining <= 1))
            remaining = remaining - active.astype(jnp.int32)
            active = active & ~done0
            # --- one draft+verify round for the surviving rows ---
            outs, acc, lps_r, keys, t_pool, d_pool = _spec_round_core(
                t_params, d_params, t_pool, d_pool, table, n_alloc,
                fill, tau, pos, active, keys, temperature, top_p,
                top_k, t_config=t_config, d_config=d_config,
                n_draft=G, all_greedy=all_greedy, use_kernel=use_kernel,
                mesh=mesh, with_logprobs=with_logprobs, placed=placed,
            )
            # --- the host's accepted-prefix emit scan, on device ---
            verify_nan = active & (acc < 0)
            acc_c = jnp.clip(acc, 0, G)
            stop_hits = stop_token_hits(outs[:, :G], stops)  # [B, G]
            e, any_done = accepted_emit_counts(
                acc_c, stop_hits, remaining
            )
            i = jnp.arange(G, dtype=jnp.int32)[None, :]
            emit = (
                (i < e[:, None]) & active[:, None]
                & ~verify_nan[:, None]
            )
            out_rest = jnp.where(
                emit, outs[:, :G], _CHUNK_PAD
            ).astype(jnp.int32)
            acc_out = jnp.where(
                active, jnp.where(verify_nan, -1, acc_c), _CHUNK_PAD
            ).astype(jnp.int32)
            # --- advance / fold-out: the classic host loop's
            # fill/pos += acc+1 rewind and slot frees, in-carry ---
            cont = active & ~verify_nan & ~any_done
            adv = jnp.where(cont, acc_c + 1, 0)
            fill = fill + adv
            pos = pos + adv
            remaining = remaining - jnp.where(
                active & ~verify_nan, e, 0
            )
            new_tau = jnp.take_along_axis(
                outs, acc_c[:, None], axis=1
            )[:, 0]
            tau = jnp.where(cont, new_tau, tau)
            if with_logprobs:
                out_lp = jnp.concatenate(
                    [out0_lp[:, None], lps_r[:, :G]], axis=1
                )
                new_lp = jnp.take_along_axis(
                    lps_r, acc_c[:, None], axis=1
                )[:, 0]
                tau_lp = jnp.where(cont, new_lp, tau_lp)
            else:
                # Unused lane: keeps the scan's ys pytree shape static
                # across the with_logprobs specializations.
                out_lp = jnp.zeros((tau.shape[0], G + 1), jnp.float32)
            active = cont
            out_tok = jnp.concatenate([out0[:, None], out_rest], axis=1)
            return (
                (t_pool, d_pool, tau, tau_lp, fill, pos, active,
                 remaining, keys),
                (out_tok, acc_out, out_lp),
            )

        carry, (toks, accs, lps) = lax.scan(
            body,
            (t_pool, d_pool, tau, tau_lp, fill, pos, active, remaining,
             keys),
            None,
            length=n_rounds,
        )
        (t_pool, d_pool, tau, tau_lp, fill, pos, active, remaining,
         keys) = carry
        # Serving-mesh placement: see _chunk_scan's epilogue (the
        # ctor's placement decision already required BOTH pools inside
        # the envelope — the draft pool shards its own KV-head axis).
        if placed:
            (tau, tau_lp, fill, pos, active, remaining,
             keys) = smesh.constrain_rows(
                tau, tau_lp, fill, pos, active, remaining, keys
            )
            t_pool = smesh.constrain_pool(t_pool)
            d_pool = smesh.constrain_pool(d_pool)
        toks = jnp.moveaxis(toks, 0, 1)   # [B, R, G+1]
        accs = jnp.swapaxes(accs, 0, 1)   # [B, R]
        if with_logprobs:
            # fp32 logprobs ride bitcast to int32 alongside the tokens
            # and acceptance counts: logprobs mode still pays exactly
            # one device->host fetch per chunk.
            lp_bits = lax.bitcast_convert_type(
                jnp.moveaxis(lps, 0, 1).astype(jnp.float32), jnp.int32
            )
            packed = jnp.concatenate(
                [toks, accs[:, :, None], lp_bits], axis=2
            )  # [B, R, 2G+3]
        else:
            packed = jnp.concatenate(
                [toks, accs[:, :, None]], axis=2
            )  # [B, R, G+2]
        return (
            packed, tau, tau_lp, fill, pos, active, remaining, keys,
            t_pool, d_pool,
        )


# ---------------------------------------------------------------------------
# Jit-cache observability: the registered serving programs
# ---------------------------------------------------------------------------

# Every jitted program the serving stack dispatches (the same ten the
# analysis lowering contracts audit), by name — the source for the
# per-program ``jit_cache_entries`` gauge (/metrics) and the cost-model
# hooks below.  ``_cache_size()`` is jax's own per-function executable
# cache; a runaway entry count here is a bucketing bug re-specializing
# a program per request (the stall that used to be invisible).
def _programs() -> Dict[str, Any]:
    from .kvcache import _adopt_jit
    return {
        "_paged_decode_step": _paged_decode_step,
        "_paged_decode_chunk": _paged_decode_chunk,
        "_fused_chunk": _fused_chunk,
        "_spec_round": _spec_round,
        "_spec_rounds_chunk": _spec_rounds_chunk,
        "_paged_insert": _paged_insert,
        "_paged_suffix_insert": _paged_suffix_insert,
        "_scatter_rows": _scatter_rows,
        "_release_blocks": _release_blocks,
        "_adopt_jit": _adopt_jit,
    }


def jit_cache_entries() -> Dict[str, int]:
    """Live jit-cache entry count per registered program (-1 when the
    jax version hides the cache) — scrape-time host work only."""
    out: Dict[str, int] = {}
    for name, fn in _programs().items():
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = -1
    return out


# Process-wide static cost models (obs.CostModelCache): one entry per
# (program, geometry, static args) — written at trace time by the
# dispatch hooks below, read per dispatch as a dict hit.
_COST_MODELS = CostModelCache()

# Batcher-incarnation counter for the cost-model geometry key (see
# ContinuousBatcher.__init__).
_COST_GEOM_SEQ = itertools.count()


# ---------------------------------------------------------------------------
# Host-side batcher
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Slot:
    request_id: int
    emitted: List[int]
    max_new: int
    stop_tokens: frozenset
    blocks: List[int]
    # Leading blocks[:shared] were REUSED prefix-cache hits (KV written
    # by earlier healthy dispatches); blocks[shared:] are this request's
    # own writes — the distinction the non-finite guard needs to
    # unpublish only suspect KV.
    shared: int = 0


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@dataclasses.dataclass
class _Prefill:
    """Host view of the single in-flight fused admission (queued ->
    prefilling(off) -> decoding).  The device twins (``d_*``) are
    uploaded ONCE when the prefill starts; ``d_off`` is a donated carry
    the fused program advances on device, and ``off`` is the host's
    deterministic replay of it (off advances by exactly ``chunk`` per
    dispatch, so completion is host-computable without a fetch)."""

    slot: int
    req: "_Request"
    chain: List[bytes]
    n_share: int          # leading prefix-cache-hit blocks
    base: int             # fill0 in tokens (block multiple)
    suffix_len: int       # real suffix tokens still to prefill at start
    chunk: int            # C: prompt tokens advanced per dispatch
    off: int = 0          # suffix tokens already dispatched
    d_toks: Any = None    # [buf] int32, uploaded once
    d_off: Any = None     # int32 scalar, donated carry
    d_row: Any = None     # int32 scalar
    d_base: Any = None    # int32 scalar
    d_len: Any = None     # int32 scalar
    d_key: Any = None     # [2] uint32 request key (chain start)

    @property
    def remaining_tokens(self) -> int:
        return max(0, self.suffix_len - self.off)

    @property
    def flash(self) -> bool:
        """Host mirror of the prefill half's "auto" resolution: the
        chunk runs the flash kernel iff it is wider than
        ``FLASH_MIN_SEQ`` tokens and the config allows flash (the
        view's index is scalar, so the per-row-index must-xla rule
        never triggers here) — the shared constant keeps this mirror,
        and therefore flash_kernel fault-site firing and quarantine
        attribution, in lockstep with forward()'s actual resolution."""
        return self.chunk > FLASH_MIN_SEQ


@dataclasses.dataclass
class _Restore:
    """Host view of one in-flight swap-in (the ``restoring`` admission
    state): the request left the queue, its matched path's RESIDENT
    blocks are claimed (refcounted — eviction cannot take them), its
    demoted nodes are pinned in the host tier, fresh HBM blocks are
    allocated, and the slabs are mid-flight in ``staged``
    (``jax.device_put`` staging buffers — see ``kvcache.stage_restore``
    for why staging, not a direct pool write, is what makes the decode
    overlap real).  ``_poll_restores`` adopts the blocks into the pool
    and hands the request to ``_restored_ready`` once the transfer
    lands; decode chunks keep dispatching the whole time."""

    req: "_Request"
    chain: List[bytes]
    path: List[Any]          # kvcache.RadixNode path (resident + demoted)
    restore: List[Any]       # the demoted nodes being swapped in
    resident: List[int]      # the path's HBM-resident blocks, CLAIMED at
    #                          begin — recorded by id, not recomputed
    #                          from the nodes (a concurrent non-finite
    #                          subtree drop may null node.block)
    fresh: List[int]         # their freshly allocated HBM blocks
    staged: Dict[str, Any]   # kvcache.stage_restore buffers
    t0: float
    polls: int = 0


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: List[int]
    max_new: int
    stops: frozenset
    temperature: float
    top_p: float
    top_k: int
    seed: Optional[int]

    def blocks_needed(self, block_size: int) -> int:
        padded = _round_up(len(self.tokens), block_size)
        return -(-(padded + self.max_new) // block_size)


class ContinuousBatcher:
    """Host-side slot manager around the jitted paged step programs.

    Usage:
        cb = ContinuousBatcher(params, config, n_slots=8, max_len=2048)
        rid = cb.submit([1, 5, 9, ...], max_new_tokens=128)
        while cb.pending():
            for request_id, token, done in cb.step():
                ...stream token to the caller...

    ``n_blocks`` sizes the KV pool; the default matches contiguous
    capacity (n_slots × max_len).  A smaller pool overcommits: admission
    reserves ceil((padded_prompt + max_new) / block_size) blocks and
    requests queue until their reservation fits.

    ``decode_chunk`` fuses up to that many decode iterations per jitted
    dispatch (module docstring, "Chunked decode"): each ``step()`` call
    may emit up to K tokens per slot, token-identically to the K=1 loop,
    at one host round-trip per chunk.  1 (the default) preserves the
    classic one-dispatch-per-token behavior; serving entry points
    (run.py ``--decode-chunk``) default higher.

    Passing ``draft_params``/``draft_config`` turns on speculative
    decoding inside the batcher: each step drafts ``n_draft`` tokens per
    slot and verifies them in one target forward.  Greedy slots emit
    token-identically to the plain greedy batcher; sampled slots emit
    bit-identically to a standalone seeded ``generate_speculative`` of
    the same request (per-row Leviathan rejection sampling with per-slot
    key chains) — the draft only ever changes speed, never content (see
    ``acceptance_rate()``).

    ``spec_rounds`` is ``decode_chunk``'s speculative twin: up to that
    many draft+verify ROUNDS fuse into one jitted dispatch (module
    docstring, "Chunked speculative serving"), token-identically to the
    per-round loop — one ``step()`` may then emit up to
    R * (n_draft + 1) tokens per slot at one host round-trip per chunk.
    1 (the default) preserves the classic one-dispatch-per-round
    behavior; serving entry points (run.py ``--spec-rounds``) default
    higher.

    ``prefill_budget`` turns on fused prefill-decode scheduling (module
    docstring, "Fused prefill-decode scheduling"): warm admissions
    advance up to that many prompt tokens per chunk dispatch inside the
    decode chunk itself instead of stalling every decoding row for a
    whole-prompt prefill dispatch — token-identical to the classic
    path, first sampled token emitted by the dispatch that finishes the
    prefill.  0 (the default) keeps classic admission; serving entry
    points (run.py ``--prefill-budget``) default it on.  Ignored by
    speculative batchers.

    ``prefix_index`` picks the prefix cache's index implementation
    (module docstring, "KV capacity"): ``"radix"`` (default) shares
    partial prefixes across ALL cached chains through a block-granular
    trie; ``"exact"`` keeps the legacy flat chain map as the
    behavioral oracle; ``"off"`` ≡ ``prefix_cache=False``.
    ``host_kv_blocks`` > 0 (radix only) attaches the host-DRAM block
    tier: cold blocks demote into it instead of being freed, and
    admissions whose matched prefix was demoted swap it back in
    asynchronously through the ``restoring`` state — decode rows never
    stall on a swap-in (run.py ``--host-kv-blocks``).
    """

    def __init__(
        self,
        params: Any,
        config: LLaMAConfig,
        n_slots: int = 8,
        max_len: Optional[int] = None,
        stop_tokens: Tuple[int, ...] = (),
        temperature: float = 0.0,
        top_p: Optional[float] = None,
        top_k: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        seed: int = 0,
        block_size: Optional[int] = None,
        n_blocks: Optional[int] = None,
        draft_params: Any = None,
        draft_config: Optional[LLaMAConfig] = None,
        n_draft: int = 4,
        mesh=None,
        use_pallas_kernel: bool = True,
        logprobs: bool = False,
        prefix_cache: bool = True,
        fault_injector: Optional[FaultInjector] = None,
        decode_chunk: int = 1,
        spec_rounds: int = 1,
        prefill_budget: int = 0,
        prefix_index: str = "radix",
        host_kv_blocks: int = 0,
        obs: Optional[Observability] = None,
        cost_models: bool = False,
        prefill_kernel: Optional[str] = None,
        decode_kernel: Optional[str] = None,
    ):
        # Raw construction arguments, captured before any derivation so
        # ``rebuild()`` (crash recovery) reproduces this batcher exactly
        # — fresh pool + host state, same geometry and policies.  The
        # injector is shared across rebuilds so its call counters index
        # the process's dispatches, not one incarnation's.
        self._ctor_kwargs = dict(
            n_slots=n_slots, max_len=max_len, stop_tokens=stop_tokens,
            temperature=temperature, top_p=top_p, top_k=top_k,
            prefill_chunk=prefill_chunk, seed=seed, block_size=block_size,
            n_blocks=n_blocks, draft_params=draft_params,
            draft_config=draft_config, n_draft=n_draft, mesh=mesh,
            use_pallas_kernel=use_pallas_kernel, logprobs=logprobs,
            prefix_cache=prefix_cache, fault_injector=fault_injector,
            decode_chunk=decode_chunk, spec_rounds=spec_rounds,
            prefill_budget=prefill_budget, prefix_index=prefix_index,
            host_kv_blocks=host_kv_blocks, obs=obs,
            cost_models=cost_models, prefill_kernel=prefill_kernel,
            decode_kernel=decode_kernel,
        )
        # Device-time attribution (obs.py): static per-program cost
        # models from jit lowering's cost_analysis at the live
        # geometry.  OFF by default — computing a model costs one
        # extra trace per (program, jit-cache key), which live serving
        # amortizes over hours but a compile-bound test matrix cannot
        # (tier-1 sits at its time ceiling); run.py turns it on for
        # real serving.  Compile ATTRIBUTION (the jax.monitoring
        # listener) is always on: it is two thread-local writes per
        # dispatch.
        self.cost_models = bool(cost_models)
        _obs_mod.install_compile_listener()
        # Observability sink (obs.py): request span timelines, dispatch
        # spans, latency histograms, SLO accounting.  Always on — pure
        # host-side bookkeeping at boundaries the loop already crosses,
        # zero device dispatches / host syncs of its own (asserted by
        # make perf-smoke).  Shared across rebuilds like the injector:
        # the created instance replaces the ctor arg in _ctor_kwargs so
        # crash recovery keeps one continuous trace.
        self.obs = obs if obs is not None else Observability()
        self._ctor_kwargs["obs"] = self.obs
        self.fault_injector = fault_injector
        if fault_injector is not None and getattr(
            fault_injector, "trace_sink", None
        ) is None:
            # Injections land in the trace's annotation ring, so a
            # chaos drill's fault is visible next to the dispatch spans
            # it killed.
            fault_injector.trace_sink = self.obs.annotate
        if config.attn_impl not in ("xla", "auto"):
            raise ValueError(
                "continuous batching requires attn_impl 'xla' or 'auto' "
                "(per-row cache offsets run on the xla path)"
            )
        # Kernel selection (ops/kernels.py): ctor kwargs override the
        # config's fields; "auto" (and None-with-"auto"-config) resolves
        # HERE, once — the resolved names bake into the config (a static
        # jit argument), so every dispatch of this batcher's lifetime
        # traces against one concrete kernel choice and the jit-cache
        # key set stays ctor-stable.  "gathered" is not a kernel: it
        # maps to the paged path's existing use_pallas_kernel=False
        # escape (identical pool geometry, gathered-view attention).
        if decode_kernel == "gathered":
            use_pallas_kernel = False
            decode_kernel = "paged"
        config = config.replace(
            prefill_kernel=_kernels_mod.resolve_prefill_kernel(
                prefill_kernel or config.prefill_kernel, config
            ),
            decode_kernel=_kernels_mod.resolve_decode_kernel(
                decode_kernel or config.decode_kernel, config
            ),
        )
        if draft_config is not None:
            draft_config = draft_config.replace(
                prefill_kernel=_kernels_mod.resolve_prefill_kernel(
                    prefill_kernel or draft_config.prefill_kernel,
                    draft_config,
                ),
                decode_kernel=_kernels_mod.resolve_decode_kernel(
                    decode_kernel or draft_config.decode_kernel,
                    draft_config,
                ),
            )
        self.spec = draft_params is not None
        self.logprobs = logprobs
        if self.spec:
            if draft_config is None:
                raise ValueError("draft_params requires draft_config")
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError("target and draft must share a vocabulary")
            if n_draft < 1:
                raise ValueError("n_draft must be >= 1")
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.n_draft = n_draft
        self.params = params
        self.config = config
        self.mesh = mesh
        # False forces the gathered-view attention everywhere the kernel
        # would run — an A/B and debugging knob (bench.py uses it to
        # compare the two paths at identical block size / pool geometry).
        self.use_pallas_kernel = use_pallas_kernel
        self.n_slots = n_slots
        self.max_len = max_len or config.max_seq_len
        if block_size is None:
            # Larger blocks raise the kernel's DMA efficiency (it
            # fetches one [KVH, BLK, d] tile per table entry; on-chip
            # sweeps measured the decode step at a 16k context going
            # 8.9 -> 5.8 ms/step from 128 -> 512 blocks, and 5.5 -> 4.3
            # at 8k) at the cost of allocation granularity.  Default:
            # capacity-friendly 128-and-down short, bandwidth-friendly
            # 512 at >= 8k.  Granularity trade at the default: prompts
            # pad to a block multiple, so the longest admissible prompt
            # is max_len rounded DOWN to the block size minus max_new —
            # a request within 512 tokens of capacity needs an explicit
            # smaller block_size.
            if self.max_len >= 8192:
                block_size = 512
            else:
                block_size = min(128, max(16, self.max_len // 16))
        self.block_size = block_size
        self.blocks_per_slot = -(-self.max_len // self.block_size)
        self.n_blocks = n_blocks or n_slots * self.blocks_per_slot
        self.default_stop = frozenset(int(s) for s in stop_tokens)
        self.temperature = float(temperature)
        self.top_p = 1.0 if top_p is None else float(top_p)
        self.top_k = 0 if top_k is None else int(top_k)
        self.prefill_chunk = prefill_chunk
        self.seed = seed
        # Cost-model cache key prefix: the geometry half of the
        # jit-cache key (per-dispatch statics like K append to it).
        # A process-unique incarnation token keys per-batcher without
        # requiring config to hash — id(config) would be unsound (a
        # GC'd config's address can be reused by a new model with the
        # same geometry, silently serving stale FLOPs/bytes).  Each
        # rebuild re-lowers once per program — trace-time only.
        self._cost_geom = (
            next(_COST_GEOM_SEQ), self.n_slots, self.n_blocks,
            self.block_size, bool(logprobs), mesh is not None,
        )

        self.pool = init_pool(self.config, self.n_blocks, self.block_size)
        self.draft_pool = (
            init_pool(self.draft_config, self.n_blocks, self.block_size)
            if self.spec else None
        )
        # Serving-mesh placement (parallel/serve_mesh.py): on a
        # data x tensor serving mesh inside the placement envelope, the
        # KV pool(s) shard their KV-head axis over `tensor` and the
        # per-slot device twins shard rows over the batch axes, AT
        # CONSTRUCTION — matching the output constraints the chunk
        # programs apply, so every donated leaf aliases shard-locally
        # from the first dispatch (no per-dispatch GSPMD reshard, no
        # silent donation copy).  Meshes outside the envelope (seq or
        # stage axes, non-dividing tensor/rows) keep legacy placement
        # — GSPMD still serves them through propagation.
        self._mesh_placed = smesh.placement_ok(
            config, mesh, n_slots,
            draft_config=draft_config if self.spec else None,
        )
        if self._mesh_placed:
            self.pool = smesh.shard_pool(self.pool, mesh)
            if self.draft_pool is not None:
                self.draft_pool = smesh.shard_pool(self.draft_pool, mesh)
        self.free_blocks: List[int] = list(range(self.n_blocks))
        # Prefix cache (vLLM-style, r5): full prompt blocks are keyed by
        # a position-invariant chain hash of their tokens; admission
        # reuses a cached chain's blocks (refcounted) instead of
        # re-prefilling them, and completed requests RETAIN their keyed
        # blocks in the store's idle LRU until allocation pressure
        # evicts them — so the /chat pattern of identical system prompts
        # across sequential requests skips the shared prefill entirely.
        # Hits are token-identical to a cold batcher in the tested
        # (CPU fp32) configurations — the suffix path computes its
        # activations in a differently-shaped dispatch than a cold full
        # prefill, so on-chip bf16 identity is a parity test away, not a
        # theorem.  Enabled by default; ``prefix_cache=False`` disables
        # matching
        # and retention (refcounts still maintained — the mechanism is
        # the same, it just never hits).
        #
        # The INDEX behind the cache lives in kvcache.py
        # (``prefix_index``: "radix" — block-granular trie, partial-
        # prefix hits shared across all chains, leaves-first eviction,
        # host-tier residency; "exact" — the legacy flat chain map,
        # kept as the behavioral oracle; "off").  ``host_kv_blocks``
        # > 0 attaches the host-DRAM tier (radix only — inert
        # elsewhere, see kvcache.make_prefix_store): cold blocks
        # demote into it instead of being freed, and admissions whose
        # matched prefix includes demoted blocks swap them back in
        # asynchronously through the ``restoring`` admission state
        # (module docstring, "KV capacity").
        if prefix_index not in ("radix", "exact", "off"):
            raise ValueError(
                f"unknown prefix_index {prefix_index!r}; "
                "have ('radix', 'exact', 'off')"
            )
        if not prefix_cache:
            prefix_index = "off"
        self.prefix_index = prefix_index
        self.host_kv_blocks = max(0, int(host_kv_blocks))
        self.prefix_cache_enabled = prefix_index != "off"
        self._store = make_prefix_store(
            prefix_index, host_blocks=self.host_kv_blocks,
            on_event=self.obs.annotate,
        )
        # The store's chain digest, surfaced as a batcher attribute so
        # HTTP handler threads (/debug/kv, /healthz, /metrics) can read
        # it WITHOUT touching the thread-confined ``_store`` — the
        # digest carries its own leaf lock (kvcache.KvDigest; lockcheck
        # registered), making it the one piece of KV state that is
        # legitimately cross-thread.
        self.kv_digest = self._store.digest
        # Bytes one pool block occupies (k+v+pos+scales, draft twins
        # included) — the duplicate-chain accounting unit the router's
        # fleet cache view multiplies by.  Ctor-stable.
        self.block_bytes = pool_block_bytes(self.pool) + (
            pool_block_bytes(self.draft_pool) if self.spec else 0
        )
        self._block_refs: Dict[int, int] = {}    # block -> active users
        # In-flight swap-ins (the ``restoring`` admission state) and
        # completed ones awaiting a free slot.  ``swap_poll_min`` is a
        # determinism lever for drills/tests: it holds a READY swap-in
        # for at least that many poll intervals so the restoring
        # window is observable (0 = adopt as soon as the transfer
        # lands).
        self._restoring: List[_Restore] = []
        self._restored_ready: List[
            Tuple[_Request, List[bytes], List[int]]
        ] = []
        self.swap_poll_min = 0
        # Non-finite-guard channel: (request_id, message) pairs for
        # requests whose dispatch produced NaN/Inf logits — the slot is
        # freed immediately and the server fails just that request with
        # a clean error instead of streaming garbage (``pop_failed``).
        self.failed: List[Tuple[int, str]] = []
        self.nonfinite_rows_total = 0
        # Degradation attribution: the features (degrade.FEATURES names)
        # in play for the most recent jitted dispatch, and the union over
        # the current step() call.  The server reads the former to
        # attribute a dispatch exception and the latter to credit
        # probe successes.
        self.last_dispatch_features: Tuple[str, ...] = ()
        self.last_step_features: set = set()
        # Observability counters (exposed via the HTTP /metrics endpoint).
        self.emitted_total = 0
        self.steps_total = 0
        self.drafts_proposed = 0
        self.drafts_accepted = 0
        self.prefix_requests_hit = 0
        self.prefix_blocks_reused = 0
        # KV-capacity observability: prompt tokens admitted vs prompt
        # tokens served from cached prefix blocks (the
        # prefix_hit_tokens_ratio numerator/denominator), plus the
        # host-tier swap counters (blocks demoted D2H, blocks restored
        # H2D, cumulative swap-in latency, clean swap failures).
        self.prompt_tokens_total = 0
        self.prefix_hit_tokens_total = 0
        self.swap_out_blocks_total = 0
        self.swap_in_blocks_total = 0
        self.swap_ins_total = 0
        self.swap_in_ms_total = 0.0
        self.swap_failures_total = 0
        # Disaggregation handoff (export_prefix / import_prefix):
        # prefix blocks shipped to / landed from peer replicas, plus
        # the handoff EVENT counts (calls that moved >= 1 block — the
        # per-event ledger the KV telemetry layer exports next to the
        # digest's publish/evict/demote/restore counters).
        self.kv_export_blocks_total = 0
        self.kv_import_blocks_total = 0
        self.kv_export_events_total = 0
        self.kv_import_events_total = 0
        # Handoff hardening (r14): imports that hit the wall timeout
        # and unwound cleanly, and exported blocks demoted/dropped at
        # the source so the migration deduplicates instead of copying.
        self.kv_handoff_aborted_total = 0
        self.kv_export_demoted_blocks_total = 0
        # Host-side numpy mirrors of the per-slot decode state — the
        # AUTHORITATIVE copy for all host bookkeeping (admission
        # capacity, slot frees, replay).  The chunked decode path keeps
        # DEVICE-RESIDENT twins (``d_*`` below) that are written
        # incrementally at admission/free/cancel time via ``_scatter_rows``
        # (one dispatch per batch of dirty rows) and advanced ON DEVICE
        # by ``_paged_decode_chunk`` / ``_spec_rounds_chunk`` —
        # steady-state decode uploads nothing and fetches one packed
        # token block per chunk.  Only the CLASSIC speculative path
        # (spec_rounds=1) still uploads the mirrors per round.
        B, MB = n_slots, self.blocks_per_slot
        # Row placer: the mesh-sharded upload for [B, ...] per-slot
        # device arrays (plain jnp.asarray without placement).
        self._rows = (
            functools.partial(smesh.place_rows, mesh)
            if self._mesh_placed else jnp.asarray
        )
        self.table = np.full((B, MB), self.n_blocks, np.int32)
        self.n_alloc = np.zeros((B,), np.int32)
        self.fill = np.zeros((B,), np.int32)
        self.tau = self._rows(jnp.zeros((B,), jnp.int32))
        # Model logprob of each slot's pending tau (valid while active).
        # The numpy mirror serves the speculative emit scan; the chunked
        # path carries the device twin through the chunk program.
        self.tau_lp = np.zeros((B,), np.float32)
        self.pos = np.zeros((B,), np.int32)
        self.active = np.zeros((B,), bool)
        self.keys = self._rows(jnp.zeros((B, 2), jnp.uint32))
        self.temp_arr = np.zeros((B,), np.float32)
        self.top_p_arr = np.ones((B,), np.float32)
        self.top_k_arr = np.zeros((B,), np.int32)
        # Per-slot generation budget (max_new - emitted) and -1-padded
        # per-slot stop sets — the on-device stop detection's inputs.
        # The stop table's width grows in pow2 buckets as requests with
        # larger stop sets arrive (bounded jit-cache growth).
        self.remaining = np.zeros((B,), np.int32)
        w0 = pow2_bucket(len(self.default_stop))
        self.stop_tab = np.full((B, w0), -1, np.int32)
        # decode_chunk: max fused decode iterations per dispatch (the
        # effective K per dispatch adapts — see _pick_chunk — and is
        # always a power of two <= this).  1 = the classic one-dispatch-
        # per-token loop.
        self.decode_chunk = max(1, int(decode_chunk))
        # spec_rounds: max fused speculative draft+verify ROUNDS per
        # dispatch (the speculative twin of decode_chunk; the effective
        # R adapts through the same _pick_chunk policy).  1 = the
        # classic one-dispatch-per-round loop.
        self.spec_rounds = max(1, int(spec_rounds))
        # prefill_budget: fused prefill-decode scheduling.  > 0 admits
        # prompts that would stall mid-decode rows through _fused_chunk
        # instead of a whole-prompt _paged_insert dispatch: each chunk
        # dispatch also advances up to this many prompt tokens of at
        # most ONE in-flight admission (queued -> prefilling(off) ->
        # decoding), with the admitted row folding into the decode mask
        # the dispatch its last chunk lands.  0 (the ctor default)
        # keeps every admission on the classic whole-prompt path — the
        # parity oracle; the serving entry points (run.py
        # --prefill-budget) default it on.  A COLD pool (no row
        # mid-decode, no prefill in flight) still admits through the
        # classic batched insert even when fused: there is nobody to
        # stall, and a k-request cold burst pays one dispatch, not k
        # chunk walks.  Speculative batchers keep classic admission
        # (the spec round program has no prefill lane).
        self.prefill_budget = max(0, int(prefill_budget))
        self._pf: Optional[_Prefill] = None
        # Device-resident twins (chunked path only); row-sharded on a
        # placed serving mesh (see _mesh_placed above).
        self.d_table = self._rows(self.table)
        self.d_n_alloc = self._rows(self.n_alloc)
        self.d_fill = self._rows(self.fill)
        self.d_pos = self._rows(self.pos)
        self.d_active = self._rows(self.active)
        self.d_temps = self._rows(self.temp_arr)
        self.d_top_ps = self._rows(self.top_p_arr)
        self.d_top_ks = self._rows(self.top_k_arr)
        self.d_remaining = self._rows(self.remaining)
        self.d_stops = self._rows(self.stop_tab)
        self.d_tau_lp = self._rows(jnp.zeros((B,), jnp.float32))
        # Rows whose mirrors changed since the last device sync
        # (admission / free / cancel); flushed in one _scatter_rows
        # dispatch before the next chunk.
        self._dirty_rows: set = set()
        # Host-boundary instrumentation (asserted by make perf-smoke):
        # device->host fetches and host->device state-sync dispatches
        # performed by step()/admission — the quantities chunked decode
        # exists to amortize.
        self.host_syncs_total = 0
        self.state_uploads_total = 0
        self.decode_dispatches_total = 0
        self.decode_chunk_last = 0
        self._admit_dispatches = 0
        self._admits_at_last_chunk = 0
        # Speculative-path observability: the effective R of the most
        # recent spec dispatch, its dispatch/sync/token counters (the
        # spec twin of host_syncs_per_token), and a window of recent
        # per-dispatch (proposed, accepted) pairs so /metrics can report
        # a CURRENT acceptance rate (the lifetime ratio hides a draft
        # going stale mid-run).
        self.spec_rounds_last = 0
        self.spec_dispatches_total = 0
        self.spec_host_syncs_total = 0
        self.spec_emitted_total = 0
        self._accept_window: deque = deque(maxlen=64)
        # Fused prefill-decode observability: chunk dispatches that
        # carried a prefill lane, admissions routed through the fused
        # path, and the wall time classic whole-prompt admission
        # dispatches spent while >= 1 row was mid-decode — the decode
        # stall fused scheduling exists to eliminate (stays ~0 with
        # prefill_budget > 0; approximate on the suffix path, whose
        # dispatch is async).
        self.prefill_chunks_total = 0
        self.fused_admissions_total = 0
        self.decode_stall_ms_total = 0.0

        self.slots: Dict[int, Optional[_Slot]] = {
            b: None for b in range(n_slots)
        }
        self.queue: List[_Request] = []
        self._next_id = 0

    # -- public API ---------------------------------------------------------

    def rebuild(self) -> "ContinuousBatcher":
        """Fresh batcher with this one's construction: new KV pool and
        host-side slot/queue/cache state from the still-held params (the
        jitted step programs are cached per-function, so no recompile).
        The crash-recovery path: after a dispatch exception the old
        instance's device state is suspect; callers resubmit every
        in-flight request (prompt + delivered tokens as the new prompt)
        against the rebuilt instance and drop this one.  (The
        degradation layer does NOT go through this method — it rebuilds
        from the server-retained original ctor state with fallback
        substitutions, see ``LLMServer._build_batcher``.)"""
        return ContinuousBatcher(
            self.params, self.config, **self._ctor_kwargs
        )

    def default_seed(self, rid: int) -> int:
        """The PRNG seed a request without an explicit one derives from
        the pool seed and its id (the exact mix ``_request_key`` uses).
        Exposed so a recovery layer can pin a replayed request to its
        original chain start instead of a new id's derivation."""
        return (self.seed * 1000003 + rid) & 0x7FFFFFFF

    def _fault(self, site: str) -> None:
        """Named fault-injection hook (no-op without an injector)."""
        if self.fault_injector is not None:
            self.fault_injector.fire(site)

    def _record_dispatch(self, features: Sequence[str]) -> None:
        """Note which degradable features the NEXT jitted dispatch
        exercises (set before the site hooks fire, so an exception out
        of either the hook or the dispatch itself is attributable)."""
        self.last_dispatch_features = tuple(features)
        self.last_step_features.update(features)

    def _dispatch_cost(
        self, program: str, key: Tuple, lower,
    ) -> Tuple[Optional[float], Optional[float]]:
        """Per-dispatch attribution hook, called right before a jitted
        program runs: (1) names ``program`` as this thread's compile
        attribution (so a jit-cache miss during the call books its
        backend-compile duration onto our obs sink), and (2) when cost
        models are enabled, returns the program's static
        (flops, bytes_accessed) at the live geometry — computed ONCE
        per (program, geometry, key) via ``lower().cost_analysis()``
        (``lower`` closes over the exact dispatch args), a dict hit on
        every later dispatch.  Never a device dispatch or host sync
        either way."""
        _obs_mod.attribute_compiles(self.obs, program)
        if not self.cost_models:
            return None, None
        cost = _COST_MODELS.get(program, self._cost_geom + tuple(key),
                                lower)
        return (None, None) if cost is None else cost

    def _take_nan(self) -> bool:
        """Consume an armed ``nan`` fault (the non-finite guard's test
        lever); no-op without an injector."""
        return (
            self.fault_injector is not None
            and self.fault_injector.take_nan()
        )

    def pop_failed(self) -> List[Tuple[int, str]]:
        """Drain (request_id, message) for requests failed by the
        non-finite guard since the last call.  Their slots and blocks
        are already freed; the server maps these to per-request HTTP
        errors."""
        out, self.failed = self.failed, []
        return out

    def _fail_slot(
        self, b: int, message: str, device_done: bool = False
    ) -> None:
        """Fail slot ``b``'s request with ``message``: record it for
        ``pop_failed`` and free the slot.  The request's freshly written
        prompt blocks are UNPUBLISHED from the prefix index first — KV
        produced by a dispatch that emitted non-finite logits must never
        be retained for future cache hits.  Reused hit blocks
        (``slot.shared`` leading ones) hold earlier healthy dispatches'
        KV and stay published — dropping a popular shared system
        prompt's chain over one poisoned suffix would cold-prefill the
        whole fleet.  ``device_done`` — see ``_free_slot``."""
        slot = self.slots[b]
        assert slot is not None
        stranded: List[int] = []
        for blk in slot.blocks[slot.shared:]:
            stranded.extend(self._store.unpublish(blk))
        # A radix unpublish drops the node's SUBTREE too (deeper shared
        # chain blocks only reachable through the suspect node); idle
        # retained blocks stranded by that go back to the free list.
        self._invalidate_and_free(stranded)
        self.failed.append((slot.request_id, message))
        self.nonfinite_rows_total += 1
        self.obs.request_end(slot.request_id, "failed", message)
        self._free_slot(b, device_done=device_done)

    def submit(
        self,
        prompt_tokens: Sequence[int],
        max_new_tokens: int = 256,
        stop_tokens: Optional[Tuple[int, ...]] = None,
        temperature: Optional[float] = None,
        top_p: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
    ) -> int:
        """Queue a request; returns its id.  Tokens only — tokenize first.

        temperature/top_p/top_k default to the pool-level policy; ``seed``
        starts the request's own PRNG chain (default: derived from the
        pool seed and request id).
        """
        if not prompt_tokens:
            raise ValueError("empty prompt")
        # Capacity covers the BLOCK-PADDED prompt: admission pads the
        # prompt to a block multiple and the row's write offset starts
        # there.
        padded = _round_up(len(prompt_tokens), self.block_size)
        if padded + max_new_tokens > self.max_len:
            # Name the padding lever: near-capacity requests that fit
            # unpadded are admissible with a smaller explicit block_size
            # (the >= 8k default is 512 for DMA efficiency — see
            # __init__), and users must be able to self-diagnose that.
            raise ValueError(
                f"prompt ({len(prompt_tokens)} tokens, padded to {padded} "
                f"= a multiple of block_size={self.block_size}) + "
                f"max_new ({max_new_tokens}) exceeds per-request capacity "
                f"{self.max_len}"
                + (
                    "; the unpadded request fits - construct the batcher "
                    "with a smaller block_size to admit it"
                    if len(prompt_tokens) + max_new_tokens <= self.max_len
                    else ""
                )
            )
        rid = self._next_id
        self._next_id += 1
        req = _Request(
            rid=rid,
            tokens=list(prompt_tokens),
            max_new=max_new_tokens,
            stops=(
                self.default_stop if stop_tokens is None
                else frozenset(int(s) for s in stop_tokens)
            ),
            temperature=(
                self.temperature if temperature is None
                else float(temperature)
            ),
            top_p=self.top_p if top_p is None else float(top_p),
            top_k=self.top_k if top_k is None else int(top_k),
            seed=seed,
        )
        if req.blocks_needed(self.block_size) > self.n_blocks:
            raise ValueError(
                f"request needs {req.blocks_needed(self.block_size)} "
                f"blocks; the pool has {self.n_blocks} total"
            )
        # Queue only — admission happens at the next step() boundary, so
        # a burst of submits is admitted as ONE batched prefill dispatch
        # instead of k serialized ones.
        self.queue.append(req)
        self.obs.request_queued(rid, len(req.tokens))
        return rid

    def pending(self) -> bool:
        return (
            bool(self.queue)
            or bool(self._restoring)
            or bool(self._restored_ready)
            or any(s is not None for s in self.slots.values())
        )

    def cancel(self, request_id: int, outcome: str = "cancelled",
               error: Optional[str] = None) -> bool:
        """Abort a request: dequeue it, or free its slot and blocks
        mid-generation.  Returns False if the id is unknown (already
        finished or never submitted).

        ``outcome`` names the terminal state the request's timeline
        records — "cancelled" (default; client disconnects and explicit
        cancels) or "failed" (the server's deadline reaper passes it
        for timeouts, which the metric registry counts as failures,
        never cancellations).

        Like every batcher method, this must be called from the thread
        that owns the batcher (the serving loop); the HTTP server's
        handler threads never call it directly — they set a flag the
        loop's reap scan acts on.
        """
        for i, req in enumerate(self.queue):
            if req.rid == request_id:
                del self.queue[i]
                self.obs.request_end(request_id, outcome, error)
                return True
        for r in self._restoring:
            if r.req.rid == request_id:
                # Mid-swap cancel: the staged transfer may still be in
                # flight, but nothing was scattered into the pool yet —
                # release the claims, return the fresh blocks, and let
                # the nodes fall back to host residency (slab intact).
                self._restoring.remove(r)
                self._abort_restore(r)
                self.obs.request_end(request_id, outcome, error)
                return True
        for i, (req, chain, hits) in enumerate(self._restored_ready):
            if req.rid == request_id:
                # Restored but not yet admitted: blocks are adopted and
                # claimed — unclaim them back into the idle LRU.
                del self._restored_ready[i]
                self._unclaim_blocks(hits)
                self.obs.request_end(request_id, outcome, error)
                return True
        for b, slot in self.slots.items():
            if slot is not None and slot.request_id == request_id:
                self._free_slot(b)
                self.obs.request_end(request_id, outcome, error)
                return True
        return False

    def acceptance_rate(self) -> float:
        """Fraction of proposed draft tokens accepted (speculative mode)."""
        if not self.drafts_proposed:
            return 0.0
        return self.drafts_accepted / self.drafts_proposed

    def describe(self) -> Dict[str, Any]:
        """Ctor-stable configuration snapshot — the ``config`` section
        of the ``/debug/bundle`` flight-recorder artifact (server.py).
        Reads only geometry/policy values fixed at construction (the
        mutable knobs — live prefill_budget under a brownout, live
        occupancy — belong to stats()/healthz), so it is safe from any
        thread without a pragma."""
        kw = self._ctor_kwargs
        return {
            "n_slots": self.n_slots,
            "max_len": self.max_len,
            "block_size": self.block_size,
            "n_blocks": self.n_blocks,
            "block_bytes": self.block_bytes,
            "decode_chunk": int(kw["decode_chunk"]),
            "spec_rounds": int(kw["spec_rounds"]),
            "speculative": self.spec,
            "n_draft": self.n_draft if self.spec else 0,
            "prefill_budget": int(kw["prefill_budget"]),
            "prefix_index": self.prefix_index,
            "host_kv_blocks": self.host_kv_blocks,
            "logprobs": self.logprobs,
            "use_pallas_kernel": bool(kw["use_pallas_kernel"]),
            "cost_models": self.cost_models,
            "serve_mesh": smesh.mesh_shape(
                self.mesh if self._mesh_placed else None
            ),
        }

    def stats(self) -> Dict[str, float]:
        """Counters for observability (the HTTP /metrics endpoint).

        Runs on HTTP handler threads while the serving loop owns the
        batcher: every read below is a point-in-time snapshot of
        single-writer state (GIL-consistent; a scrape may be one step
        stale, never torn).  ``_pf`` is snapshotted into a local first
        — the loop can null it between a check and a dereference (the
        TOCTOU the lock-discipline checker flagged)."""
        # audit: racy-read(point-in-time /metrics snapshot of
        # single-writer loop state; stale by <= 1 step, never torn)
        pf = self._pf
        dg = self.kv_digest.summary()  # lock-guarded, O(1)
        out: Dict[str, float] = {} if self.fault_injector is None else (
            dict(self.fault_injector.stats())
        )
        # audit: racy-read(point-in-time /metrics snapshot of
        # single-writer loop state; stale by <= 1 step, never torn)
        out.update({
            "emitted_tokens_total": self.emitted_total,
            "decode_steps_total": self.steps_total,
            "active_slots": sum(
                s is not None for s in self.slots.values()
            ),
            "queued_requests": len(self.queue),
            "free_blocks": len(self.free_blocks),
            "total_blocks": self.n_blocks,
            "drafts_proposed_total": self.drafts_proposed,
            "drafts_accepted_total": self.drafts_accepted,
            "draft_acceptance_rate": self.acceptance_rate(),
            # "prefix_cached_blocks" predates the radix index and is
            # KEPT as an alias of the store's idle resident count so
            # existing dashboards don't break.
            "prefix_cached_blocks": self._store.cached_blocks(),
            "prefix_requests_hit_total": self.prefix_requests_hit,
            "prefix_blocks_reused_total": self.prefix_blocks_reused,
            # KV-capacity subsystem (kvcache.py): radix index size,
            # the fraction of admitted prompt tokens served from
            # cached prefix blocks, and the host-tier swap ledger
            # (blocks demoted D2H / restored H2D, in-flight swap-ins,
            # cumulative swap-in wall time, clean per-request swap
            # failures).
            "radix_nodes_total": self._store.nodes_total(),
            "prefix_hit_tokens_ratio": (
                self.prefix_hit_tokens_total
                / max(1, self.prompt_tokens_total)
            ),
            "host_kv_blocks": self.host_kv_blocks,
            "host_tier_blocks": self._store.host_blocks(),
            "swap_queue_depth": len(self._restoring),
            "swap_ins_total": self.swap_ins_total,
            "swap_in_blocks_total": self.swap_in_blocks_total,
            "swap_out_blocks_total": self.swap_out_blocks_total,
            "swap_in_ms_total": round(self.swap_in_ms_total, 3),
            "swap_failures_total": self.swap_failures_total,
            # Chain-digest surface (kvcache.KvDigest, its own leaf
            # lock): digest versions for staleness detection plus the
            # per-event publish/evict/demote/restore ledger — the
            # replica half of the fleet cache view.
            "kv_digest_version": dg["version"],
            "kv_digest_loss_version": dg["loss_version"],
            "kv_publish_events_total": dg["publishes_total"],
            "kv_evict_events_total": dg["evictions_total"],
            "kv_demote_events_total": dg["demotions_total"],
            "kv_restore_events_total": dg["restores_total"],
            "kv_host_evict_events_total": dg["host_evictions_total"],
            "kv_block_bytes": self.block_bytes,
            # Disaggregation handoff ledger + serving-mesh shape (1/1
            # off-mesh AND on unplaced meshes — the gauge reports the
            # sharding actually ACTIVE, not the mesh the batcher was
            # handed; the router's aggregate view labels these per
            # replica).
            "kv_export_blocks_total": self.kv_export_blocks_total,
            "kv_import_blocks_total": self.kv_import_blocks_total,
            "kv_export_events_total": self.kv_export_events_total,
            "kv_import_events_total": self.kv_import_events_total,
            "kv_handoff_aborted_total": self.kv_handoff_aborted_total,
            "kv_export_demoted_blocks_total": (
                self.kv_export_demoted_blocks_total
            ),
            "serve_mesh_data": (
                smesh.mesh_shape(self.mesh)["data"]
                if self._mesh_placed else 1
            ),
            "serve_mesh_tensor": (
                smesh.mesh_shape(self.mesh)["tensor"]
                if self._mesh_placed else 1
            ),
            "nonfinite_rows_total": self.nonfinite_rows_total,
            # Chunked-decode observability: the effective K of the most
            # recent chunk dispatch, dispatch count, and the host-
            # boundary traffic the chunking amortizes (syncs per emitted
            # token trends toward 1/K in steady state).
            "decode_chunk_size": self.decode_chunk_last,
            "decode_dispatches_total": self.decode_dispatches_total,
            "host_syncs_total": self.host_syncs_total,
            "state_uploads_total": self.state_uploads_total,
            "host_syncs_per_token": (
                self.host_syncs_total / max(1, self.emitted_total)
            ),
            # Speculative-path observability (zero / empty when the
            # batcher has no draft model): the effective R of the most
            # recent fused spec dispatch, its host-boundary cost per
            # emitted token, and the acceptance rate over the recent
            # dispatch window (the lifetime draft_acceptance_rate above
            # cannot show a draft going stale mid-run).
            "spec_rounds_per_dispatch": self.spec_rounds_last,
            "spec_dispatches_total": self.spec_dispatches_total,
            "spec_host_syncs_per_token": (
                self.spec_host_syncs_total
                / max(1, self.spec_emitted_total)
            ),
            "spec_window_acceptance_rate": self._window_acceptance(),
            # Fused prefill-decode scheduling (zero / empty with
            # prefill_budget=0): prompt tokens of the in-flight
            # admission still to prefill, chunk dispatches that carried
            # a prefill lane, admissions routed through the fused path,
            # and the cumulative decode stall classic whole-prompt
            # admissions cost (≈0 once fused scheduling is on).
            "prefill_budget": self.prefill_budget,
            "prefill_tokens_inflight": (
                pf.remaining_tokens if pf is not None else 0
            ),
            "prefill_chunks_total": self.prefill_chunks_total,
            "fused_admissions_total": self.fused_admissions_total,
            "decode_stall_ms_total": round(self.decode_stall_ms_total, 3),
        })
        return out

    def _window_acceptance(self) -> float:
        """Acceptance rate over the recent spec-dispatch window.

        Called from /metrics handler threads: iterating the live deque
        while the loop appends raises RuntimeError mid-scrape, so take
        an atomic ``list()`` snapshot first (C-level copy under the
        GIL) — the race the lock-discipline checker flagged."""
        # audit: racy-read(atomic list() snapshot of the single-writer
        # window; a scrape may miss the newest dispatch, never crash)
        window = list(self._accept_window)
        proposed = sum(p for p, _ in window)
        if not proposed:
            return 0.0
        return sum(a for _, a in window) / proposed

    def kv_debug_json(self, depth: Optional[int] = None,
                      max_nodes: int = 2048,
                      since: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /debug/kv[?since=V]`` payload: the chain digest's
        bounded tree walk (per-node chain-prefix hash / depth /
        residency tier / refcount flag / recency) plus the O(1)
        summary with this replica's cache geometry.  With ``since``,
        the INCREMENTAL form: the digest's journaled mutations past
        version V (``{"events": [...], "version": V2}``) so the
        router's global radix index syncs at O(changes) per poll; when
        the bounded journal cannot prove completeness (consumer too
        far behind, or a rebuild reset the digest) the reply falls
        back to the full walk tagged ``"resync": true``.  Safe from
        HTTP handler threads: it reads ONLY the lock-guarded digest
        (kvcache.KvDigest) and ctor-stable geometry scalars, plus two
        single-writer token counters whose point-in-time reads are the
        same /metrics snapshot contract ``stats()`` documents — never
        the thread-confined store or pool."""
        if since is not None:
            got = self.kv_digest.events_since(since)
            if got is not None:
                events, version = got
                out: Dict[str, Any] = {
                    "version": version, "since": since,
                    "events": events,
                }
                out["summary"] = self._kv_summary()
                return out
        out = self.kv_digest.nodes_json(depth=depth, max_nodes=max_nodes)
        if since is not None:
            out["resync"] = True
        out["summary"] = self._kv_summary()
        return out

    def _kv_summary(self) -> Dict[str, Any]:
        """The /debug/kv ``summary`` section: digest aggregates plus
        ctor-stable cache geometry (same cross-thread safety argument
        as ``kv_debug_json``)."""
        summary = self.kv_digest.summary()
        summary.update({
            "prefix_index": self.prefix_index,
            "block_size": self.block_size,
            "block_bytes": self.block_bytes,
            "total_blocks": self.n_blocks,
            "host_kv_blocks": self.host_kv_blocks,
            # audit: racy-read(point-in-time snapshot of single-writer
            # hit counters; stale by <= 1 admission, never torn — the
            # fleet view's hit-ratio numerator/denominator)
            "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
            "prompt_tokens_total": self.prompt_tokens_total,
        })
        return summary

    def step(self) -> List[Tuple]:
        """One decode dispatch for every active slot.

        Returns [(request_id, token, done)] for tokens emitted this call
        — up to the effective chunk size K per slot on the chunked path
        (``decode_chunk`` > 1), up to ``n_draft + 1`` per slot in
        speculative mode.  With ``logprobs=True`` each tuple carries a
        4th element: the token's model logprob (fp32 log-softmax of the
        raw logits — what ``engine.score`` reports for the position).
        Finished slots free their blocks and queued requests are
        admitted for the NEXT step.

        Chunked decode contract (non-speculative path): one call runs K
        fused decode iterations inside a single jitted program
        (``_paged_decode_chunk``), with stop-token / max_new / non-finite
        handling ON DEVICE, and pays exactly one device->host fetch (the
        packed token block).  Batcher state lives device-resident; the
        host mirrors advance by replaying the block.  K adapts: 1 when
        an admission just landed, <= _QUEUED_CHUNK_CAP while the queue
        holds capacity-blocked requests (slot turnaround / admission
        latency), up to ``decode_chunk`` (pow2, clamped to the largest
        remaining budget) once slots are steady.
        """
        self.last_step_features = set()
        # Fused scheduling routes warm admissions through the chunk
        # dispatch itself (no insert program), so the deferred-error
        # barrier below — which exists to keep attribution on a CLASSIC
        # insert dispatch — must not fire for them: it would re-add the
        # per-dispatch host sync chunking removed.
        classic_admission_possible = not (
            self._fused_scheduling()
            and (self._pf is not None or bool(np.any(self.active)))
        )
        if (
            classic_admission_possible
            and (self.queue or self._restoring or self._restored_ready)
            and any(s is not None for s in self.slots.values())
            and any(s is None for s in self.slots.values())
        ):
            # Deferred-error barrier, only when _admit is about to
            # record NEW dispatches: jax dispatch is async, so the
            # previous step's device error can surface at the next host
            # sync — which must happen while ``last_dispatch_features``
            # still names the dispatch that produced it, not after
            # admission overwrites the attribution record.  Admissions
            # are rare relative to steps, so the extra [B] fetch stays
            # off the steady-state hot path.  A completed swap-in can
            # admit through the same classic insert program even with
            # the queue empty (``_restored_ready``), so in-flight and
            # landed restores arm the barrier too.
            # audit: host-fetch(deferred-error barrier before admission
            # overwrites dispatch attribution; counted)
            np.asarray(self.tau)
            self.host_syncs_total += 1
        self._admit()
        if not any(s is not None for s in self.slots.values()):
            return []
        if self.spec:
            return self._step_spec()
        return self._step_chunked()

    _NONFINITE_MSG = (
        "non-finite logits: the model produced NaN/Inf for "
        "this request; it was aborted (server healthy)"
    )

    # Chunk clamp while the queue is capacity-blocked: small enough that
    # a finishing slot is detected within a few iterations (bounded
    # admission latency for the queue head), large enough that a
    # SATURATED server — the normal high-throughput regime, where the
    # queue is never empty — still amortizes the per-dispatch host
    # overhead instead of reverting to one dispatch per token.
    _QUEUED_CHUNK_CAP = 4

    def _pick_chunk(self, admitted: bool, cap: Optional[int] = None) -> int:
        """Effective K for the next chunk dispatch.  K=1 right after an
        admission (the fresh request's first token should not wait out a
        full chunk); K <= _QUEUED_CHUNK_CAP while the queue holds
        capacity-blocked requests (their admission waits on a slot
        finishing, which the host only learns at a chunk boundary);
        otherwise the largest power of two <= min(cap, max remaining
        budget) — pow2 throughout, so the jit cache holds O(log cap)
        chunk programs.  ``cap`` defaults to ``decode_chunk``; the
        speculative path passes ``spec_rounds`` (each round emits at
        least one token, so clamping R by the token budget bounds the
        dead masked tail the same way it does for K).

        ``admitted`` only counts CLASSIC whole-prompt admissions: a
        fused admission's first token is sampled inside the chunk
        dispatch chain itself, so K no longer collapses to 1 while a
        prefill rides along — exactly when a burst is hammering the
        server (the queued clamp below still bounds the queue head's
        wait on a finishing slot)."""
        cap = self.decode_chunk if cap is None else cap
        if cap <= 1 or admitted:
            return 1
        rem = max(
            s.max_new - len(s.emitted)
            for s in self.slots.values() if s is not None
        )
        k = max(1, min(cap, rem))
        if self.queue:
            k = min(k, self._QUEUED_CHUNK_CAP)
        return 1 << (k.bit_length() - 1)

    def _sync_device_rows(self) -> None:
        """Flush host-side per-row state changes (admission / free /
        cancel) to the device-resident twins in ONE ``_scatter_rows``
        dispatch.  No dirty rows (the steady state) -> no upload."""
        if not self._dirty_rows:
            return
        if self.d_stops.shape != self.stop_tab.shape:
            # Stop-table width grew (pow2-bucketed): rebuild the device
            # twin wholesale before the row scatter — admission-time
            # only, and the array is [B, S] ints.
            self.d_stops = self._rows(self.stop_tab)
        rows = sorted(self._dirty_rows)
        self._dirty_rows.clear()
        R = len(rows)
        Rb = pow2_bucket(R)  # pow2 jit-cache bucket
        idx = np.full((Rb,), self.n_slots, np.int32)  # pads drop
        idx[:R] = rows

        def take(a: np.ndarray) -> jnp.ndarray:
            out = np.zeros((Rb,) + a.shape[1:], a.dtype)
            out[:R] = a[rows]
            return jnp.asarray(out)

        state = (
            self.d_table, self.d_n_alloc, self.d_fill, self.d_pos,
            self.d_active, self.d_temps, self.d_top_ps, self.d_top_ks,
            self.d_remaining, self.d_stops,
        )
        self._dispatch_cost(
            "_scatter_rows", (Rb, self.d_stops.shape),
            lambda: _scatter_rows.lower(
                state,
                jax.ShapeDtypeStruct(idx.shape, idx.dtype),
                tuple(
                    jax.ShapeDtypeStruct((Rb,) + a.shape[1:], a.dtype)
                    for a in state
                ),
            ),
        )
        (self.d_table, self.d_n_alloc, self.d_fill, self.d_pos,
         self.d_active, self.d_temps, self.d_top_ps, self.d_top_ks,
         self.d_remaining, self.d_stops) = _scatter_rows(
            (self.d_table, self.d_n_alloc, self.d_fill, self.d_pos,
             self.d_active, self.d_temps, self.d_top_ps, self.d_top_ks,
             self.d_remaining, self.d_stops),
            jnp.asarray(idx),
            (take(self.table), take(self.n_alloc), take(self.fill),
             take(self.pos), take(self.active), take(self.temp_arr),
             take(self.top_p_arr), take(self.top_k_arr),
             take(self.remaining), take(self.stop_tab)),
        )
        self.state_uploads_total += 1

    def _step_chunked(self) -> List[Tuple]:
        """Non-speculative step: one fused K-iteration chunk dispatch,
        one packed fetch, then the host replays the block to advance its
        mirrors and emit events.  While an admission is mid-prefill
        (``self._pf``) the dispatch is ``_fused_chunk`` — the same K
        decode iterations PLUS one bounded prefill chunk, same packed
        fetch — so decoding rows keep emitting while the prompt lands,
        and K does NOT collapse to 1 (fused admissions never set the
        ``admitted`` reset; the first token rides this dispatch chain
        regardless of K)."""
        # CLASSIC admissions since the last chunk dispatch — including
        # one this step() performed at the PREVIOUS call's trailing
        # _admit().  Fused admissions perform no insert dispatch, so
        # they neither owe the error barrier nor reset K.
        admitted = self._admit_dispatches > self._admits_at_last_chunk
        if admitted:
            # Surface any async admission-dispatch error NOW, while
            # last_dispatch_features still names the insert (the chunk's
            # _record_dispatch below would otherwise steal attribution).
            # audit: host-fetch(post-admission error barrier; counted)
            np.asarray(self.tau)
            self.host_syncs_total += 1
        self._admits_at_last_chunk = self._admit_dispatches
        pf = self._pf
        if pf is not None and not bool(np.any(self.active)):
            # Nothing is decoding: the scan half would be all-masked
            # forwards, so keep it minimal while the prefill advances.
            K = 1
        else:
            K = self._pick_chunk(admitted)
        self._sync_device_rows()
        # Injection site "step": fires BEFORE the chunk dispatch; an
        # exception out of the dispatch (or its packed fetch below)
        # reaches the caller with nothing appended to slot.emitted or
        # delivered — recovery replays from the server's delivered-token
        # record, exactly as in the K=1 contract (a mid-prefill request
        # replays from its prompt + delivered tokens like any other).
        # The paged_kernel site fires once per CHUNK dispatch, not per
        # token; when a prefill chunk rides along on the flash path the
        # flash_kernel site fires too (same dispatch, finer
        # attribution — a flash quarantine rebuilds onto attn_impl=xla
        # and the replayed admission continues on the gathered path).
        feats: List[str] = []
        if self.use_pallas_kernel and _kernel_eligible(
            self.block_size, self.mesh, self.config.kv_heads,
            self.n_slots,
        ):
            feats.append("paged_kernel")
            # Host mirror of the _block static predicate: the stock
            # kernel serves the chunk's T=1 decode steps whenever the
            # paged path is live, the config selects it, and the pool
            # is full-precision (int8 stays on the custom kernel).  A
            # stock_paged quarantine rebuilds onto decode_kernel=
            # "paged" — the CUSTOM kernel, not the gathered view.
            if (
                self.config.decode_kernel == "stock-paged"
                and not self.pool.quantized
            ):
                feats.append("stock_paged")
        pf_flash = (
            pf is not None and pf.flash
            and self.config.attn_impl in ("auto", "flash")
        )
        if pf_flash:
            feats.append("flash_attention")
        self._record_dispatch(feats)
        self._fault("step")
        if pf is not None:
            # Site "prefill_chunk": indexes prefill-CARRYING dispatches
            # only, so drills can land a fault mid-prefill
            # deterministically (plain decode chunks do not advance its
            # counter).
            self._fault("prefill_chunk")
        if pf_flash:
            self._fault("flash_kernel")
        if "paged_kernel" in feats:
            self._fault("paged_kernel")
        if "stock_paged" in feats:
            self._fault("stock_paged_kernel")
        self.steps_total += K
        self.decode_dispatches_total += 1
        self.decode_chunk_last = K
        # Dispatch-span bookkeeping (obs.py): capture the riding rids,
        # prompt tokens this dispatch will advance, and the wall clock
        # BEFORE the dispatch — recorded after the packed fetch, so the
        # span covers submit through sync (pure host bookkeeping; the
        # 1-fetch/0-upload contract is unchanged).
        obs_rids = [
            s.request_id for s in self.slots.values() if s is not None
        ]
        pf_adv = 0 if pf is None else min(pf.chunk, pf.remaining_tokens)
        pf_done_rid: Optional[int] = None
        all_greedy = bool(np.all(self.temp_arr[self.active] == 0.0))
        if pf is not None:
            # The prefilling request samples inside the program, so the
            # greedy specialization must account for its policy too.
            all_greedy = all_greedy and pf.req.temperature <= 0.0
        # Compile attribution + static cost model (obs.py): named
        # BEFORE the dispatch so a jit-cache miss books onto the right
        # program; the lower thunk closes over the exact live args
        # (trace-time only — a dict hit once cached).
        if pf is None:
            prog = "_paged_decode_chunk"
            cost_fl, cost_by = self._dispatch_cost(
                prog, (K, all_greedy),
                lambda: _paged_decode_chunk.lower(
                    self.params, self.pool, self.d_table,
                    self.d_n_alloc, self.d_fill, self.tau,
                    self.d_tau_lp, self.d_pos, self.d_active,
                    self.d_remaining, self.d_stops, self.keys,
                    self.d_temps, self.d_top_ps, self.d_top_ks,
                    config=self.config, n_iter=K,
                    all_greedy=all_greedy, mesh=self.mesh,
                    allow_kernel=self.use_pallas_kernel,
                    with_logprobs=self.logprobs,
                    placed=self._mesh_placed,
                ),
            )
        else:
            prog = "_fused_chunk"
            cost_fl, cost_by = self._dispatch_cost(
                prog, (K, pf.chunk, all_greedy),
                lambda: _fused_chunk.lower(
                    self.params, self.pool, self.d_table,
                    self.d_n_alloc, self.d_fill, self.tau,
                    self.d_tau_lp, self.d_pos, self.d_active,
                    self.d_remaining, self.d_stops, self.keys,
                    self.d_temps, self.d_top_ps, self.d_top_ks,
                    pf.d_row, pf.d_toks, pf.d_len, pf.d_base, pf.d_off,
                    pf.d_key,
                    config=self.config, n_iter=K, pf_chunk=pf.chunk,
                    all_greedy=all_greedy, mesh=self.mesh,
                    allow_kernel=self.use_pallas_kernel,
                    with_logprobs=self.logprobs,
                    placed=self._mesh_placed,
                ),
            )
        t0_obs = time.monotonic()
        if pf is None:
            (packed, self.tau, self.d_tau_lp, self.d_fill, self.d_pos,
             self.d_active, self.d_remaining, self.keys,
             self.pool) = _paged_decode_chunk(
                self.params, self.pool, self.d_table, self.d_n_alloc,
                self.d_fill, self.tau, self.d_tau_lp, self.d_pos,
                self.d_active, self.d_remaining, self.d_stops, self.keys,
                self.d_temps, self.d_top_ps, self.d_top_ks,
                config=self.config, n_iter=K, all_greedy=all_greedy,
                mesh=self.mesh, allow_kernel=self.use_pallas_kernel,
                with_logprobs=self.logprobs, placed=self._mesh_placed,
            )
        else:
            (packed, self.tau, self.d_tau_lp, self.d_fill, self.d_pos,
             self.d_active, self.d_remaining, self.keys, self.pool,
             pf.d_off) = _fused_chunk(
                self.params, self.pool, self.d_table, self.d_n_alloc,
                self.d_fill, self.tau, self.d_tau_lp, self.d_pos,
                self.d_active, self.d_remaining, self.d_stops, self.keys,
                self.d_temps, self.d_top_ps, self.d_top_ks,
                pf.d_row, pf.d_toks, pf.d_len, pf.d_base, pf.d_off,
                pf.d_key,
                config=self.config, n_iter=K, pf_chunk=pf.chunk,
                all_greedy=all_greedy, mesh=self.mesh,
                allow_kernel=self.use_pallas_kernel,
                with_logprobs=self.logprobs, placed=self._mesh_placed,
            )
            self.prefill_chunks_total += 1
            pf.off += pf.chunk
            if pf.off >= pf.suffix_len:
                # Prefill complete: the device already folded the row
                # into the decode state mid-dispatch (and the scan below
                # emitted its first token); catch the host mirrors up —
                # device_done semantics, no dirty marking — and publish
                # the request's freshly written full prompt blocks
                # (only now do they hold the whole chain's KV).
                b = pf.slot
                self.fill[b] = _round_up(
                    len(pf.req.tokens), self.block_size
                )
                self.pos[b] = len(pf.req.tokens)
                self.active[b] = True
                slot = self.slots[b]
                # FULL chain, not the suffix: the radix publish walk
                # starts at the root, so a suffix-only publication
                # after a partial hit would parent the new nodes at
                # the root under mid-chain keys — unreachable for
                # matching (extensions never hit) and depth-wrong in
                # the digest.  The hit prefix re-publishes as a no-op
                # (existing resident nodes keep their block) and
                # supplies the correct parent chain.
                self._register_chain(
                    slot.blocks[: len(pf.chain)], pf.chain,
                )
                pf_done_rid = pf.req.rid
                self._pf = None
        # THE one device->host sync of the chunk: tokens (+ bitcast
        # logprobs) in a single packed array.
        tf_obs = time.monotonic()
        # audit: host-fetch(the one packed [B, K] fetch per chunk; counted)
        arr = np.asarray(packed)
        self.host_syncs_total += 1
        now_obs = time.monotonic()
        self.obs.record_dispatch(
            # Per-kernel MXU attribution: a stock-paged pure-decode
            # chunk books under its own kind, so llm_mxu_utilization
            # {kind="decode:stock-paged"} vs {kind="decode"} IS the live
            # A/B gauge.  Fused chunks keep one kind — their FLOPs mix
            # prefill and decode, so splitting them per-kernel would
            # attribute flash work to the decode kernel.
            kind=(
                ("decode:stock-paged" if "stock_paged" in feats
                 else "decode")
                if pf_adv == 0 else "fused"
            ),
            k=K, occupancy=len(obs_rids), prefill_tokens=pf_adv,
            wall_ms=(now_obs - t0_obs) * 1000.0,
            fetch_ms=(now_obs - tf_obs) * 1000.0,
            swap_inflight=len(self._restoring), rids=obs_rids,
            program=prog, flops=cost_fl, bytes_accessed=cost_by,
        )
        if pf_done_rid is not None:
            # The prefill's last chunk linked into the prefilling span
            # above; the first token it sampled opens the decoding span.
            self.obs.begin_span(pf_done_rid, "decoding")
        toks = arr[0]
        lps = arr[1].view(np.float32) if self.logprobs else None

        out: List[Tuple] = []
        forced_nan = self._take_nan()
        for b, slot in self.slots.items():
            if slot is None:
                continue
            if forced_nan:
                # An armed ``nan`` fault (chaos drills) poisons the
                # first active row, exactly like the K=1 emit scan; the
                # row's chunk tokens are discarded (the request fails
                # with a clean error either way).
                forced_nan = False
                self._fail_slot(b, self._NONFINITE_MSG)
                continue
            advanced = 0
            ended = False
            for i in range(toks.shape[1]):
                tok = int(toks[b, i])
                if tok == _CHUNK_PAD:
                    break
                if tok < 0:
                    # On-device non-finite sentinel: the device already
                    # folded the row out of the chunk; fail just this
                    # request (tokens before the sentinel were emitted).
                    self._fail_slot(
                        b, self._NONFINITE_MSG, device_done=True
                    )
                    ended = True
                    break
                slot.emitted.append(tok)
                self.emitted_total += 1
                done = (
                    tok in slot.stop_tokens
                    or len(slot.emitted) >= slot.max_new
                )
                if self.logprobs:
                    out.append((
                        slot.request_id, tok, done, float(lps[b, i])
                    ))
                else:
                    out.append((slot.request_id, tok, done))
                if done:
                    # The device made the same call mid-chunk (stop set
                    # and budget live on device), so the row is already
                    # inactive there — no deactivation upload needed.
                    self.obs.request_end(slot.request_id, "finished")
                    self._free_slot(b, device_done=True)
                    ended = True
                    break
                advanced += 1
            if not ended:
                # Mirror advance by replay: the device ran one forward
                # per emitted-and-continued token.
                self.fill[b] += advanced
                self.pos[b] += advanced
                self.remaining[b] = slot.max_new - len(slot.emitted)
        self._admit()
        return out

    def _step_spec(self) -> List[Tuple]:
        """Speculative step.  With ``spec_rounds`` > 1 the fused
        R-round chunk path (``_step_spec_chunked``) runs: R draft+verify
        rounds per jitted dispatch, state device-resident, one packed
        fetch per chunk.  The default (``spec_rounds=1``) keeps the
        classic one-round-per-dispatch loop below, with its per-round
        mirror uploads — the parity oracle the chunked path is pinned
        against (tests/test_serving_spec.py)."""
        if self.spec_rounds > 1:
            return self._step_spec_chunked()
        # Emit each active slot's current tau; free finished slots BEFORE
        # the round so a completing request doesn't pay for one more
        # forward whose output would be discarded.
        out: List[Tuple] = []
        # audit: host-fetch(classic spec path: per-round pending-tau
        # emit fetch; counted)
        taus = np.asarray(self.tau)
        self.host_syncs_total += 1
        self.spec_host_syncs_total += 1
        # Non-finite guard: a -1 tau is the step programs' sentinel for
        # "this row's logits contained NaN/Inf" — fail just that request
        # with a clean error instead of streaming a garbage token.  An
        # armed ``nan`` fault (chaos drills) poisons the first active
        # row the same way.
        forced_nan = self._take_nan()
        for b, slot in self.slots.items():
            if slot is None:
                continue
            tok = int(taus[b])
            if tok < 0 or forced_nan:
                forced_nan = False
                self._fail_slot(b, self._NONFINITE_MSG)
                continue
            slot.emitted.append(tok)
            self.emitted_total += 1
            self.spec_emitted_total += 1
            done = (
                tok in slot.stop_tokens
                or len(slot.emitted) >= slot.max_new
            )
            if self.logprobs:
                out.append((
                    slot.request_id, tok, done, float(self.tau_lp[b])
                ))
            else:
                out.append((slot.request_id, tok, done))
            if done:
                self.obs.request_end(slot.request_id, "finished")
                self._free_slot(b, device_done=True)

        if any(s is not None for s in self.slots.values()):
            # Injection site "step": fires AFTER the emit/free scan above
            # — exactly where a real dispatch failure lands, with this
            # step's events already appended to slot.emitted but never
            # returned to the caller.  Recovery must therefore replay
            # from the tokens it DELIVERED, not from slot.emitted (the
            # server keeps its own per-request token record).
            # The kernel/spec sites fire after "step" (same dispatch,
            # finer attribution: their exceptions carry a site name the
            # degradation layer maps to a quarantinable feature).
            feats: List[str] = ["spec_decode"]
            if self._spec_kernel_ok():
                feats.append("paged_kernel")
                # Stock kernel serves the DRAFT model's T=1 steps (the
                # target's T=G+1 verify keeps the custom kernel's
                # multi-token sweep — the _block predicate is static on
                # T), so the feature keys on the draft config/pool.
                if (
                    self.draft_config.decode_kernel == "stock-paged"
                    and not self.draft_pool.quantized
                ):
                    feats.append("stock_paged")
            self._record_dispatch(feats)
            self._fault("step")
            self._fault("spec_decode")
            if "paged_kernel" in feats:
                self._fault("paged_kernel")
            if "stock_paged" in feats:
                self._fault("stock_paged_kernel")
            self.steps_total += 1
            self.spec_dispatches_total += 1
            self.spec_rounds_last = 1
            self._spec_tail(out)
        self._admit()
        return out

    def _step_spec_chunked(self) -> List[Tuple]:
        """Speculative step, fused: ONE ``_spec_rounds_chunk`` dispatch
        runs R draft+verify rounds with the pending-tau emit, the
        accepted-prefix emit scan, stop/max_new/non-finite folding and
        the fill rewind all ON DEVICE; the host gets one packed
        [B, R, W] block (each round's G+1 token columns + its
        acceptance count + bitcast logprobs) in ONE fetch and replays
        it to advance the mirrors and produce the caller's events —
        token-identically (including the acceptance pattern) to the
        classic per-round loop.  Both pools and all per-slot decode
        state are device-resident via the ``d_*`` twins; admission /
        free / cancel sync dirty rows exactly as in ``_step_chunked``,
        so steady state = 1 fetch + 0 uploads per R rounds."""
        admitted = self._admit_dispatches > self._admits_at_last_chunk
        if admitted:
            # Surface any async admission-dispatch error NOW, while
            # last_dispatch_features still names the insert (see
            # _step_chunked).
            # audit: host-fetch(post-admission error barrier; counted)
            np.asarray(self.tau)
            self.host_syncs_total += 1
            self.spec_host_syncs_total += 1
        self._admits_at_last_chunk = self._admit_dispatches
        R = self._pick_chunk(admitted, cap=self.spec_rounds)
        self._sync_device_rows()
        # Fault sites and dispatch attribution fire once per CHUNK
        # dispatch, not once per round — an aborted chunk delivers
        # nothing, so recovery replays all R rounds from the server's
        # delivered-token record, exactly as in the chunked-decode
        # contract.
        feats: List[str] = ["spec_decode"]
        if self._spec_kernel_ok():
            feats.append("paged_kernel")
            # Draft T=1 steps ride the stock kernel when selected (see
            # _step_spec for the target-verify split).
            if (
                self.draft_config.decode_kernel == "stock-paged"
                and not self.draft_pool.quantized
            ):
                feats.append("stock_paged")
        self._record_dispatch(feats)
        self._fault("step")
        self._fault("spec_decode")
        if "paged_kernel" in feats:
            self._fault("paged_kernel")
        if "stock_paged" in feats:
            self._fault("stock_paged_kernel")
        self.steps_total += R
        self.decode_dispatches_total += 1
        self.spec_dispatches_total += 1
        self.decode_chunk_last = R
        self.spec_rounds_last = R
        obs_rids = [
            s.request_id for s in self.slots.values() if s is not None
        ]
        all_greedy = bool(np.all(self.temp_arr[self.active] == 0.0))
        cost_fl, cost_by = self._dispatch_cost(
            "_spec_rounds_chunk", (R, all_greedy),
            lambda: _spec_rounds_chunk.lower(
                self.params, self.draft_params, self.pool,
                self.draft_pool, self.d_table, self.d_n_alloc,
                self.d_fill, self.tau, self.d_tau_lp, self.d_pos,
                self.d_active, self.d_remaining, self.d_stops,
                self.keys, self.d_temps, self.d_top_ps, self.d_top_ks,
                t_config=self.config, d_config=self.draft_config,
                n_draft=self.n_draft, n_rounds=R,
                all_greedy=all_greedy,
                use_kernel=self._spec_kernel_ok(), mesh=self.mesh,
                with_logprobs=self.logprobs, placed=self._mesh_placed,
            ),
        )
        t0_obs = time.monotonic()
        (packed, self.tau, self.d_tau_lp, self.d_fill, self.d_pos,
         self.d_active, self.d_remaining, self.keys, self.pool,
         self.draft_pool) = _spec_rounds_chunk(
            self.params, self.draft_params, self.pool, self.draft_pool,
            self.d_table, self.d_n_alloc, self.d_fill, self.tau,
            self.d_tau_lp, self.d_pos, self.d_active, self.d_remaining,
            self.d_stops, self.keys, self.d_temps, self.d_top_ps,
            self.d_top_ks,
            t_config=self.config, d_config=self.draft_config,
            n_draft=self.n_draft, n_rounds=R, all_greedy=all_greedy,
            use_kernel=self._spec_kernel_ok(), mesh=self.mesh,
            with_logprobs=self.logprobs, placed=self._mesh_placed,
        )
        # THE one device->host sync of the chunk: tokens, acceptance
        # counts and (bitcast) logprobs in a single packed array.
        tf_obs = time.monotonic()
        # audit: host-fetch(the one packed [B, R, W] fetch per spec
        # chunk; counted)
        arr = np.asarray(packed)  # [B, R, W]
        self.host_syncs_total += 1
        self.spec_host_syncs_total += 1
        now_obs = time.monotonic()
        self.obs.record_dispatch(
            kind="spec", k=R, occupancy=len(obs_rids),
            wall_ms=(now_obs - t0_obs) * 1000.0,
            fetch_ms=(now_obs - tf_obs) * 1000.0,
            swap_inflight=len(self._restoring), rids=obs_rids,
            program="_spec_rounds_chunk", flops=cost_fl,
            bytes_accessed=cost_by,
        )
        G = self.n_draft
        toks = arr[:, :, : G + 1]
        accs = arr[:, :, G + 1]
        lps = arr[:, :, G + 2:].view(np.float32) if self.logprobs else None

        out: List[Tuple] = []
        round_proposed = round_accepted = 0
        forced_nan = self._take_nan()
        for b, slot in self.slots.items():
            if slot is None:
                continue
            if forced_nan:
                # An armed ``nan`` fault poisons the first active row,
                # exactly like the classic emit scan; the row's chunk
                # tokens are discarded.
                forced_nan = False
                self._fail_slot(b, self._NONFINITE_MSG)
                continue
            fill_adv = 0
            ended = False
            for r in range(R):
                # Column 0: the round's pending-tau emit.
                tok0 = int(toks[b, r, 0])
                if tok0 == _CHUNK_PAD:
                    # Row folded out before this round (every later
                    # round is PAD too).
                    break
                if tok0 < 0:
                    # On-device non-finite sentinel on the pending
                    # token (admission produced NaN/Inf logits).
                    self._fail_slot(
                        b, self._NONFINITE_MSG, device_done=True
                    )
                    ended = True
                    break
                slot.emitted.append(tok0)
                self.emitted_total += 1
                self.spec_emitted_total += 1
                done = (
                    tok0 in slot.stop_tokens
                    or len(slot.emitted) >= slot.max_new
                )
                if self.logprobs:
                    out.append((
                        slot.request_id, tok0, done, float(lps[b, r, 0])
                    ))
                else:
                    out.append((slot.request_id, tok0, done))
                if done:
                    # The device made the same call before running the
                    # round (stop set and budget live on device), so
                    # the row is already inactive there.
                    self.obs.request_end(slot.request_id, "finished")
                    self._free_slot(b, device_done=True)
                    ended = True
                    break
                a = int(accs[b, r])
                assert a >= -1, (b, r, a)  # PAD here would mean the
                # device and host disagreed on liveness — impossible
                # while both fold on the same stop/budget inputs.
                if a < 0:
                    # _spec_rounds_chunk's verify non-finite sentinel:
                    # the round was never committed (all written slots
                    # invalidated in-jit) — fail just this request.
                    self._fail_slot(
                        b, self._NONFINITE_MSG, device_done=True
                    )
                    ended = True
                    break
                self.drafts_proposed += G
                self.drafts_accepted += a
                round_proposed += G
                round_accepted += a
                # Columns 1..a: the round's accepted drafts (the device
                # already blanked everything past a mid-prefix
                # stop/budget hit to _CHUNK_PAD; the host re-detects
                # done from its own stop sets, exactly like
                # _step_chunked's replay).
                for i in range(a):
                    tok = int(toks[b, r, 1 + i])
                    if tok == _CHUNK_PAD:
                        break
                    slot.emitted.append(tok)
                    self.emitted_total += 1
                    self.spec_emitted_total += 1
                    done = (
                        tok in slot.stop_tokens
                        or len(slot.emitted) >= slot.max_new
                    )
                    if self.logprobs:
                        out.append((
                            slot.request_id, tok, done,
                            float(lps[b, r, 1 + i]),
                        ))
                    else:
                        out.append((slot.request_id, tok, done))
                    if done:
                        self.obs.request_end(
                            slot.request_id, "finished"
                        )
                        self._free_slot(b, device_done=True)
                        ended = True
                        break
                if ended:
                    break
                # The round committed a+1 pool slots (tau + accepted
                # drafts; outs[a] is the next pending tau) — the fill
                # rewind the device already applied in-carry.
                fill_adv += a + 1
            if not ended:
                self.fill[b] += fill_adv
                self.pos[b] += fill_adv
                self.remaining[b] = slot.max_new - len(slot.emitted)
        if round_proposed:
            self._accept_window.append((round_proposed, round_accepted))
        self._admit()
        return out

    def _spec_kernel_ok(self) -> bool:
        """Same kernel-eligibility gate as _paged_decode_step — literally:
        both call ``_kernel_eligible`` (the T>1 verify adds no
        constraints, it shards identically; the draft model adds its own
        KV-head divisibility)."""
        return self.use_pallas_kernel and _kernel_eligible(
            self.block_size, self.mesh, self.config.kv_heads,
            self.n_slots, draft_config=self.draft_config,
        )

    def _spec_tail(self, out: List[Tuple]) -> None:
        """Speculative remainder of a step: draft + verify, emit the
        accepted prefix (appended to ``out``, with per-token logprobs
        when ``logprobs=True``), rewind fills past rejected slots."""
        obs_rids = [
            s.request_id for s in self.slots.values() if s is not None
        ]
        all_greedy = bool(np.all(self.temp_arr[self.active] == 0.0))

        def _sds(a):
            # Aval-only stand-ins for the mirrors the classic path
            # uploads per round: lowering needs shapes/dtypes, never
            # the bytes — the cost hook must not add uploads.
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        cost_fl, cost_by = self._dispatch_cost(
            "_spec_round", (all_greedy,),
            lambda: _spec_round.lower(
                self.params, self.draft_params, self.pool,
                self.draft_pool, _sds(self.table), _sds(self.n_alloc),
                _sds(self.fill), self.tau, _sds(self.pos),
                _sds(self.active), self.keys, _sds(self.temp_arr),
                _sds(self.top_p_arr), _sds(self.top_k_arr),
                t_config=self.config, d_config=self.draft_config,
                n_draft=self.n_draft, all_greedy=all_greedy,
                use_kernel=self._spec_kernel_ok(), mesh=self.mesh,
                with_logprobs=self.logprobs, placed=self._mesh_placed,
            ),
        )
        t0_obs = time.monotonic()
        outs, acc, lps, self.keys, self.pool, self.draft_pool = _spec_round(
            self.params, self.draft_params, self.pool, self.draft_pool,
            jnp.array(self.table), jnp.array(self.n_alloc),
            jnp.array(self.fill), self.tau, jnp.array(self.pos),
            jnp.array(self.active), self.keys,
            jnp.array(self.temp_arr), jnp.array(self.top_p_arr),
            jnp.array(self.top_k_arr),
            t_config=self.config, d_config=self.draft_config,
            n_draft=self.n_draft, all_greedy=all_greedy,
            use_kernel=self._spec_kernel_ok(), mesh=self.mesh,
            with_logprobs=self.logprobs, placed=self._mesh_placed,
        )
        tf_obs = time.monotonic()
        # audit: host-fetch(classic spec path: per-round outs fetch; counted)
        outs = np.asarray(outs)
        # audit: host-fetch(classic spec path: per-round acceptance fetch;
        # counted)
        acc = np.asarray(acc)
        self.host_syncs_total += 2
        self.spec_host_syncs_total += 2
        if self.logprobs:
            # audit: host-fetch(classic spec path: per-round logprobs
            # fetch; counted)
            lps = np.asarray(lps)
            self.host_syncs_total += 1
            self.spec_host_syncs_total += 1
        now_obs = time.monotonic()
        self.obs.record_dispatch(
            kind="spec", k=1, occupancy=len(obs_rids),
            wall_ms=(now_obs - t0_obs) * 1000.0,
            fetch_ms=(now_obs - tf_obs) * 1000.0,
            swap_inflight=len(self._restoring), rids=obs_rids,
            program="_spec_round", flops=cost_fl,
            bytes_accessed=cost_by,
        )
        round_proposed = round_accepted = 0
        # NOTE: the per-row fill/pos advances below touch the numpy
        # mirrors only — the CLASSIC (spec_rounds=1) path re-uploads
        # them every round and never consumes the chunked paths'
        # device-resident twins.
        new_tau = np.zeros((self.n_slots,), np.int32)
        for b, slot in self.slots.items():
            if slot is None:
                continue
            a = int(acc[b])
            if a < 0:
                # _spec_round's non-finite sentinel: the row's verify
                # logits held NaN/Inf; its round was never committed
                # (all slots invalidated in-jit) — fail just this
                # request.
                self._fail_slot(b, self._NONFINITE_MSG)
                continue
            self.drafts_proposed += self.n_draft
            self.drafts_accepted += a
            round_proposed += self.n_draft
            round_accepted += a
            # Emit accepted drafts outs[0..a-1] (== the draft tokens);
            # outs[a] becomes the next pending token, mirroring the plain
            # batcher's sampled-but-unemitted tau.
            done = False
            for i in range(a):
                tok = int(outs[b, i])
                slot.emitted.append(tok)
                self.emitted_total += 1
                self.spec_emitted_total += 1
                done = (
                    tok in slot.stop_tokens
                    or len(slot.emitted) >= slot.max_new
                )
                if self.logprobs:
                    out.append((
                        slot.request_id, tok, done, float(lps[b, i])
                    ))
                else:
                    out.append((slot.request_id, tok, done))
                if done:
                    break
            if done:
                self.obs.request_end(slot.request_id, "finished")
                self._free_slot(b)
            else:
                new_tau[b] = outs[b, a]
                if self.logprobs:
                    # The pending token's logprob travels with it: emitted
                    # at the next step() from tau_lp, exactly like the
                    # plain batcher's sampled-but-unemitted tau.
                    self.tau_lp[b] = float(lps[b, a])
                self.fill[b] += a + 1
                self.pos[b] += a + 1
        if round_proposed:
            self._accept_window.append((round_proposed, round_accepted))
        self.tau = jnp.asarray(new_tau)

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drain everything; returns {request_id: emitted tokens}."""
        results: Dict[int, List[int]] = {}
        while self.pending():
            for rid, tok, *_ in self.step():
                results.setdefault(rid, []).append(tok)
        return results

    # -- internals ----------------------------------------------------------

    def _capacity(self) -> int:
        """Allocatable blocks: truly free + evictable cached prefixes."""
        return len(self.free_blocks) + self._store.evictable()

    def _demote_block(self, blk: int) -> Dict[str, Any]:
        """Host-tier demotion D2H: fetch block ``blk``'s KV image (plus
        the draft pool's twin under speculative serving) to host numpy
        BEFORE the allocator invalidates its positions.  Admission-path
        only — never on the decode hot path — and counted separately
        from ``host_syncs_total`` (that counter is the chunked decode
        loop's contract; demotion is capacity traffic)."""
        slab = fetch_slab(self.pool, blk)
        if self.spec:
            slab.update(fetch_slab(self.draft_pool, blk, prefix="d_"))
        self.swap_out_blocks_total += 1
        return slab

    def _alloc_blocks(self, n: int) -> List[int]:
        """Pop n blocks, evicting LRU cached-prefix blocks when the free
        list runs dry — into the host-DRAM tier when one is attached
        (the block's KV demotes and its radix node stays matchable),
        dropped outright otherwise.  Evicted blocks' POSITIONS are
        invalidated here: retained blocks keep valid pos (future
        reusers need them), but a block re-purposed as part of a DECODE
        reservation is only overwritten up to the prompt span — a stale
        pos >= 0 in the beyond-the-prompt region would be attended as a
        live slot."""
        self._fault("alloc")
        out: List[int] = []
        evicted: List[int] = []
        for _ in range(n):
            if self.free_blocks:
                out.append(self.free_blocks.pop(0))
            else:
                blk, extra = self._store.pop_evictable(self._demote_block)
                assert blk is not None, "allocation past capacity"
                evicted.append(blk)
                out.append(blk)
                if extra:
                    # A forced subtree drop (host-LRU victim / stranded
                    # suffix) orphaned additional idle blocks: back to
                    # the free list, positions invalidated.
                    self._invalidate_and_free(extra)
        if evicted:
            # More evictions than one slot's span is impossible in one
            # call (n <= blocks_per_slot), but stay defensive.
            self._invalidate_evicted(evicted)
        return out

    def _invalidate_evicted(self, evicted: List[int]) -> None:
        """Invalidate repurposed blocks' pool positions (batched; pads
        drop) — AFTER any demotion fetch, which needs them live."""
        for start in range(0, len(evicted), self.blocks_per_slot):
            ids = np.full(
                (self.blocks_per_slot,), self.n_blocks, np.int32
            )
            chunk = evicted[start:start + self.blocks_per_slot]
            ids[: len(chunk)] = chunk
            self._dispatch_cost(
                "_release_blocks", (ids.shape[0],),
                lambda: _release_blocks.lower(
                    self.pool.pos,
                    jax.ShapeDtypeStruct(ids.shape, ids.dtype),
                ),
            )
            self.pool = dataclasses.replace(
                self.pool,
                # audit: host-upload(eviction-batch id upload on the
                # admission/capacity path, never per-token)
                pos=_release_blocks(self.pool.pos, jnp.asarray(ids)),
            )
            if self.spec:
                self.draft_pool = dataclasses.replace(
                    self.draft_pool,
                    # audit: host-upload(draft-pool twin of the above)
                    pos=_release_blocks(
                        self.draft_pool.pos, jnp.asarray(ids)
                    ),
                )

    def demote_idle(self, n: int) -> int:
        """Proactively demote up to ``n`` idle cached-prefix blocks into
        the host tier, freeing HBM without dropping cache content (the
        pressure path does the same thing lazily inside
        ``_alloc_blocks``; this is the operational lever — and the
        deterministic one for drills).  No-op without a tier; returns
        the number of blocks demoted."""
        if self.host_kv_blocks <= 0 or self._store.kind != "radix":
            return 0
        count = 0
        drained: List[int] = []
        for _ in range(n):
            if not self._store.evictable():
                break
            blk, extra = self._store.pop_evictable(self._demote_block)
            if blk is None:
                break
            drained.append(blk)
            drained.extend(extra)
            count += 1
        # One batched invalidation for the whole sweep: per-block
        # _release_blocks dispatches would pay the ~100ms tunnel
        # latency once per demoted block.
        self._invalidate_and_free(drained)
        return count

    def _invalidate_and_free(self, blocks: List[int]) -> None:
        """Return blocks to the free list with their pool positions
        invalidated (a stale pos >= 0 in a re-purposed block's
        beyond-the-prompt region would be attended as live KV)."""
        if not blocks:
            return
        self._invalidate_evicted(blocks)
        self.free_blocks.extend(blocks)

    # -- prefill/decode disaggregation handoff ------------------------------

    def resident_chain_keys(self) -> List[List[bytes]]:
        """Every maximal HBM-resident cached chain, as ordered key
        lists in the shared ``chain_keys`` schema — the drain
        enumeration surface: a scale-down controller asks the victim
        (via ``call_on_loop``) what it holds, then ``export_prefix``-es
        each returned chain to a survivor.  Pure host bookkeeping
        (store tree walk, no device ops), but thread-confined like
        everything on the batcher."""
        if not self.prefix_cache_enabled:
            return []
        return self._store.resident_chains()

    def export_prefix(
        self, tokens: Optional[Sequence[int]] = None,
        request_id: Optional[str] = None,
        *,
        keys: Optional[Sequence[bytes]] = None,
        max_bytes: Optional[int] = None,
        demote_after_export: bool = False,
    ) -> Tuple[List[bytes], List[Dict[str, Any]]]:
        """Disaggregation handoff, PREFILL side: the longest
        HBM-resident cached chain prefix of ``tokens`` fetched as host
        slabs (``kvcache.fetch_slab``; the draft pool's twins ride
        along under speculative serving).  A prefill replica serves a
        request once (publishing its chain), exports here, and a
        decode replica ``import_prefix``-es the slabs so the session's
        next turn admits there as a plain prefix hit — the same
        fetch/adopt primitives the host-DRAM tier uses, pointed across
        replicas instead of across memory tiers (router.py owns the
        orchestration).  Returns ``(chain_keys, slabs)``; empty when
        the prefix cache is off or nothing is resident.

        ``keys`` passes precomputed chain-prefix keys instead of
        tokens (the router schedules handoffs from its global radix
        index, which speaks keys — ``router.chain_keys`` is the shared
        schema).  ``max_bytes`` bounds the slab payload (block-aligned
        truncation from the root — a partial prefix is still a valid
        chain).  ``demote_after_export=True`` demotes the exported
        chain's IDLE blocks to the host tier (or drops idle leaf
        blocks with no tier) so a migration *reduces* fleet duplicate
        KV bytes instead of growing them; claimed blocks never move
        (radix index only — the exact oracle keeps its chains).

        Must run on the thread that owns this batcher (the D2H fetch
        is admission-class traffic, like demotion — never on the
        decode hot path)."""
        if not self.prefix_cache_enabled:
            return [], []
        if keys is None:
            assert tokens is not None, "export_prefix needs tokens or keys"
            keys = self._chain_keys(tokens, self.block_size)
        else:
            keys = list(keys)
        match = self._match_prefix(keys)
        blocks = match.blocks
        if max_bytes is not None and self.block_bytes > 0:
            blocks = blocks[: max(0, max_bytes // self.block_bytes)]
        slabs: List[Dict[str, Any]] = []
        for blk in blocks:
            slab = fetch_slab(self.pool, blk)
            if self.spec:
                slab.update(fetch_slab(self.draft_pool, blk, prefix="d_"))
            slabs.append(slab)
        self.kv_export_blocks_total += len(slabs)
        if slabs:
            self.kv_export_events_total += 1
        if demote_after_export and slabs:
            self.demote_exported(
                keys[: len(slabs)], slabs, request_id=request_id,
            )
        # Fleet-trace link: the instant event carries the EXTERNAL
        # request id (when the handoff orchestrator knows it), so the
        # router's merged /debug/trace ties this replica's export to
        # the peer's import of the same session.
        self.obs.annotate(
            "prefix_export", blocks=len(slabs), request_id=request_id,
        )
        return list(keys[: len(slabs)]), slabs

    def demote_exported(
        self, keys: Sequence[bytes],
        slabs: Optional[Sequence[Dict[str, Any]]] = None,
        request_id: Optional[str] = None,
    ) -> int:
        """Deduplicate after handoff: demote the exported chain's IDLE
        blocks to the host tier (or drop idle leaf blocks with no
        tier) so the migration *reduces* fleet duplicate KV bytes.
        The router's scheduler calls this as its OWN control step only
        after the copy landed on the peer — decoupled from the export
        so an abandoned or failed handoff never costs the fleet its
        only HBM-resident copy.  ``slabs`` are the export's already-
        fetched host images, reused for tier insertion instead of a
        second D2H fetch of the identical blocks.  Radix index only
        (the exact oracle keeps its chains); claimed blocks never
        move.  Returns the number of blocks that left HBM."""
        if not self.prefix_cache_enabled or self._store.kind != "radix":
            return 0
        keys = list(keys)
        slab_by_key: Dict[bytes, Dict[str, Any]] = (
            dict(zip(keys, slabs)) if slabs else {}
        )

        def fetch(blk: int) -> Dict[str, Any]:
            node = self._store._by_block.get(blk)
            slab = (
                slab_by_key.get(node.key) if node is not None else None
            )
            if slab is not None:
                self.swap_out_blocks_total += 1
                return slab
            return self._demote_block(blk)

        freed = self._store.demote_keys(
            keys, fetch if self.host_kv_blocks > 0 else None,
        )
        self.kv_export_demoted_blocks_total += len(freed)
        self._invalidate_and_free(freed)
        if freed:
            self.obs.annotate(
                "prefix_demote_after_export", blocks=len(freed),
                request_id=request_id,
            )
        return len(freed)

    def import_prefix(
        self, keys: Sequence[bytes], slabs: Sequence[Dict[str, Any]],
        request_id: Optional[str] = None,
        *,
        max_bytes: Optional[int] = None,
        timeout_s: Optional[float] = None,
    ) -> int:
        """Disaggregation handoff, DECODE side: land exported slabs in
        this batcher's pool (alloc + ``kvcache.stage_restore`` +
        ``adopt_into_pool`` — the host-tier swap-in path with the slabs
        arriving from a peer instead of this replica's own tier) and
        publish the chain, so the next admission of those tokens is a
        prefix hit.  Blocks already resident here are skipped;
        truncates to pool capacity (and to ``max_bytes`` when given —
        block-aligned from the root, so a partial landing is still a
        valid chain prefix).  Synchronous (admission-class, on the
        owning thread); returns the number of blocks landed.

        ``timeout_s`` bounds the staged H2D transfer wall time: past
        the deadline the import UNWINDS cleanly — fresh blocks freed
        with positions invalidated, matched blocks unclaimed, NOTHING
        published (a partial publish would advertise KV that never
        landed) — ``kv_handoff_aborted_total`` counts it, and
        :class:`TimeoutError` raises so the scheduler can tell an
        abort from the benign already-resident no-op (return 0).
        Without the bound a wedged transfer would hold allocated
        blocks indefinitely."""
        if not self.prefix_cache_enabled or not slabs:
            return 0
        keys = list(keys)[: len(slabs)]
        have = self._store.match(keys).blocks
        todo = list(slabs)[len(have):len(keys)]
        if max_bytes is not None and self.block_bytes > 0:
            todo = todo[: max(0, max_bytes // self.block_bytes)]
        if not todo:
            return 0
        # Claim the matched resident blocks BEFORE allocating — the
        # same discipline every admission path follows: idle matched
        # blocks are exactly what _alloc_blocks evicts first, and an
        # evicted-then-republished id would bind the old chain key to
        # another chain's KV (silent wrong-token corruption).
        self._claim_blocks(have)
        try:
            cap = self._capacity()
            if len(todo) > cap:
                todo = todo[:cap]
            if not todo:
                return 0
            fresh = self._alloc_blocks(len(todo))
            staged = stage_restore(
                todo, fresh, self.n_blocks,
                placements=(
                    smesh.staging_shardings(self.mesh, list(todo[0]))
                    if self._mesh_placed else None
                ),
            )
            if timeout_s is not None:
                # Bounded wait: poll the staged transfers (non-blocking
                # is_ready, the swap-in path's own probe) against the
                # wall deadline; a wedge unwinds instead of pinning
                # the allocation forever.  Raises (rather than
                # returning 0) so the scheduler can tell an ABORT from
                # the benign already-resident/no-capacity no-op.
                deadline = time.monotonic() + timeout_s
                while not restore_ready(staged):
                    if time.monotonic() >= deadline:
                        self.kv_handoff_aborted_total += 1
                        self._invalidate_and_free(fresh)
                        self.obs.annotate(
                            "prefix_import_aborted",
                            blocks=len(todo),
                            request_id=request_id,
                            timeout_s=timeout_s,
                        )
                        raise TimeoutError(
                            f"prefix import: staged transfer of "
                            f"{len(todo)} block(s) not ready within "
                            f"{timeout_s}s (unwound cleanly)"
                        )
                    time.sleep(0.001)
            # audit: host-fetch(blocking handoff import: synchronous
            # admission-class landing of peer slabs — nothing is
            # decoding on behalf of this not-yet-admitted session)
            jax.block_until_ready(list(staged.values()))
            self.pool = adopt_into_pool(self.pool, staged)
            if self.spec:
                self.draft_pool = adopt_into_pool(
                    self.draft_pool, staged, prefix="d_"
                )
            self._store.publish(
                keys[: len(have) + len(todo)], have + fresh
            )
            # A node mid-swap-in (restoring) refuses the published
            # copy: its fresh block stays unkeyed — free it instead
            # of leaking.
            adopted = [b for b in fresh if self._store.is_keyed(b)]
            self._store.retain(adopted)
            self._invalidate_and_free(
                [b for b in fresh if b not in adopted]
            )
            self.kv_import_blocks_total += len(adopted)
            if adopted:
                self.kv_import_events_total += 1
            # Fleet-trace link (see export_prefix).
            self.obs.annotate(
                "prefix_import", blocks=len(adopted),
                request_id=request_id,
            )
            return len(adopted)
        finally:
            # Matched blocks return to the idle LRU (nobody is using
            # them yet — the claim only protected them from this
            # call's own allocation).
            self._unclaim_blocks(have)

    # Chain hash per FULL prompt block: key_j = H(key_{j-1}, block-j
    # tokens), so a hit at block j certifies the whole prefix up to
    # it.  The implementation lives in router.chain_keys — the ONE
    # shared key schema the router-side global radix index must agree
    # with (router.py stays jax-free, so the pure helper lives there).
    _chain_keys = staticmethod(_router_chain_keys)

    def _match_prefix(self, keys: List[bytes]) -> MatchResult:
        """Longest cached chain prefix across ALL cached chains (the
        radix walk; the exact store degenerates to the flat-map walk).
        ``.blocks`` are the HBM-resident hits; a nonempty ``.restore``
        names demoted nodes a host-tier swap-in could bring back."""
        return self._store.match(keys)

    def _claim_blocks(self, blocks: List[int]) -> None:
        self._store.on_claim(blocks)
        for blk in blocks:
            self._block_refs[blk] = self._block_refs.get(blk, 0) + 1

    def _unclaim_blocks(self, blocks: List[int]) -> None:
        """Reverse of ``_claim_blocks`` for admissions that never landed
        (aborted/cancelled swap-ins): drop the refs and push keyed
        blocks whose last user this was back into the idle LRU."""
        retained: List[int] = []
        plain: List[int] = []
        for blk in blocks:
            refs = self._block_refs.get(blk, 1) - 1
            if refs > 0:
                self._block_refs[blk] = refs
                continue
            self._block_refs.pop(blk, None)
            if self.prefix_cache_enabled and self._store.is_keyed(blk):
                retained.append(blk)
            else:
                plain.append(blk)
        self._store.retain(retained)
        self._invalidate_and_free(plain)

    def _register_chain(self, blocks: List[int], keys: List[bytes]) -> None:
        """Publish a request's freshly prefilled full prompt blocks into
        the prefix index.

        Radix: divergent chains share their common prefix NODES by
        construction — a duplicate publication leaves the existing
        node's block in place and the publisher's copy stays private
        (plain-freed with its slot).  Exact (the legacy oracle): a
        duplicate publication SUPERSEDES — the store returns the old
        idle blocks, freed here in one batch (per-block frees would be
        one jitted _release_blocks dispatch each, ~100 ms of tunnel
        latency apiece in this environment)."""
        if not self.prefix_cache_enabled:
            return
        self._invalidate_and_free(self._store.publish(keys, blocks))

    def _free_slot(self, b: int, device_done: bool = False) -> None:
        """Free slot ``b``.  ``device_done=True`` means the chunk program
        already folded the row out of its on-device active mask (stop /
        budget / non-finite detected in-jit), so no deactivation upload
        is owed; a HOST-initiated free (cancel, forced-nan drill) must
        mark the row dirty so the next chunk dispatch deactivates it on
        device — a stale device-active row would keep decoding into
        blocks the allocator may hand to someone else."""
        slot = self.slots[b]
        assert slot is not None
        if self._pf is not None and self._pf.slot == b:
            # Mid-prefill free (cancel / forced-nan drill): drop the
            # in-flight admission — no further fused dispatches reference
            # it, and device ordering makes the already-enqueued chunk
            # writes land before any re-allocation of its blocks.  The
            # chain was never published (publication happens at
            # completion), so nothing to unpublish beyond _fail_slot's
            # usual scan.
            self._pf = None
        # Keyed blocks with no remaining users are RETAINED (prefix
        # cache) — their positions must stay valid for future reusers —
        # handed to the store in chain order (it reverses, so chains
        # enter the idle LRU leaves-first and evict back-to-front).
        plain: List[int] = []
        retained: List[int] = []
        for blk in slot.blocks:
            refs = self._block_refs.get(blk, 1) - 1
            if refs > 0:
                self._block_refs[blk] = refs
                continue
            self._block_refs.pop(blk, None)
            if self.prefix_cache_enabled and self._store.is_keyed(blk):
                retained.append(blk)
            else:
                plain.append(blk)
        self._store.retain(retained)
        self._invalidate_and_free(plain)
        # Session KV footprint at teardown (peak blocks held).
        self.obs.observe_kv(session_blocks=len(slot.blocks))
        self.slots[b] = None
        self.table[b] = self.n_blocks
        self.n_alloc[b] = 0
        self.fill[b] = 0
        self.active[b] = False
        self.remaining[b] = 0
        self.stop_tab[b, :] = -1
        if not device_done:
            self._dirty_rows.add(b)

    def _suffix_pad(self, n_suffix_tokens: int, n_share: int) -> int:
        """Padded suffix length for the grouped suffix-insert: round to a
        block multiple, then bucket the BLOCK COUNT to a power of two —
        the same jit-cache-key discipline admission row counts already
        follow — so diverse /chat prompt lengths compile a bounded
        O(log2(max_len / block_size)) set of ``_paged_suffix_insert``
        executables instead of one per distinct suffix length.  The
        extra padding is masked compute (positions -1, mask False), and
        POOL write columns past a row's reservation resolve to sentinel
        table entries and drop (the ``paged_write_indices`` contract).
        The hard bound is the gathered VIEW: its width is
        blocks_per_slot x block_size and the in-forward cache write
        starts at fill0 = n_share blocks — a bucket past the remaining
        view columns would make that dynamic-update clamp its start and
        scribble over the reused prefix KV, so clamp the bucket to the
        columns the row actually has (admissibility guarantees the
        un-bucketed count fits, so the clamp never shrinks below it)."""
        nb = max(1, -(-n_suffix_tokens // self.block_size))
        nb_b = pow2_bucket(nb)
        cap = self.blocks_per_slot - n_share
        return (min(nb_b, cap) if cap >= nb else nb) * self.block_size

    def _ensure_stop_width(self, n: int) -> None:
        """Grow the -1-padded per-slot stop table to hold ``n`` stops
        (pow2-bucketed width, so the chunk program's jit cache sees
        O(log max_stops) shapes).  The device twin is rebuilt wholesale
        at the next ``_sync_device_rows``."""
        if n <= self.stop_tab.shape[1]:
            return
        w = pow2_bucket(n)
        tab = np.full((self.n_slots, w), -1, np.int32)
        tab[:, : self.stop_tab.shape[1]] = self.stop_tab
        self.stop_tab = tab

    def _set_stop_row(self, b: int, stops: frozenset) -> None:
        """Write slot ``b``'s stop set into the on-device stop table's
        host mirror (order irrelevant — membership test only)."""
        self._ensure_stop_width(max(1, len(stops)))
        self.stop_tab[b, :] = -1
        if stops:
            self.stop_tab[b, : len(stops)] = sorted(stops)

    def _row_bucket(self, reqs: List["_Request"]):
        """Shared admission-row-bucket setup: the pow2 row count (jit
        cache key discipline — both admission paths must bucket the same
        way) plus the per-row key/sampling-parameter arrays."""
        k = len(reqs)
        kb = pow2_bucket(k)
        keys = np.zeros((kb, 2), np.uint32)
        temps = np.zeros((kb,), np.float32)
        top_ps = np.ones((kb,), np.float32)
        top_ks = np.zeros((kb,), np.int32)
        for i, req in enumerate(reqs):
            keys[i] = self._request_key(req)
            temps[i] = req.temperature
            top_ps[i] = req.top_p
            top_ks[i] = req.top_k
        return kb, keys, temps, top_ps, top_ks

    def _request_key(self, req: "_Request") -> np.ndarray:
        """Host-built threefry key words for a request.  The obvious
        np.asarray(jax.random.PRNGKey(seed)) is a device round-trip PER
        REQUEST — ~100 ms of tunnel latency each here, which silently
        handed back the entire batched-prefill admission win (measured:
        8 admissions cost ~800 ms in key fetches alone).  Under the
        default (x64-disabled) canonicalization PRNGKey(seed) is exactly
        [0, seed & 0xFFFFFFFF] (parity-tested); with x64 enabled
        threefry_seed keeps the high word too, so mirror it — otherwise
        an embedding application that flips jax_enable_x64 would
        silently fork the batcher's sampled streams from standalone
        seeded generates.  (Seed mix: a stable multiply, NOT Python's
        hash() — its tuple algorithm is an interpreter detail that would
        change sampled outputs across Python versions.)"""
        seed = (
            req.seed if req.seed is not None
            else self.default_seed(req.rid)
        )
        kw = np.zeros((2,), np.uint32)
        if jax.config.jax_enable_x64:
            kw[0] = np.uint32((seed >> 32) & 0xFFFFFFFF)
        kw[1] = np.uint32(seed & 0xFFFFFFFF)
        return kw

    def _admit_shared_group(
        self,
        grp: List[Tuple["_Request", List[bytes], List[int]]],
        slots: List[int],
    ) -> None:
        """Admit a group of prefix-cache-hit requests sharing one padded
        suffix length: reuse the cached blocks (already claimed by
        _admit) and prefill only the suffixes through the rows' gathered
        views in ONE dispatch (per-row fill offsets differ freely).
        Each request's own freshly prefilled full prompt blocks extend
        the published chain, so a follow-up with a longer shared prefix
        hits deeper."""
        bs = self.block_size
        k = len(grp)
        kb, keysA, temps, top_ps, top_ks = self._row_bucket(
            [r for r, _, _ in grp]
        )
        T = self._suffix_pad(
            len(grp[0][0].tokens) - len(grp[0][2]) * bs, len(grp[0][2])
        )
        st = np.zeros((kb, T), np.int32)
        sm = np.zeros((kb, T), bool)
        table_rows = np.full((kb, self.blocks_per_slot), self.n_blocks,
                             np.int32)
        n_alloc_arr = np.zeros((kb,), np.int32)
        fill0s = np.zeros((kb,), np.int32)
        row_blocks: List[List[int]] = []
        row_fresh: List[List[int]] = []
        for i, (req, chain, hits) in enumerate(grp):
            n_share = len(hits)
            L0 = n_share * bs
            fresh = self._alloc_blocks(req.blocks_needed(bs) - n_share)
            blocks = hits + fresh
            row_blocks.append(blocks)
            row_fresh.append(fresh)
            suffix = req.tokens[L0:]
            st[i, : len(suffix)] = suffix
            sm[i, : len(suffix)] = True
            table_rows[i, : len(blocks)] = blocks
            n_alloc_arr[i] = len(blocks)
            fill0s[i] = L0
        # No flash here regardless of T: the gathered view carries
        # PER-ROW cache offsets (fill0 is a vector), which forces
        # forward()'s must_xla path — "auto" resolves to XLA for every
        # suffix chunk.  Claiming flash would fire the wrong fault site
        # and, worse, credit a probing flash kernel with a success it
        # never executed.
        for req, _, _ in grp:
            self.obs.begin_span(req.rid, "prefilling")

        def _sds(a):
            # Aval stand-ins (shape/dtype only) for the host arrays the
            # dispatch below uploads — the cost hook must not add one.
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        cost_fl, cost_by = self._dispatch_cost(
            "_paged_suffix_insert", (kb, T),
            lambda: _paged_suffix_insert.lower(
                self.params, self.pool, _sds(table_rows),
                _sds(n_alloc_arr), _sds(fill0s), _sds(st), _sds(sm),
                _sds(keysA), _sds(temps), _sds(top_ps), _sds(top_ks),
                config=self.config, prefill_chunk=self.prefill_chunk,
                mesh=self.mesh, with_logprobs=self.logprobs,
                placed=self._mesh_placed,
            ),
        )
        t0_obs = time.monotonic()
        self._record_dispatch(["prefix_cache"])
        self._fault("suffix_insert")
        self._admit_dispatches += 1
        tau, tau_lp, keys_out, self.pool = _paged_suffix_insert(
            self.params, self.pool, jnp.asarray(table_rows),
            jnp.asarray(n_alloc_arr), jnp.asarray(fill0s),
            jnp.asarray(st), jnp.asarray(sm), jnp.asarray(keysA),
            jnp.asarray(temps), jnp.asarray(top_ps), jnp.asarray(top_ks),
            config=self.config, prefill_chunk=self.prefill_chunk,
            mesh=self.mesh, with_logprobs=self.logprobs,
            placed=self._mesh_placed,
        )
        if self.spec:
            # Draft pool: the shared blocks hold the DRAFT model's KV
            # for the same tokens (written when the chain was first
            # admitted under this batcher), so only the suffixes run
            # here too; sampled tokens are discarded.
            _, _, _, self.draft_pool = _paged_suffix_insert(
                self.draft_params, self.draft_pool,
                jnp.asarray(table_rows), jnp.asarray(n_alloc_arr),
                jnp.asarray(fill0s), jnp.asarray(st), jnp.asarray(sm),
                jnp.asarray(keysA),
                jnp.zeros((kb,), jnp.float32),
                jnp.ones((kb,), jnp.float32),
                jnp.zeros((kb,), jnp.int32),
                config=self.draft_config,
                prefill_chunk=self.prefill_chunk, mesh=self.mesh,
                placed=self._mesh_placed,
            )
        # Dispatch span (async submit — wall covers dispatch time only,
        # the suffix path's known undercount); linked into each
        # request's prefilling span, which then closes into decoding.
        self.obs.record_dispatch(
            kind="suffix_insert", k=k,
            occupancy=sum(s is not None for s in self.slots.values()),
            prefill_tokens=sum(
                len(r.tokens) - len(h) * bs for r, _, h in grp
            ),
            wall_ms=(time.monotonic() - t0_obs) * 1000.0,
            swap_inflight=len(self._restoring),
            rids=[r.rid for r, _, _ in grp],
            program="_paged_suffix_insert", flops=cost_fl,
            bytes_accessed=cost_by,
        )
        idx = jnp.asarray(np.asarray(slots, np.int32))
        self.tau = self.tau.at[idx].set(tau[:k])
        if self.logprobs:
            # Device twin always; the numpy mirror only feeds the
            # CLASSIC (spec_rounds=1) speculative emit scan — fetching
            # it costs an admission-time device->host sync neither
            # chunked path (plain or fused-spec) needs.
            self.d_tau_lp = self.d_tau_lp.at[idx].set(tau_lp[:k])
            if self.spec and self.spec_rounds == 1:
                # audit: host-fetch(classic-spec admission: the numpy
                # tau_lp mirror feeds the per-round emit scan; counted
                # — was an uncounted sync until the host-boundary lint
                # flagged it)
                self.tau_lp[np.asarray(slots)] = np.asarray(tau_lp)[:k]
                self.host_syncs_total += 1
                self.spec_host_syncs_total += 1
        self.keys = self.keys.at[idx].set(keys_out[:k])
        for i, (req, chain, hits) in enumerate(grp):
            b = slots[i]
            blocks = row_blocks[i]
            n_share = len(hits)
            self.pos[b] = len(req.tokens)
            self.fill[b] = _round_up(len(req.tokens), bs)
            self.active[b] = True
            self.table[b] = self.n_blocks
            self.table[b, : len(blocks)] = blocks
            self.n_alloc[b] = len(blocks)
            self.temp_arr[b] = req.temperature
            self.top_p_arr[b] = req.top_p
            self.top_k_arr[b] = req.top_k
            self.remaining[b] = req.max_new
            self._set_stop_row(b, req.stops)
            self._dirty_rows.add(b)
            self.slots[b] = _Slot(
                request_id=req.rid, emitted=[], max_new=req.max_new,
                stop_tokens=req.stops, blocks=blocks, shared=n_share,
            )
            self._claim_blocks(row_fresh[i])
            # Extend the published chain with this request's own full
            # prompt blocks (indices n_share..len(chain)-1 are fresh).
            # FULL chain, not the suffix: a suffix-only radix publish
            # would mis-root the extension at the tree root under
            # mid-chain keys (unreachable for future matches) — the
            # hit prefix re-publishes as a no-op and parents the
            # fresh nodes correctly.
            self._register_chain(blocks[: len(chain)], chain)
            self.prefix_requests_hit += 1
            self.prefix_blocks_reused += n_share
            self.prompt_tokens_total += len(req.tokens)
            self.prefix_hit_tokens_total += n_share * bs
            self.obs.begin_span(req.rid, "decoding")
            # Per-session KV accounting: blocks reserved + hit depth
            # onto the timeline, hit depth into its histogram.
            self.obs.request_kv(
                req.rid, blocks_held=len(blocks),
                prefix_hit_tokens=n_share * bs,
            )
            self.obs.observe_kv(hit_depth_tokens=n_share * bs)

    def _fused_scheduling(self) -> bool:
        """Fused prefill-decode scheduling is in force for this batcher
        (spec batchers keep classic admission — the round program has no
        prefill lane; quarantine off spec_decode lands on a plain
        chunked batcher where it IS in force)."""
        return self.prefill_budget > 0 and not self.spec

    def _admit(self) -> None:
        """Admit queued requests.

        Swap path first: in-flight swap-ins are POLLED (non-blocking
        while anything is decoding — the overlap contract) and
        completed ones admitted as plain prefix hits with FIFO
        priority.  Then the classic path (``prefill_budget=0``,
        speculative batchers, or a COLD pool with nothing mid-decode):
        whole-prompt batched prefill dispatches at the step boundary —
        see ``_admit_classic``.  Fused path (``prefill_budget`` > 0
        while any row is mid-decode): the queue head is moved to
        ``prefilling`` state (blocks reserved, prompt uploaded once,
        row visible-but-inactive) and its prompt advances INSIDE the
        subsequent ``_fused_chunk`` dispatches — at most one admission
        is in flight at a time, FIFO; the rest of the queue waits
        exactly as it would for capacity.  A queue head whose matched
        prefix includes host-tier blocks moves to ``restoring``
        instead (either path) — later queue entries keep admitting
        while its swap-in flies."""
        self._poll_restores()
        self._admit_restored_ready()
        if self._fused_scheduling():
            if self._pf is not None:
                return  # one in-flight admission at a time
            if bool(np.any(self.active)):
                if self.queue:
                    self._begin_fused_prefill()
                return
            # Cold pool: nobody to stall — classic batched admission.
        self._admit_classic()

    # -- host-tier swap-ins (the ``restoring`` admission state) -------------

    def _begin_restore(
        self, req: "_Request", chain: List[bytes], match: MatchResult
    ) -> bool:
        """Start an async swap-in for a request whose matched prefix
        includes demoted (host-tier) blocks: claim the path's resident
        blocks, pin the demoted nodes, allocate their fresh HBM blocks,
        and ``jax.device_put`` the slabs into staging buffers — then
        park the request in ``restoring``.  No pool dependency is
        created here, so decode chunks dispatched while the transfer
        flies never wait on it.

        Fault site ``kv_swap`` fires before the transfer; an injected
        fault (or injected allocation OOM) fails ONLY this request —
        claims released, fresh blocks returned, nodes unpinned and
        host-resident again — and returns False (the server maps the
        ``pop_failed`` entry to a clean HTTP 500)."""
        resident = [n.block for n in match.path if n.block is not None]
        self._claim_blocks(resident)
        self._store.pin_restoring(match.restore)
        fresh: List[int] = []
        try:
            self._fault("kv_swap")
            fresh = self._alloc_blocks(len(match.restore))
            staged = stage_restore(
                [n.host for n in match.restore], fresh, self.n_blocks,
                placements=(
                    smesh.staging_shardings(
                        self.mesh, list(match.restore[0].host)
                    ) if self._mesh_placed else None
                ),
            )
        except InjectedFault as e:
            self._store.unpin_restoring(match.restore)
            self._unclaim_blocks(resident)
            if fresh:
                self._invalidate_and_free(fresh)
            msg = (
                f"kv swap-in failed: {e} (request aborted; host-tier "
                f"blocks unpinned, server healthy)"
            )
            self.failed.append((req.rid, msg))
            self.swap_failures_total += 1
            self.obs.request_end(req.rid, "failed", msg)
            return False
        self._claim_blocks(fresh)
        self._restoring.append(_Restore(
            req=req, chain=chain, path=match.path,
            restore=match.restore, resident=resident, fresh=fresh,
            staged=staged, t0=time.monotonic(),
        ))
        self.swap_ins_total += 1
        self.obs.begin_span(req.rid, "restoring")
        # The evictions this session SUFFERED: matched prefix nodes
        # that had been demoted out of HBM, forcing this swap-in.
        self.obs.request_kv(
            req.rid, evictions_suffered=len(match.restore),
        )
        return True

    def _abort_restore(self, r: "_Restore") -> None:
        """Unwind an in-flight swap-in (cancel / broken path): release
        every claim — both the resident hits and the fresh blocks were
        CLAIMED at begin, so both go through ``_unclaim_blocks`` (a
        plain ``_invalidate_and_free`` of claimed blocks would strand
        their refcounts and leak pool capacity) — and the nodes fall
        back to host residency (the slabs were read, not moved; the
        staging copy is simply dropped).  Nothing was scattered into
        the pool, so no pool state needs undoing."""
        self._store.unpin_restoring(r.restore)
        self._unclaim_blocks(r.resident)
        self._unclaim_blocks(r.fresh)

    def _poll_restores(self) -> None:
        """Advance in-flight swap-ins WITHOUT stalling decode: readiness
        is ``jax.Array.is_ready`` on the staging buffers (non-blocking);
        only when nothing at all is decoding (no active row, no
        in-flight prefill — nobody to stall) does the poll block on the
        transfer.  A ready swap-in pays ONE jitted adoption scatter
        (``kvcache.adopt_into_pool``; both pools under speculative
        serving) and moves the request to ``_restored_ready``."""
        if not self._restoring:
            return
        idle = not bool(np.any(self.active)) and self._pf is None
        for r in list(self._restoring):
            r.polls += 1
            # A concurrent non-finite subtree drop (``_fail_slot`` ->
            # ``unpublish``) may have severed the matched path while
            # the transfer flew — its KV is suspect, and the nulled
            # node.block entries would otherwise crash admission.
            # Unwind the claims and requeue the request at the head:
            # it re-admits through a clean cold prefill,
            # token-identically.
            broken = any(
                (not n.restoring) if n in r.restore else
                (n.block is None)
                for n in r.path
            )
            if broken:
                self._restoring.remove(r)
                self._abort_restore(r)
                self.queue.insert(0, r.req)
                self.obs.begin_span(
                    r.req.rid, "queued", note="swap aborted"
                )
                continue
            ready = restore_ready(r.staged)
            if not ready and idle:
                # audit: host-fetch(blocking swap-in wait ONLY when
                # nothing is decoding — nobody to stall)
                jax.block_until_ready(list(r.staged.values()))
                ready = True
            if not ready or r.polls <= self.swap_poll_min:
                continue
            cost_fl, cost_by = self._dispatch_cost(
                "_adopt_jit", (len(r.staged["ids"]),),
                lambda: adopt_lower(self.pool, r.staged),
            )
            t_adopt = time.monotonic()
            self.pool = adopt_into_pool(self.pool, r.staged)
            if self.spec:
                self.draft_pool = adopt_into_pool(
                    self.draft_pool, r.staged, prefix="d_"
                )
            adopt_ms = (time.monotonic() - t_adopt) * 1000.0
            self._store.complete_restore(r.restore, r.fresh)
            self.swap_in_blocks_total += len(r.fresh)
            swap_ms = (time.monotonic() - r.t0) * 1000.0
            self.swap_in_ms_total += swap_ms
            self._restoring.remove(r)
            self._restored_ready.append(
                (r.req, r.chain, [n.block for n in r.path])
            )
            # The adoption scatter is a real device dispatch: span it
            # (linked into the request's restoring span) and feed the
            # swap-in histogram.  wall covers the async submit only
            # (blocking on the scatter here would ADD the host sync
            # the overlap design exists to avoid — the suffix path's
            # documented undercount applies).
            self.obs.record_swap_in(swap_ms, len(r.fresh))
            # Swap bytes moved for this session (host metadata
            # arithmetic on the staged buffers — no sync).
            self.obs.request_kv(
                r.req.rid,
                swap_in_bytes=sum(
                    int(a.nbytes) for a in r.staged.values()
                ),
            )
            self.obs.record_dispatch(
                kind="adopt", k=len(r.fresh),
                occupancy=sum(
                    s is not None for s in self.slots.values()
                ),
                wall_ms=adopt_ms,
                swap_inflight=len(self._restoring),
                rids=(r.req.rid,),
                program="_adopt_jit", flops=cost_fl,
                bytes_accessed=cost_by,
            )
            self.obs.begin_span(r.req.rid, "queued", note="restored")

    def _admit_restored_ready(self) -> None:
        """Admit completed swap-ins as plain prefix hits (their path
        blocks are already claimed): through the fused prefill lane
        when rows are decoding (the chunk walk starts at the matched
        depth — no stall), through one grouped suffix-insert dispatch
        otherwise.  FIFO among themselves; each still needs a free
        slot and capacity for the rest of its reservation."""
        while self._restored_ready:
            req, chain, hits = self._restored_ready[0]
            free = [b for b, s in self.slots.items() if s is None]
            if not free:
                return
            if (req.blocks_needed(self.block_size) - len(hits)
                    > self._capacity()):
                return
            if self._fused_scheduling() and bool(np.any(self.active)):
                if self._pf is not None:
                    return
                self._restored_ready.pop(0)
                self._setup_fused_prefill(req, chain, hits, claimed=True)
            else:
                self._restored_ready.pop(0)
                self._admit_shared_group(
                    [(req, chain, hits)], [free[0]]
                )

    def _pf_chunk(self, suffix_len: int, n_share: int) -> int:
        """Prompt tokens per fused dispatch: ``prefill_budget`` rounded
        DOWN to a pow2 block count (jit-cache discipline that still
        honors the flag as an upper bound — rounding up would let a
        640-token budget ride 1024 tokens of prefill per dispatch,
        inflating exactly the per-dispatch ITL the flag caps; the floor
        is one block), clamped to the suffix's own pow2 bucket, then
        halved until the LAST chunk's write window fits the row's
        remaining gathered-view columns — the ``_suffix_pad`` clamp
        hazard: the in-forward cache write is a scalar-start
        dynamic-update that would silently clamp and scribble over the
        reused prefix KV.  Terminates at one block, where admissibility
        guarantees the fit."""
        bs = self.block_size
        nbb = max(1, self.prefill_budget // bs)
        nbb = 1 << (nbb.bit_length() - 1)
        nbs = pow2_bucket(max(1, -(-suffix_len // bs)))
        c_blocks = min(nbb, nbs)
        view_blocks = self.blocks_per_slot - n_share
        while c_blocks > 1 and (
            -(-suffix_len // (c_blocks * bs)) * c_blocks > view_blocks
        ):
            c_blocks //= 2
        return c_blocks * bs

    def _begin_fused_prefill(self) -> None:
        """Move the queue head into ``prefilling`` state: reserve its
        blocks (claiming prefix-cache hits — hit rows start their chunk
        walk at fill0 = the matched depth), set up the host mirrors
        with the row VISIBLE BUT INACTIVE (the fused program activates
        it on device the dispatch its last chunk lands), and upload the
        suffix tokens + walk scalars ONCE — later chunks are pure
        dispatches, zero per-chunk host->device state traffic.  No
        model dispatch happens here; the prefill itself rides
        ``_fused_chunk``.  A head whose matched prefix includes
        host-tier blocks moves to ``restoring`` instead, and the NEXT
        head gets the prefill lane — swap-ins never block admission."""
        free = [b for b, s in self.slots.items() if s is None]
        if not free:
            return
        while self.queue:
            req = self.queue[0]
            need = req.blocks_needed(self.block_size)
            if need > self._capacity():
                return  # head-of-line blocking (FIFO fairness): wait
            chain = (
                self._chain_keys(req.tokens, self.block_size)
                if self.prefix_cache_enabled else []
            )
            m = self._match_prefix(chain)
            if m.restore:
                del self.queue[0]
                # Restoring (or cleanly failed on an injected swap
                # fault) — either way the prefill lane is still open
                # for the next head.
                self._begin_restore(req, chain, m)
                continue
            del self.queue[0]
            self._setup_fused_prefill(req, chain, m.blocks, claimed=False)
            return

    def _setup_fused_prefill(
        self, req: "_Request", chain: List[bytes], hits: List[int],
        claimed: bool = False,
    ) -> None:
        """The ``prefilling``-state setup shared by fresh admissions and
        completed swap-ins (``claimed=True``: the hit blocks were
        claimed at restore begin)."""
        if not claimed:
            self._claim_blocks(hits)
        b = next(b for b, s in self.slots.items() if s is None)
        n_share = len(hits)
        base = n_share * self.block_size
        fresh = self._alloc_blocks(
            req.blocks_needed(self.block_size) - n_share
        )
        self._claim_blocks(fresh)
        blocks = hits + fresh
        suffix = req.tokens[base:]
        C = self._pf_chunk(len(suffix), n_share)
        # Token buffer in whole chunks, chunk count pow2-bucketed (the
        # buffer length is a jit cache key of _fused_chunk); trailing
        # zeros are masked and never dispatched.
        n_chunks = pow2_bucket(max(1, -(-len(suffix) // C)))
        toks = np.zeros((n_chunks * C,), np.int32)
        toks[: len(suffix)] = suffix
        # Host mirrors: full reservation visible, row inactive; the
        # admission-time dirty sync is the ONE state upload the whole
        # prefill pays.
        self.table[b] = self.n_blocks
        self.table[b, : len(blocks)] = blocks
        self.n_alloc[b] = len(blocks)
        self.fill[b] = 0
        self.pos[b] = 0
        self.active[b] = False
        self.temp_arr[b] = req.temperature
        self.top_p_arr[b] = req.top_p
        self.top_k_arr[b] = req.top_k
        self.remaining[b] = req.max_new
        self._set_stop_row(b, req.stops)
        self._dirty_rows.add(b)
        self.slots[b] = _Slot(
            request_id=req.rid, emitted=[], max_new=req.max_new,
            stop_tokens=req.stops, blocks=blocks, shared=n_share,
        )
        self._pf = _Prefill(
            slot=b, req=req, chain=chain, n_share=n_share, base=base,
            suffix_len=len(suffix), chunk=C,
            d_toks=jnp.asarray(toks),
            d_off=jnp.zeros((), jnp.int32),
            d_row=jnp.asarray(np.int32(b)),
            d_base=jnp.asarray(np.int32(base)),
            d_len=jnp.asarray(np.int32(len(suffix))),
            d_key=jnp.asarray(self._request_key(req)),
        )
        self.fused_admissions_total += 1
        self.prompt_tokens_total += len(req.tokens)
        self.obs.begin_span(req.rid, "prefilling")
        if n_share:
            self.prefix_requests_hit += 1
            self.prefix_blocks_reused += n_share
            self.prefix_hit_tokens_total += base
        # Per-session KV accounting (fused lane): reservation + hit
        # depth onto the timeline and the hit-depth histogram.
        self.obs.request_kv(
            req.rid, blocks_held=len(blocks), prefix_hit_tokens=base,
        )
        self.obs.observe_kv(hit_depth_tokens=base)

    def _admit_classic(self) -> None:
        """Classic admission with the decode-stall clock around it: the
        wall time whole-prompt admission dispatches spend while >= 1
        row is mid-decode accumulates into ``decode_stall_ms_total``
        (the batched-prefill path's plens fetch blocks, so the timing is
        real there; the suffix path's dispatch is async and
        undercounts)."""
        before = self._admit_dispatches
        decoding = bool(np.any(self.active))
        t0 = time.monotonic()
        try:
            self._admit_classic_impl()
        finally:
            if decoding and self._admit_dispatches > before:
                self.decode_stall_ms_total += (
                    (time.monotonic() - t0) * 1000.0
                )

    def _admit_classic_impl(self) -> None:
        """Admit queued requests into free slots.

        A burst of k admissible requests without prefix-cache hits
        shares ONE [k', P] prefill dispatch (k' = k rounded up to a
        power of two with inactive pad rows, P = the group's max
        block-padded prompt length) instead of k serialized B=1
        dispatches — in this environment each dispatch costs ~100ms of
        tunnel latency on top of the prefill itself.  Requests whose
        leading full blocks hit the prefix cache are admitted through
        ``_paged_suffix_insert``, grouped by padded suffix length so a
        burst of similar /chat prompts is ONE dispatch too (per-row
        fill0 offsets differ freely within a group — the gathered view
        and scatter-back are per-row already).  Per-row right-padding and
        per-row key chains keep every request's output bit-identical to
        one-at-a-time admission; head-of-line FIFO blocking on block
        reservations is preserved (budget stays the FULL reservation
        even for hits — shared blocks change compute, not the
        conservative capacity accounting).
        """
        while True:
            free_slots = [b for b, s in self.slots.items() if s is None]
            if not free_slots or not self.queue:
                return
            # Head-of-line swap-ins: a queue HEAD whose matched prefix
            # includes host-tier blocks parks in ``restoring`` (async
            # swap-in overlapped on decode) instead of cold-prefilling
            # the demoted span; later entries keep admitting below.
            # Non-head entries with demoted prefixes stay FIFO-honest:
            # they admit now using only their HBM-resident hit depth.
            # Only a radix store with a tier can ever report demoted
            # hits, so the no-tier common case skips the scan entirely;
            # the head's (chain, hits) carries into the pick loop so
            # its prompt is hashed and matched once, not twice.
            head_match: Optional[Tuple[int, List[bytes], List[int]]] = None
            if self.host_kv_blocks > 0 and self._store.kind == "radix":
                while self.queue:
                    req = self.queue[0]
                    chain0 = (
                        self._chain_keys(req.tokens, self.block_size)
                        if self.prefix_cache_enabled else []
                    )
                    m0 = self._match_prefix(chain0)
                    if not m0.restore:
                        head_match = (req.rid, chain0, m0.blocks)
                        break
                    if req.blocks_needed(self.block_size) > self._capacity():
                        return  # FIFO: wait for capacity
                    del self.queue[0]
                    self._begin_restore(req, chain0, m0)
            picked: List[Tuple[_Request, List[bytes], List[int]]] = []
            budget = self._capacity()
            for req in self.queue:
                if len(picked) >= len(free_slots):
                    break
                need = req.blocks_needed(self.block_size)
                if need > budget:
                    # Head-of-line blocking (FIFO fairness): wait.
                    break
                budget -= need
                if head_match is not None and head_match[0] == req.rid:
                    chain, hits = head_match[1], head_match[2]
                else:
                    # Don't hash prompts for users who opted out.
                    chain = (
                        self._chain_keys(req.tokens, self.block_size)
                        if self.prefix_cache_enabled else []
                    )
                    hits = self._match_prefix(chain).blocks
                # Claim hits at SELECTION time: a later allocation in
                # this same admission round must not evict them.
                self._claim_blocks(hits)
                picked.append((req, chain, hits))
            if not picked:
                return
            del self.queue[:len(picked)]
            slot_iter = iter(free_slots)
            shared = [(r, c, h) for r, c, h in picked if h]
            batch = [r for r, c, h in picked if not h]
            chains = {r.rid: c for r, c, h in picked}
            # Hit requests group by padded suffix length: each group is
            # ONE suffix-insert dispatch (identical /chat prompts in a
            # burst land in the same group).
            groups: Dict[int, List[Tuple[_Request, List[bytes], List[int]]]] = {}
            for req, chain, hits in shared:
                T = self._suffix_pad(
                    len(req.tokens) - len(hits) * self.block_size,
                    len(hits),
                )
                groups.setdefault(T, []).append((req, chain, hits))
            for grp in groups.values():
                self._admit_shared_group(
                    grp, [next(slot_iter) for _ in grp]
                )
            if not batch:
                continue
            k = len(batch)
            kb, keys, temps, top_ps, top_ks = self._row_bucket(batch)
            # Group width: the max block-padded prompt length, its
            # BLOCK COUNT pow2-bucketed (clamped to the reservation
            # cap, which admissibility guarantees covers every row) —
            # the same jit-cache-key discipline the suffix path
            # (_suffix_pad) and admission row counts already follow.
            # Un-bucketed, diverse prompt lengths compiled one
            # _paged_insert executable per distinct block count
            # (O(max_len / block_size) cache keys — the over-wide
            # trace-key domain analysis/retrace.py flags); the extra
            # padding is masked compute and sentinel block ids drop.
            nb = min(
                pow2_bucket(max(
                    _round_up(len(r.tokens), self.block_size)
                    for r in batch
                ) // self.block_size),
                self.blocks_per_slot,
            )
            P = nb * self.block_size
            pt = np.zeros((kb, P), np.int32)
            pm = np.zeros((kb, P), bool)
            bid = np.full((kb, nb), self.n_blocks, np.int32)
            row_blocks: List[List[int]] = []
            for i, req in enumerate(batch):
                Pb = _round_up(len(req.tokens), self.block_size)
                need = req.blocks_needed(self.block_size)
                blocks = self._alloc_blocks(need)
                row_blocks.append(blocks)
                self.prompt_tokens_total += len(req.tokens)
                # Per-session KV accounting (cold batched prefill):
                # full reservation, zero hit depth.
                self.obs.request_kv(
                    req.rid, blocks_held=need, prefix_hit_tokens=0,
                )
                self.obs.observe_kv(hit_depth_tokens=0)
                # RIGHT padding (r5): token j at view column j, so block
                # content is a pure function of the tokens (the prefix
                # cache's keying invariant).  Trailing sentinels cover
                # the group padding past this row's block-padded length.
                pt[i, :len(req.tokens)] = req.tokens
                pm[i, :len(req.tokens)] = True
                bid[i, : Pb // self.block_size] = blocks[
                    : Pb // self.block_size
                ]
            # Host mirror of forward()'s "auto" resolution for the
            # batched prefill: flash runs iff a chunk exceeds 8 tokens
            # (the chunked loop forwards ``chunk`` tokens at a time, so
            # prefill_chunk <= 8 keeps every chunk on XLA; the batch
            # cache is a fresh scalar-index init_cache, so must_xla
            # never triggers here).
            chunk = (
                self.prefill_chunk
                if self.prefill_chunk and self.prefill_chunk < P else P
            )
            flash = (
                self.config.attn_impl in ("auto", "flash")
                and chunk > FLASH_MIN_SEQ
            )
            # Host mirror of the splash dispatch inside _block: reuse
            # the real eligibility predicate with the chunk geometry
            # (q_len=chunk, kv_len=P covers every chunk of the loop —
            # per-chunk kv_len is a multiple of chunk, so if chunk and
            # P pass the %128 checks every chunk does too).
            splash_used = flash and _kernels_mod.splash_eligible(
                self.config, batch=kb, q_len=chunk, kv_len=P,
                chunk_offset=0, quantized=self.pool.quantized,
                mesh=self.mesh,
            )
            for req in batch:
                self.obs.begin_span(req.rid, "prefilling")

            def _sds(a):
                # Aval stand-ins for the admission upload arrays — the
                # cost hook lowers without adding a host->device copy.
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            cost_fl, cost_by = self._dispatch_cost(
                "_paged_insert", (kb, P),
                lambda: _paged_insert.lower(
                    self.params, self.pool, _sds(bid), _sds(pt),
                    _sds(pm), _sds(keys), _sds(temps), _sds(top_ps),
                    _sds(top_ks),
                    config=self.config,
                    prefill_chunk=self.prefill_chunk,
                    mesh=self.mesh, with_logprobs=self.logprobs,
                    placed=self._mesh_placed,
                ),
            )
            t0_obs = time.monotonic()
            feats_ins: List[str] = ["flash_attention"] if flash else []
            if splash_used:
                feats_ins.append("splash_prefill")
            self._record_dispatch(feats_ins)
            self._fault("insert")
            if flash:
                self._fault("flash_kernel")
            if splash_used:
                self._fault("splash_kernel")
            self._admit_dispatches += 1
            taus, tau_lps, plens, keys_out, self.pool = _paged_insert(
                # audit: host-upload(admission-time prompt/state upload
                # for the whole batch — once per admission round, never
                # per-token)
                self.params, self.pool, jnp.asarray(bid),
                jnp.asarray(pt), jnp.asarray(pm), jnp.asarray(keys),
                jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(top_ks),
                config=self.config, prefill_chunk=self.prefill_chunk,
                mesh=self.mesh, with_logprobs=self.logprobs,
                placed=self._mesh_placed,
            )
            if self.spec:
                # Prefill the draft pool over the same reserved blocks
                # (its sampled tokens are discarded — the target picks
                # tau, and each row's key chain carries from the TARGET
                # insert only).
                _, _, _, _, self.draft_pool = _paged_insert(
                    # audit: host-upload(draft-pool twin of the
                    # admission-time upload above)
                    self.draft_params, self.draft_pool, jnp.asarray(bid),
                    jnp.asarray(pt), jnp.asarray(pm), jnp.asarray(keys),
                    jnp.zeros((kb,), jnp.float32),
                    jnp.ones((kb,), jnp.float32),
                    jnp.zeros((kb,), jnp.int32),
                    config=self.draft_config,
                    prefill_chunk=self.prefill_chunk, mesh=self.mesh,
                    placed=self._mesh_placed,
                )
            slot_ids = [next(slot_iter) for _ in range(k)]
            # audit: host-upload(slot-index upload, once per admission)
            idx = jnp.asarray(np.asarray(slot_ids, np.int32))
            self.tau = self.tau.at[idx].set(taus[:k])
            if self.logprobs:
                self.d_tau_lp = self.d_tau_lp.at[idx].set(tau_lps[:k])
                if self.spec and self.spec_rounds == 1:
                    # audit: host-fetch(classic-spec admission: numpy
                    # tau_lp mirror for the per-round emit scan;
                    # counted — was an uncounted sync until the
                    # host-boundary lint flagged it)
                    self.tau_lp[np.asarray(slot_ids)] = (
                        np.asarray(tau_lps)[:k]
                    )
                    self.host_syncs_total += 1
                    self.spec_host_syncs_total += 1
            self.keys = self.keys.at[idx].set(keys_out[:k])
            tf_obs = time.monotonic()
            # audit: host-fetch(admission-path prompt-length fetch —
            # blocks on the batched prefill; counted — was an
            # uncounted sync until the host-boundary lint flagged it)
            plens_np = np.asarray(plens)
            self.host_syncs_total += 1
            now_obs = time.monotonic()
            # Whole-prompt insert dispatch span: the plens fetch blocks
            # on the prefill, so wall here is the real admission cost
            # (what decode_stall_ms_total clocks); linked into each
            # request's prefilling span.
            self.obs.record_dispatch(
                # Per-kernel MXU attribution: splash-served inserts get
                # their own utilization series so the A/B is a live
                # gauge, not just a bench key.
                kind="insert:splash" if splash_used else "insert", k=k,
                occupancy=sum(
                    s is not None for s in self.slots.values()
                ),
                prefill_tokens=sum(len(r.tokens) for r in batch),
                wall_ms=(now_obs - t0_obs) * 1000.0,
                fetch_ms=(now_obs - tf_obs) * 1000.0,
                swap_inflight=len(self._restoring),
                rids=[r.rid for r in batch],
                program="_paged_insert", flops=cost_fl,
                bytes_accessed=cost_by,
            )
            for i, req in enumerate(batch):
                b = slot_ids[i]
                blocks = row_blocks[i]
                self.pos[b] = int(plens_np[i])
                self.fill[b] = _round_up(len(req.tokens), self.block_size)
                self.active[b] = True
                self.table[b] = self.n_blocks
                self.table[b, : len(blocks)] = blocks
                self.n_alloc[b] = len(blocks)
                self.temp_arr[b] = req.temperature
                self.top_p_arr[b] = req.top_p
                self.top_k_arr[b] = req.top_k
                self.remaining[b] = req.max_new
                self._set_stop_row(b, req.stops)
                self._dirty_rows.add(b)
                self.slots[b] = _Slot(
                    request_id=req.rid, emitted=[], max_new=req.max_new,
                    stop_tokens=req.stops, blocks=blocks,
                )
                # Every block now has an active user; the freshly
                # prefilled full prompt blocks join the prefix index.
                self._claim_blocks(blocks)
                chain = chains[req.rid]
                self._register_chain(blocks[: len(chain)], chain)
                self.obs.begin_span(req.rid, "decoding")
