"""Graceful degradation: per-feature health tracking with quarantine.

The serving stack has a slower always-correct fallback for every
accelerated feature it runs (``serving.py``):

  ==================  =============================================
  feature             fallback when quarantined
  ==================  =============================================
  flash_attention     XLA attention (``attn_impl='xla'``)
  paged_kernel        gathered-view XLA attention
                      (``use_pallas_kernel=False``)
  spec_decode         plain non-speculative decode (no draft model)
  prefix_cache        cold full prefill (``prefix_cache=False``)
  ==================  =============================================

Quarantine swaps ONLY the failing feature: a ``spec_decode`` fallback
rebuild drops the draft model but keeps the original ``decode_chunk``
and ``spec_rounds`` configuration (the rebuild reuses the base ctor
kwargs), so a quarantined speculative server degrades onto plain
CHUNKED decode, not the per-token loop — and a later probe re-enable
restores fused speculative serving with the same R.  Failures are
attributed once per fused chunk dispatch (the R rounds inside one
jitted program are one dispatch).

PR 1 gave the server crash *recovery* (rebuild + replay); this module
gives it a notion of *degraded* operation: a Pallas kernel that starts
failing on real hardware (a Mosaic compile regression, a driver fault,
silent NaN emission) should cost throughput, not availability.  Each
feature runs a small state machine:

    healthy --[>= threshold failures inside window_s]--> quarantined
    quarantined --[cooldown_s elapsed]--> probing   (one re-trial)
    probing --[success]--> healthy
    probing --[failure]--> quarantined              (cooldown restarts)

The manager is pure bookkeeping — it never touches the batcher.  The
serving loop (``server.LLMServer``) feeds it failures attributed from
dispatch exceptions, asks ``enabled()`` when (re)building the batcher,
and applies the fallback table above.  ``clock`` is injectable so the
transitions are unit-testable without sleeping.

Thread-safety: all methods take an internal lock — ``snapshot()`` /
``stats()`` are read from HTTP handler threads while the serving loop
records failures.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

# The degradable features, in fallback-severity order.  Every name
# here must have a fallback branch in ``LLMServer._build_batcher`` — a
# feature without one would "quarantine" while the rebuild keeps
# running it.
#
# The two kernel-selection features (ops/kernels.py registry) quarantine
# to the EXISTING custom kernel, not straight to XLA — one rung of the
# ladder at a time:
#
#   splash_prefill -> flash_attention -> xla       (prefill ladder)
#   stock_paged    -> paged_kernel    -> gathered  (decode ladder)
#
# so a splash-specific Mosaic failure costs the splash upside only, and
# the base features below still guard the custom kernels themselves.
FEATURES = (
    "splash_prefill",
    "stock_paged",
    "flash_attention",
    "paged_kernel",
    "spec_decode",
    "prefix_cache",
)

HEALTHY = "healthy"
QUARANTINED = "quarantined"
PROBING = "probing"


@dataclasses.dataclass
class _Feature:
    """One feature's health record (internal; ``snapshot()`` is the API)."""

    state: str = HEALTHY
    failures: Deque[float] = dataclasses.field(default_factory=deque)
    quarantined_at: Optional[float] = None
    failures_total: int = 0
    quarantines_total: int = 0
    probes_total: int = 0


class DegradeManager:
    """Failure-windowed quarantine tracker for the serving features.

    Args:
      threshold: failures inside ``window_s`` that trip quarantine.
      window_s: sliding failure window.
      cooldown_s: time a feature stays quarantined before one probe
        re-trial is allowed.
      clock: monotonic time source (injectable for tests).
    """

    def __init__(
        self,
        threshold: int = 3,
        window_s: float = 60.0,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[
            Callable[..., None]
        ] = None,
    ):
        if threshold < 1:
            raise ValueError("quarantine threshold must be >= 1")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        # Observability sink for state EDGES (healthy->quarantined,
        # quarantined->probing, probing->healthy/quarantined), called
        # as ``on_transition("quarantine_transition", feature=...,
        # state=...)`` — the server wires obs.Observability.annotate so
        # quarantine flips are visible in the serving trace next to
        # the dispatches that caused them.  Settable after construction
        # (``mgr.on_transition = ...``); fired OUTSIDE the lock is not
        # needed — annotate only appends to a bounded deque.
        self.on_transition = on_transition

        self._features: Dict[str, _Feature] = {
            name: _Feature() for name in FEATURES
        }

    def _emit(self, feature: str, state: str) -> None:
        if self.on_transition is not None:
            self.on_transition(
                "quarantine_transition", feature=feature, state=state
            )

    # audit: locked(every caller is a public method that already holds
    # self._lock around this lookup)
    def _get(self, name: str) -> _Feature:
        if name not in self._features:
            raise KeyError(
                f"unknown degradable feature {name!r}; have {FEATURES}"
            )
        return self._features[name]

    def record_failure(self, name: str) -> bool:
        """Count one failure; returns True when this failure moved the
        feature into quarantine (from healthy past the threshold, or a
        failed probe).  The caller uses the True edge to switch the
        batcher onto the fallback path."""
        now = self._clock()
        with self._lock:
            f = self._get(name)
            f.failures_total += 1
            f.failures.append(now)
            while f.failures and now - f.failures[0] > self.window_s:
                f.failures.popleft()
            if f.state == PROBING:
                # The re-trial failed: straight back to quarantine, full
                # cooldown restarts.
                f.state = QUARANTINED
                f.quarantined_at = now
                f.quarantines_total += 1
                self._emit(name, QUARANTINED)
                return True
            if f.state == HEALTHY and len(f.failures) >= self.threshold:
                f.state = QUARANTINED
                f.quarantined_at = now
                f.quarantines_total += 1
                self._emit(name, QUARANTINED)
                return True
            return False

    def record_success(self, name: str) -> bool:
        """A dispatch exercising the feature completed.  Only meaningful
        while probing: the probe passed, the feature is healthy again
        (returns True on that edge; failure history clears)."""
        with self._lock:
            f = self._get(name)
            if f.state != PROBING:
                return False
            f.state = HEALTHY
            f.quarantined_at = None
            f.failures.clear()
            self._emit(name, HEALTHY)
            return True

    def enabled(self, name: str) -> bool:
        """Whether the batcher may run the feature: healthy or probing."""
        with self._lock:
            return self._get(name).state != QUARANTINED

    def due_probes(self) -> List[str]:
        """Quarantined features whose cooldown has expired (ready for a
        probe re-trial; call ``start_probe`` before re-enabling)."""
        now = self._clock()
        with self._lock:
            return [
                name for name, f in self._features.items()
                if f.state == QUARANTINED
                and f.quarantined_at is not None
                and now - f.quarantined_at >= self.cooldown_s
            ]

    def start_probe(self, name: str) -> None:
        with self._lock:
            f = self._get(name)
            if f.state == QUARANTINED:
                f.state = PROBING
                f.probes_total += 1
                self._emit(name, PROBING)

    def degraded(self) -> bool:
        """Any feature currently QUARANTINED (a fallback is serving).

        Probing does NOT count: the feature is re-enabled and merely
        awaiting a confirming dispatch, which may take arbitrarily long
        to arrive (e.g. a probed prefix cache needs two requests sharing
        a prefix) — reporting that as degraded would wedge a permanent
        false alert on /healthz."""
        with self._lock:
            return any(
                f.state == QUARANTINED for f in self._features.values()
            )

    def quarantined(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(
                name for name, f in self._features.items()
                if f.state == QUARANTINED
            )

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Full per-feature state for the /healthz payload."""
        now = self._clock()
        out: Dict[str, Dict[str, object]] = {}
        with self._lock:
            for name, f in self._features.items():
                probe_in = None
                if f.state == QUARANTINED and f.quarantined_at is not None:
                    probe_in = max(
                        0.0, self.cooldown_s - (now - f.quarantined_at)
                    )
                out[name] = {
                    "state": f.state,
                    "failures_in_window": sum(
                        1 for t in f.failures if now - t <= self.window_s
                    ),
                    "failures_total": f.failures_total,
                    "quarantines_total": f.quarantines_total,
                    "probes_total": f.probes_total,
                    "probe_in_s": (
                        round(probe_in, 3) if probe_in is not None else None
                    ),
                }
        return out

    def stats(self) -> Dict[str, float]:
        """Flat counters/gauges for the /metrics endpoint."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, f in self._features.items():
                out[f"feature_quarantined_{name}"] = int(
                    f.state == QUARANTINED
                )
                out[f"feature_failures_{name}_total"] = f.failures_total
                out[f"feature_quarantines_{name}_total"] = (
                    f.quarantines_total
                )
        return out
