"""Minimal HTTP serving front-end over ``ContinuousBatcher``.

The reference has no server at all (its only entry point is a batch CLI,
reference ``jax_example.py:33-40``); this is framework surface beyond
parity.  Design constraints, in order:

  * **One device thread.**  The batcher (and JAX dispatch) is driven by a
    single serving loop thread; HTTP handler threads only enqueue work
    and wait.  This keeps the jitted step/insert programs free of locking
    and the device queue deep (the loop calls ``step()`` back-to-back
    while any slot is active).  Cancellation follows the same rule: a
    handler thread never touches the batcher — it only flips the
    request's ``disconnected`` flag (or the deadline expires), and the
    loop's ``_reap`` scan calls ``batcher.cancel`` at the next step
    boundary.
  * **Stdlib only.**  ``http.server.ThreadingHTTPServer`` + ``json`` — no
    web framework to vendor or pin.
  * **Observability.**  ``GET /metrics`` exposes the batcher counters
    (tokens, steps, slot/block occupancy, speculative acceptance) in
    Prometheus text format; ``GET /healthz`` for liveness.  Chunked
    decode adds: ``llm_decode_chunk_size`` (gauge — the effective K of
    the most recent fused decode dispatch; 1 around admissions; under
    speculative serving it mirrors the fused ROUND count R),
    ``llm_decode_dispatches_total``
    (counter — jitted decode dispatches; tokens/dispatch trends toward
    K), ``llm_host_syncs_total`` / ``llm_state_uploads_total``
    (counters — device->host fetches and host->device state-sync
    dispatches the serving loop performed), and
    ``llm_host_syncs_per_token`` (gauge — trends toward 1/K in steady
    state; ~1.0 means the loop is paying one round-trip per token).
    Speculative serving (a batcher with a draft model) adds:
    ``llm_spec_rounds_per_dispatch`` (gauge — the effective R of the
    most recent fused draft+verify dispatch; 1 right after an
    admission, powers of two up to ``--spec-rounds`` once slots are
    steady), ``llm_spec_dispatches_total`` (counter — jitted
    speculative dispatches, each carrying R rounds),
    ``llm_spec_host_syncs_per_token`` (gauge — the speculative twin of
    host_syncs_per_token: device->host fetches per emitted token on
    the spec path; trends toward 1 / (R * (acceptance * n_draft + 1))
    under the fused path, vs the 2-3 fetches PER ROUND the classic
    loop pays), and ``llm_spec_window_acceptance_rate`` (gauge —
    draft-token acceptance over the last 64 dispatches; unlike the
    lifetime ``llm_draft_acceptance_rate`` it shows a draft going
    stale mid-run).  Fused prefill-decode scheduling
    (``--prefill-budget``) adds: ``llm_prefill_chunks_total``
    (counter — chunk dispatches that also advanced an in-flight
    admission's prompt), ``llm_prefill_tokens_inflight`` (gauge —
    prompt tokens of the current admission still to prefill; 0 when
    none), ``llm_fused_admissions_total`` (counter),
    ``llm_decode_stall_ms_total`` (counter — wall time classic
    whole-prompt admission dispatches spent while rows were
    mid-decode; ≈0 once fused scheduling is on), and
    ``llm_ttft_ms_ewma`` (gauge — exponentially-weighted
    time-to-first-token over delivered requests, alpha 0.2; the
    stall win surfaces here first).  The KV-capacity subsystem
    (``kvcache.py``: radix prefix index + host-DRAM block tier,
    run.py ``--prefix-index`` / ``--host-kv-blocks``) adds:
    ``llm_radix_nodes_total`` (gauge — keyed blocks in the radix
    tree), ``llm_prefix_hit_tokens_ratio`` (gauge — fraction of
    admitted prompt tokens served from cached prefix blocks; the
    partial-prefix sharing win reads directly off this),
    ``llm_host_tier_blocks`` (gauge — blocks currently demoted to
    host DRAM, vs the ``llm_host_kv_blocks`` capacity),
    ``llm_swap_queue_depth`` (gauge — swap-ins in flight; a
    restoring request waits here while decode rows keep emitting),
    ``llm_swap_in_ms_total`` / ``llm_swap_ins_total`` /
    ``llm_swap_in_blocks_total`` / ``llm_swap_out_blocks_total``
    (counters — swap ledger), and ``llm_swap_failures_total``
    (counter — swap-ins failed cleanly per-request, never the
    server).  ``llm_prefix_cached_blocks`` predates the radix index
    and is kept as an alias of the idle resident count so existing
    dashboards don't break.
  * **Chunked decode is transparent here.**  The batcher's ``step()``
    may return up to K tokens per slot per call
    (``serving.ContinuousBatcher`` ``decode_chunk``, run.py
    ``--decode-chunk``); the loop below already iterates per-token
    events, so streaming clients still receive one NDJSON line per
    token, delivered-token accounting (the crash-recovery replay
    record) stays token-exact, and a mid-chunk stop/max_new/non-finite
    ends the request at exactly the token it would under the per-token
    loop.  Dispatch-failure attribution and fault sites fire once per
    chunk dispatch; an aborted chunk delivers nothing, so replay
    regenerates the whole chunk from the delivered record.
  * **Degrade before dying.**  Every accelerated feature has a slower
    always-correct fallback, and a feature that keeps failing is
    QUARANTINED onto it (``degrade.py``) instead of burning the crash-
    recovery budget: after ``quarantine_threshold`` attributable
    failures inside ``quarantine_window_s`` the batcher is rebuilt with
    the feature disabled (flash attention -> XLA attention, paged
    kernel -> gathered-view XLA decode, speculative -> plain decode,
    prefix cache -> cold prefill), in-flight requests replay exactly as
    in crash recovery, and after ``quarantine_cooldown_s`` the feature
    is re-probed (one trial: success re-enables it, failure re-
    quarantines).  A non-finite guard fails just the request whose
    logits came back NaN/Inf (HTTP 500 with a clean error) instead of
    streaming garbage.

/healthz schema (200 when ``ok``, 503 otherwise)::

    {
      "ok": bool,              # loop alive, not stalled, not draining
      "stalled": bool,         # step watchdog tripped
      "loop_alive": bool,
      "last_step_age_s": float,
      "recoveries_total": int,
      "watchdog_stalls_total": int,
      "draining": bool,        # drain mode (see below)
      "drain_remaining_s": float | null,
      "degraded": bool,        # any feature quarantined or probing
      "quarantined": [feature, ...],
      "kv": {                  # KV-capacity subsystem (kvcache.py)
        "prefix_index": "radix"|"exact"|"off",
        "host_kv_blocks": int,     # tier capacity (0 = tier off)
        "host_tier_blocks": int,   # blocks currently demoted
        "swap_queue_depth": int,   # swap-ins in flight (restoring)
        "restored_waiting": int,   # swapped in, awaiting a slot
        "digest": {                # chain-digest summary (KvDigest —
                                   # the compact form the router's
                                   # health poller scrapes; bounded)
          "version": int,          # bumps on publish/evict/demote/
                                   # restore; resets on rebuild —
                                   # compare with !=
          "loss_version": int,     # bumps only on HBM-residency loss
          "hash": "hex16",         # order-free set-hash of
                                   # (chain key, tier)
          "nodes": int, "hbm_blocks": int, "host_blocks": int,
          "idle_blocks": int, "depth_max": int,
          "publishes_total": int, "evictions_total": int,
          "demotions_total": int, "restores_total": int,
          "host_evictions_total": int
        },
        "block_bytes": int,        # pool bytes per block (the
                                   # duplicate-chain accounting unit)
        "total_blocks": int,
        "prefix_hit_tokens_total": int,  # fleet hit-ratio numerator
        "prompt_tokens_total": int       # ... and denominator
      },
      "overload": {            # overload controller (overload.py)
        "enabled": bool,           # priority classes + ladder active
        "rung": "normal"|"elevated"|"brownout-1"|"brownout-2"|"shed",
        "rung_since_s": float,
        "queued": {"interactive": int, "batch": int},
        "queued_tokens": {"interactive": int, "batch": int},
        "transitions_total": int,
        "sheds_total": int,        # queued batch entries shed (503)
        "refused": {"backlog": int, "deadline": int, "batch": int},
        "prefill_tokens_per_s_ewma": float,
        "interactive_attainment": float   # ladder's signal window
      },
      "features": {            # per degradable feature
        "<name>": {"state": "healthy"|"quarantined"|"probing",
                    "failures_in_window": int, "failures_total": int,
                    "quarantines_total": int, "probes_total": int,
                    "probe_in_s": float | null},  # cooldown countdown
        ...
      }
    }

Observability (obs.py) schemas
------------------------------

``/metrics`` histogram families (Prometheus text exposition; every
scalar metric also carries explicit ``# HELP`` + ``# TYPE`` lines from
the ``obs.METRICS`` registry — the old ``"total" in name`` type
heuristic is gone)::

    llm_<family>_bucket{le="<bound>"} N   # cumulative, +Inf last
    llm_<family>_sum S                    # sum of observed ms
    llm_<family>_count C                  # == the +Inf bucket

    families: ttft_ms, itl_ms, queue_wait_ms, prefill_chunk_ms,
              swap_in_ms, compile_ms  (all milliseconds), and
    llm_dispatch_ms{kind="decode"|"fused"|"spec"|"insert"|
    "suffix_insert"|"adopt"} — one labeled series PER DISPATCH KIND
    (every sample line carries the kind label; sum the series for the
    old lumped view).

Device-time attribution (obs.py cost models; batcher
``cost_models=True``, run.py default ON, ``--no-cost-models`` off):
each dispatch kind's recent window exposes
``llm_mxu_utilization{kind=...}`` / ``llm_hbm_utilization{kind=...}``
(modeled FLOPs / bytes over wall time, against ``--peak-tflops`` /
``--peak-hbm-gbps``) and ``llm_host_overhead_ratio{kind=...}`` (wall
over the roofline device-time estimate — ~1 device-bound, >>1 host
overhead).  Jit-cache observability:
``llm_jit_cache_entries{program=...}`` (live executable-cache entries
per registered serving program), ``llm_compiles_total`` +
``llm_program_compiles_total{program=...}`` and the ``compile_ms``
histogram (every backend compile, attributed to the program whose
dispatch triggered it via the jax.monitoring listener).

SLO accounting (run.py ``--slo-ttft-ms`` / ``--slo-itl-ms``; a 0/unset
dimension always passes): ``llm_slo_ttft_attainment`` /
``llm_slo_itl_attainment`` / ``llm_slo_attainment`` gauges (fraction of
the last 256 scored requests meeting each deadline), plus
``llm_requests_slo_ok_total`` and ``llm_goodput_tokens_total`` (tokens
from requests that met EVERY configured deadline — the objective the
ROADMAP-item-5 chunk controller will maximize).

``GET /debug/requests/<id>`` (id = client X-Request-Id / generated hex
id, the provisional ``r<rid>``, or a bare batcher rid; 404 when
evicted)::

    {
      "request_id": str, "rids": [int, ...],   # rid per incarnation
      "prompt_tokens": int,
      "outcome": "finished"|"failed"|"cancelled"|null,
      "error": str|null,
      "spans": [{"state": "queued"|"prefilling"|"restoring"|"decoding",
                 "start_ms": float, "end_ms": float|null,
                 "duration_ms": float|null,
                 "dispatches": [seq, ...],     # causal links
                 "note": str}, ...],
      "dispatch_spans": [<dispatch records the spans link to>]
    }

``GET /debug/requests?n=64`` lists recent timelines (id, rids, states,
outcome).  ``GET /debug/dispatches?n=128`` returns the dispatch ring::

    {"dispatches": [{"seq": int,
                     "kind": "decode"|"fused"|"spec"|"insert"|
                             "suffix_insert"|"adopt",
                     "k": int,                 # K iterations / R rounds
                     "occupancy": int,         # live slots
                     "prefill_tokens": int,    # prompt tokens advanced
                     "start_ms": float, "wall_ms": float,
                     "fetch_ms": float,        # the packed np.asarray
                     "swap_inflight": int,     # decode/swap overlap
                     "rids": [int, ...]}, ...]}

``GET /debug/trace[?window_s=S]`` emits Chrome ``trace_event`` JSON
(``{"traceEvents": [...]}``) — load in chrome://tracing or
https://ui.perfetto.dev: dispatches on one track, request lifecycles on
per-request tracks, fault/quarantine/kv-tier annotations as instant
events, jit compiles on their own track, and the document carries a
``t0_unix_s`` wall-clock anchor — the router's fleet-merged
``/debug/trace`` uses it to shift this replica's timestamps into one
frame (clock-offset normalization; see router.py for the merged
schema).  ``POST /debug/profiler`` ``{"action": "start", "log_dir":
D}`` / ``{"action": "stop"}`` brackets a ``jax.profiler`` xplane
session around live traffic (the device-side complement);
``GET /debug/profile/summary[?log_dir=D]`` then parses the completed
capture into per-program attribution::

    {"xplane": path, "log_dir": D,
     "programs": {"<program>": {"device_ms": F, "host_ms": F}, ...},
     "total_device_ms": F, "total_host_ms": F}

(404 with no completed session, 409 while one is active, 501 without
the xplane protos).  Dispatch records (/debug/dispatches) gain
``program`` and — with cost models on — ``flops`` /
``bytes_accessed`` / ``device_est_ms`` (the roofline estimate the
host_overhead_ratio gauge divides by).

``GET /debug/kv[?depth=D&n=N]`` (KV chain digest, r13 — reads only the
lock-guarded ``kvcache.KvDigest``, never the thread-confined store)::

    {"version": int,
     "nodes": [{"key": "<hex chain-prefix hash>",
                "depth": int,            # blocks from the root
                "tier": "hbm"|"host",    # residency
                "refcount": bool,        # claimed by a live session?
                "seq": int}, ...],       # recency (digest mutation seq)
     "truncated": int,                   # nodes past the n= cap
     "depth_cap": int|null,
     "summary": {<the /healthz kv.digest dict> +
                 prefix_index/block_size/block_bytes/total_blocks/
                 host_kv_blocks/prefix_hit_tokens_total/
                 prompt_tokens_total}}

Nodes sort (depth, key) so equal content serializes identically; the
walk is depth-capped by ``depth`` and truncated past ``n`` (default
2048), so the payload stays bounded at max radix occupancy.  With
``?since=V`` (r14) the reply is the INCREMENTAL form — ``{"version":
int, "since": V, "events": [{"version", "op": "publish"|"remove"|
"demote"|"restore"|"host_evict", "key", "depth", "tier"}, ...],
"summary": {...}}`` from the digest's bounded journal (the router's
global radix index syncs off it at O(changes) per poll); when the
journal cannot prove completeness (rebuild reset, consumer too far
behind) the full walk returns instead, tagged ``"resync": true``.  Per-
session KV accounting rides ``/debug/requests/<id>`` as a ``kv`` dict
(``blocks_held`` / ``prefix_hit_tokens`` / ``swap_in_bytes`` /
``evictions_suffered``), the ``prefix_hit_depth_tokens`` (pow2 token
buckets) and ``session_kv_blocks`` (pow2 block buckets) histograms
feed from admissions and slot frees, and kv-tier events (demote /
host-evict / evict / swap-in / handoff export+import) render on a
dedicated ``kv cache`` track in the /debug/trace export, linked to the
owning request through their args.  The router aggregates the per-
replica digests at ``GET /debug/kv/fleet`` (router.py docstring).

Every reply carries the end-to-end request id: blocking bodies and
error bodies (400/413/500/503/504) as ``"request_id"``, plus an
``X-Request-Id`` header; each NDJSON stream line carries
``"request_id"`` too.  Clients may supply their own ``X-Request-Id``
header (<= 128 chars) — it is honored verbatim, so a failure is
traceable from the client's logs without a join.

Overload control (``overload.py``, run.py ``--priority-classes`` /
``--brownout-*``): POST payloads may carry ``"priority"``
("interactive" | "batch"; junk is a 400).  The server keeps per-class
pre-admission queues with strict interactive-first ordering, admission
is cost-based (an EWMA of observed prefill/decode throughput converts
prompt length + backlog into a TTFT lower bound; a request whose
``timeout_s`` provably cannot be met is refused 503 + load-derived
``Retry-After`` immediately instead of queuing to die in the reaper),
and an SLO-driven brownout ladder (normal -> elevated -> brownout-1 ->
brownout-2 -> shed, hysteresis both ways) shrinks ``prefill_budget``,
caps batch-class ``max_new``, proactively demotes idle KV blocks to
the host tier, suspends batch admissions, and finally sheds queued
batch entries (clean 503 + Retry-After — never a hang).  ``/metrics``
gains ``llm_overload_rung`` (0=normal..4=shed),
``llm_overload_transitions_total``, ``llm_overload_sheds_total``,
``llm_overload_refused_{backlog,deadline,batch}_total``,
``llm_queued_interactive`` / ``llm_queued_batch``,
``llm_prefill_tokens_per_s_ewma`` / ``llm_decode_tokens_per_s_ewma``,
``llm_overload_ttft_estimate_ms``, ``llm_overload_batch_max_new_cap``,
and per-class ``llm_slo_interactive_attainment`` /
``llm_slo_batch_attainment``; ``/healthz`` gains the ``overload``
section (schema above).  Every ladder transition is a structured-log
line, an obs annotation, and visible in both surfaces.

Drain semantics: ``begin_drain()`` (run.py wires it to SIGTERM/SIGINT)
finishes every in-flight request, answers new POSTs ``503`` with a
``Retry-After`` header, and exits the serving loop once idle — bounded
by ``drain_timeout_s`` (``--drain-timeout-s``), past which stragglers
are failed with 503.  ``/healthz`` flips to 503 immediately so load
balancers stop routing here while streams finish.

Request bodies are capped at ``max_body_bytes`` (default 8 MiB): an
oversized or missing ``Content-Length`` is refused up front with
``413`` — the body is never read, so a hostile length claims no memory.

Endpoints:
  POST /chat       {"messages": [{"role": ..., "content": ...}, ...]}
                   (needs a server-side chat_format — llama3 ChatFormat).
                   Same sampling/stream/timeout options as /generate;
                   stop_tokens default to the tokenizer's stop set
                   (end_of_text + eot for llama3) and "text" fields
                   decode with stop ids stripped.
  POST /generate   {"prompt": [ids]} or {"text": "..."} (needs tokenizer),
                   optional max_new_tokens / temperature / top_p / top_k /
                   seed / stop_tokens / timeout_s / stream / logprobs /
                   priority ("interactive" default | "batch" — the
                   overload controller's class; see above)
                   (per-token model logprobs; needs a logprobs=True
                   batcher — run.py --logprobs).
                   Default: blocks until the request finishes; returns
                   {"request_id", "tokens", "text"?}.
                   "stream": true streams NDJSON, one line per token
                   ({"token": id, "text"?}), then a final
                   {"done": true, "tokens": [...]} line (close-delimited
                   body).  A client disconnect mid-stream cancels the
                   request and frees its slot and blocks.
                   "timeout_s" bounds the generation: on expiry the
                   request is cancelled server-side and (non-stream)
                   answered 504 / (stream) finished with
                   {"done": true, "timeout": true, ...}.
  GET  /metrics    Prometheus text exposition: ``ContinuousBatcher.stats()``
                   + degradation/server/SLO scalars (# HELP/# TYPE from
                   the obs.METRICS registry) + the latency histograms.
  GET  /healthz    {"ok": true}
  GET  /debug/requests[/<id>]   request-timeline JSON (schema above).
  GET  /debug/dispatches        recent dispatch-span ring.
  GET  /debug/kv                chain-digest tree walk (schema above).
  GET  /debug/trace             Chrome/Perfetto trace_event JSON.
  GET  /debug/decisions         control-plane decision audit log
                                (obs.DecisionLog: brownout rung moves,
                                recoveries, quarantines, probes,
                                sheds, drains; ?n= / ?kind= /
                                ?request_id= filter — the request_id
                                filter joins decisions to the
                                /debug/requests/<id> timeline).
  GET  /debug/bundle            flight-recorder postmortem artifact:
                                config + health + metrics + the
                                periodic metric-snapshot ring
                                (flight_interval_s) + last-N decisions
                                + annotation ring + structured-log
                                tail + request index + Perfetto trace
                                (?trace=0 omits the trace).
  POST /debug/profiler          jax.profiler session start/stop.
  GET  /debug/profile/summary   per-program xplane attribution
                                (schema above).

Control-plane observability (ISSUE 15): the router's synthetic canary
probes arrive as the RESERVED ``"priority": "canary"`` class — served
normally (interactive ordering) but excluded from SLO attainment,
goodput, the ttft/itl histograms + EWMAs, and the brownout ladder's
attainment/queue-wait windows (a fleet must never brown itself out on
its own probes); ``llm_canary_requests_total`` counts them.
``llm_itl_ms_ewma`` exposes the inter-token-latency EWMA the router's
health sentinel z-scores, and ``llm_decision_events_total`` counts
audit-log entries.
"""

from __future__ import annotations

import inspect
import json
import math
import queue
import select
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, unquote, urlsplit

from .degrade import DegradeManager
from .obs import Observability, StructuredLogger, metric_meta
from .overload import CANARY, PRIORITIES, RUNG_INDEX, OverloadController
from .parallel import serve_mesh as smesh
from . import serving as serving_mod
from .serving import ContinuousBatcher, _round_up

# Injection-site -> degradable-feature attribution for dispatch
# exceptions that carry a site name (InjectedFault.site; the generic
# step/insert/alloc sites stay unattributed and use the crash-recovery
# budget).  Real device errors carry no site — they attribute through
# _KERNEL_ERROR_MARKERS + the batcher's last-dispatch record instead.
_SITE_FEATURES = {
    "flash_kernel": "flash_attention",
    "paged_kernel": "paged_kernel",
    "splash_kernel": "splash_prefill",
    "stock_paged_kernel": "stock_paged",
    "spec_decode": "spec_decode",
    "suffix_insert": "prefix_cache",
}
# Substrings that mark a real (non-injected) dispatch error as coming
# out of a Pallas kernel (Mosaic compile/runtime failures name their
# origin); matched case-insensitively against the exception text.
# "splash" covers the upstream splash-attention module's own error
# text (mask/BlockSizes validation raises name the kernel, not Mosaic).
_KERNEL_ERROR_MARKERS = (
    "mosaic", "pallas", "custom-call", "custom_call", "splash",
)

_DONE = object()  # stream sentinel

# The batcher's own default generation budget — read from the signature
# so the recovery snapshot can never drift from what submit() reserved.
_SUBMIT_DEFAULT_MAX_NEW = inspect.signature(
    ContinuousBatcher.submit
).parameters["max_new_tokens"].default


class _ControlCall:
    """One unit of batcher work scheduled onto the serving-loop thread
    by a foreign thread (``LLMServer.call_on_loop``): the batcher is
    thread-confined, so the router's handoff scheduler drives
    ``export_prefix`` / ``import_prefix`` through this control path
    instead of touching the batcher directly.  ``cancelled`` makes the
    caller's timeout safe: a call abandoned before the loop picked it
    up never runs; one abandoned mid-run completes harmlessly (its
    result is simply dropped)."""

    __slots__ = ("fn", "done", "cancelled", "result", "error")

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.cancelled = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None


@dataclass
class _Pending:
    payload: Dict[str, Any]
    done: threading.Event = field(default_factory=threading.Event)
    tokens: List[int] = field(default_factory=list)
    error: Optional[str] = None
    error_code: int = 400  # 400 = rejected payload, 503 = server-side
    request_id: Optional[int] = None
    # Streaming: the loop feeds token ids (then _DONE) into ``chunks``;
    # the handler thread drains it onto the socket.
    stream: bool = False
    chunks: "queue.Queue[Any]" = field(default_factory=queue.Queue)
    # Absolute deadline (time.monotonic()); enforced by the loop.
    deadline: Optional[float] = None
    timed_out: bool = False
    # Set by the handler when the client socket dies mid-stream; the loop
    # cancels the request at the next step boundary.
    disconnected: bool = False
    # /chat request: dialog framing on submit, stop ids stripped from the
    # decoded text fields.
    chat: bool = False
    # Client sent its own "stop_tokens": the tokenizer's stop set is no
    # longer protocol framing for this request, so _visible must not
    # strip it from decoded text (it may legitimately appear mid-stream).
    stops_overridden: bool = False
    # "logprobs": true — per-token model logprobs in the response
    # (requires the batcher to be constructed with logprobs=True).
    want_lp: bool = False
    lps: List[float] = field(default_factory=list)
    # Crash-recovery snapshot, recorded at submit time: the CPU-side
    # state a replay needs.  ``tokens`` above is the DELIVERED record —
    # authoritative over the batcher's slot.emitted, which may include
    # tokens an aborted step() never returned; replaying from prompt +
    # delivered regenerates those, so clients neither miss nor repeat
    # tokens.
    prompt_tokens: List[int] = field(default_factory=list)
    submit_kwargs: Dict[str, Any] = field(default_factory=dict)
    max_new: int = _SUBMIT_DEFAULT_MAX_NEW
    replay_seed: Optional[int] = None
    # Recovery clamped this request's continuation budget (the replayed
    # prompt's block padding ate capacity): the reply is shorter than a
    # fault-free run's and says so.
    truncated: bool = False
    # Submit-time monotonic stamp: TTFT = first delivered token minus
    # this (survives crash-recovery resubmits, so the gauge reflects
    # what the CLIENT waited, recovery included).
    submitted_at: Optional[float] = None
    # ReplicaRouter decision (the X-Routed-By request header, e.g.
    # "replica-1/least-loaded"): recorded on the request's timeline at
    # submit so /debug/requests/<id> shows which replica served it.
    route: Optional[str] = None
    # End-to-end request id: the client's X-Request-Id header when
    # supplied, a generated hex id otherwise.  Echoed in every reply
    # (blocking body, each stream line, error bodies) and the key of
    # the request's /debug/requests/<id> timeline — stable across
    # crash-recovery replays, unlike the batcher rid.
    ext_id: str = ""
    # Client-observed latency record for the SLO accounting: TTFT, the
    # worst inter-token gap, and whether this request was already
    # scored (each request is scored exactly once, at its terminal
    # transition).
    ttft_ms: Optional[float] = None
    last_tok_t: Optional[float] = None
    itl_max_ms: Optional[float] = None
    slo_accounted: bool = False
    # Overload control (overload.py): the request's priority class
    # ("interactive" | "batch"; validated in do_POST), its admission
    # cost estimate in prompt tokens (exact for token prompts, a
    # chars/4 heuristic for text/chat — it only feeds the TTFT lower
    # bound and Retry-After, nothing token-exact), and the POST-arrival
    # stamp the pre-admission queue wait is measured from.
    priority: str = "interactive"
    cost_tokens: int = 0
    received_at: Optional[float] = None
    # Retry-After (seconds) for a 503 delivered through fail() — set by
    # the shed path so the reply carries the load-derived header even
    # though the refusal happens long after do_POST returned.
    retry_after_s: Optional[int] = None

    def fail(self, message: str, code: int) -> None:
        self.error = message
        self.error_code = code
        self.done.set()
        self.chunks.put(_DONE)

    def finish(self) -> None:
        self.done.set()
        self.chunks.put(_DONE)


class LLMServer:
    """HTTP wrapper: handler threads enqueue; one loop thread owns the
    batcher and the device."""

    def __init__(
        self,
        batcher: ContinuousBatcher,
        tokenizer: Any = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_queue: int = 256,
        chat_format: Any = None,
        max_recoveries: int = 3,
        recovery_window_s: float = 60.0,
        watchdog_deadline_s: Optional[float] = 60.0,
        watchdog_interval_s: float = 1.0,
        degrade: Optional[DegradeManager] = None,
        quarantine_threshold: int = 3,
        quarantine_window_s: float = 60.0,
        quarantine_cooldown_s: float = 30.0,
        drain_timeout_s: float = 30.0,
        max_body_bytes: int = 8 << 20,
        logger: Optional[StructuredLogger] = None,
        priority_classes: bool = True,
        overload: Optional[OverloadController] = None,
        brownout_enter_attainment: float = 0.85,
        brownout_exit_attainment: float = 0.95,
        brownout_queue_wait_ms: Optional[float] = None,
        brownout_dwell_s: float = 2.0,
        brownout_cooldown_s: float = 10.0,
        brownout_batch_max_new: int = 64,
        brownout_demote_blocks: int = 32,
        replica_id: Optional[int] = None,
        flight_interval_s: float = 5.0,
    ):
        self.batcher = batcher
        # Replica index behind a ReplicaRouter (router.py); None when
        # standalone.  Purely observational: /healthz gains a
        # ``replica`` section and /metrics a ``replica_id`` gauge so a
        # fleet scrape can tell the instances apart.
        self.replica_id = replica_id
        # Structured logging (obs.StructuredLogger; run.py --log-json):
        # lifecycle events — recoveries, quarantines, per-request
        # failures — go through one formatter carrying request_id /
        # feature fields.  With no logger supplied a QUIET one is
        # created: stdout stays as silent as the old print-free
        # server, but the flight recorder's /debug/bundle log tail
        # still records every lifecycle line.
        self.logger = (
            logger if logger is not None
            else StructuredLogger(quiet=True)
        )
        self.tokenizer = tokenizer
        self.chat_format = chat_format
        self.max_queue = max_queue
        self.max_body_bytes = int(max_body_bytes)
        # Crash-recovery circuit breaker: at most ``max_recoveries``
        # batcher rebuilds per sliding ``recovery_window_s`` window; one
        # more failure hard-drains (every client 503s) instead of
        # crash-looping a persistently broken device.
        self.max_recoveries = max_recoveries
        self.recovery_window_s = recovery_window_s
        self.recoveries_total = 0
        # Monotonic times of UNATTRIBUTABLE recoveries only — failures
        # attributed to a degradable feature are budgeted by the
        # quarantine threshold/window instead (see _recover).
        self._recovery_times: List[float] = []
        # Degradation layer: failures attributable to a quarantinable
        # feature feed this state machine; a quarantine rebuilds the
        # batcher onto the feature's fallback path instead of tripping
        # the breaker.  The ORIGINAL construction is captured here so a
        # later probe can rebuild with the feature restored (a rebuilt
        # batcher only remembers its own, possibly-degraded, ctor args).
        self.degrade = degrade if degrade is not None else DegradeManager(
            threshold=quarantine_threshold,
            window_s=quarantine_window_s,
            cooldown_s=quarantine_cooldown_s,
        )
        # Quarantine state EDGES land in the serving trace next to the
        # dispatches that caused them (degrade.py only counts totals).
        if self.degrade.on_transition is None:
            self.degrade.on_transition = self.batcher.obs.annotate
        # Overload controller (overload.py): per-class admission
        # queues, the cost-based deadline refusal, and the brownout
        # ladder.  Server-owned like the DegradeManager, so it survives
        # batcher rebuilds; the dispatch sink feeds its throughput
        # EWMAs from the obs records the loop already produces.
        # ``priority_classes=False`` keeps the controller as a plain
        # FIFO with only the depth backstop (the pre-PR-9 behavior,
        # plus the Retry-After header the bare 503 lacked).
        self.overload = overload if overload is not None else (
            OverloadController(
                enabled=priority_classes,
                max_queue=max_queue,
                enter_attainment=brownout_enter_attainment,
                exit_attainment=brownout_exit_attainment,
                queue_wait_ms=brownout_queue_wait_ms,
                slo_ttft_ms=self.batcher.obs.slo_ttft_ms,
                dwell_s=brownout_dwell_s,
                cooldown_s=brownout_cooldown_s,
                batch_max_new=brownout_batch_max_new,
                demote_blocks=brownout_demote_blocks,
            )
        )
        # The depth backstop now lives in the controller; an
        # explicitly-injected controller brings its OWN max_queue, so
        # mirror it back — ``server.max_queue`` must never disagree
        # with the bound actually enforced.
        self.max_queue = self.overload.max_queue
        if self.batcher.obs.on_dispatch is None:
            self.batcher.obs.on_dispatch = self.overload.on_dispatch
        # On-demand jax.profiler session (POST /debug/profiler): the
        # log_dir of the active trace, None when idle; the lock
        # serializes handler threads racing start/stop.
        # _profiler_last_dir remembers the most recently COMPLETED
        # session so GET /debug/profile/summary can attribute it
        # without the client re-supplying the path.
        self._profiler_dir: Optional[str] = None
        self._profiler_last_dir: Optional[str] = None
        self._profiler_lock = threading.Lock()
        self._base_ctor = (
            batcher.params, batcher.config, dict(batcher._ctor_kwargs)
        )
        self.quarantine_rebuilds_total = 0
        self.probe_rebuilds_total = 0
        self.nonfinite_failed_total = 0
        # Time-to-first-token EWMA (ms, alpha 0.2) over delivered
        # requests — the latency the fused prefill-decode scheduler
        # (serving.py, run.py --prefill-budget) exists to bound; None
        # until the first request delivers.
        self.ttft_ms_ewma: Optional[float] = None
        # Inter-token-latency EWMA (ms, alpha 0.2) — the per-replica
        # degradation signal the router's health sentinel z-scores off
        # the /healthz scrape.  Canary probes are excluded (a tiny
        # probe's gaps would drag the signal the probe exists to
        # watch).
        self.itl_ms_ewma: Optional[float] = None
        # Synthetic canary probes served (the reserved "canary"
        # request class — router.py sends them; excluded from SLO /
        # goodput / ladder inputs, counted here so a replica can
        # prove its probes are arriving).
        self.canary_requests_total = 0
        # Flight recorder: the serving loop appends a compact metric
        # snapshot to obs.metric_snapshots every flight_interval_s
        # (<= 0 disables), so /debug/bundle carries the trend into an
        # incident, not just the final values.
        self.flight_interval_s = float(flight_interval_s)
        self._last_flight_t = 0.0
        # Features whose LAST completed step's success is still
        # unconfirmed by a host sync (see the probe-success note in
        # _loop); cleared on every rebuild.
        self._pending_success: tuple = ()
        # Drain-on-signal: once set, new POSTs 503 with Retry-After,
        # in-flight requests run to completion (bounded by the deadline)
        # and the loop exits cleanly.
        self.drain_timeout_s = float(drain_timeout_s)
        self._draining = threading.Event()
        self._drain_deadline: Optional[float] = None
        # Step watchdog: the loop heartbeats every iteration; a monitor
        # thread flips /healthz to a degraded payload when the heartbeat
        # goes stale past the deadline (a wedged dispatch, not a crash —
        # crashes drain loudly).  None disables the monitor thread.
        self.watchdog_deadline_s = watchdog_deadline_s
        self.watchdog_interval_s = watchdog_interval_s
        self.watchdog_stalls_total = 0
        self._heartbeat = time.monotonic()
        self._stalled = False
        self._inbox: "queue.Queue[_Pending]" = queue.Queue()
        # Control path (thread-safe queue): foreign threads schedule
        # batcher work (handoff export/import) the loop executes
        # between steps — see call_on_loop.
        self._control: "queue.Queue[_ControlCall]" = queue.Queue()
        self._active: Dict[int, _Pending] = {}
        self._stop = threading.Event()
        self._closed = threading.Event()  # set once the loop has drained
        self._loop_thread = threading.Thread(
            target=self._loop, name="llm-serving-loop", daemon=True
        )
        self._watchdog_thread = (
            threading.Thread(
                target=self._watchdog, name="llm-watchdog", daemon=True
            )
            if watchdog_deadline_s is not None else None
        )

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet test output
                pass

            def _reply(self, code: int, body: bytes, ctype: str,
                       headers: Optional[Dict[str, str]] = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, obj: Dict[str, Any],
                            headers: Optional[Dict[str, str]] = None):
                self._reply(
                    code, json.dumps(obj).encode(), "application/json",
                    headers,
                )

            def do_GET(self):
                parts = urlsplit(self.path)
                route, query = parts.path, parse_qs(parts.query)

                def qint(name: str, default: int) -> int:
                    try:
                        return int(query.get(name, [default])[0])
                    except ValueError:
                        return default

                if route == "/healthz":
                    h = server._health()
                    self._reply_json(200 if h["ok"] else 503, h)
                elif route == "/metrics":
                    self._reply(
                        200, server._metrics_text().encode(),
                        "text/plain; version=0.0.4",
                    )
                elif route == "/debug/requests":
                    self._reply_json(
                        200, server.obs.requests_json(qint("n", 64))
                    )
                elif route.startswith("/debug/requests/"):
                    rid = unquote(route[len("/debug/requests/"):])
                    tl = server.obs.timeline_json(rid)
                    if tl is None:
                        self._reply_json(
                            404,
                            {"error": f"unknown request id {rid!r} "
                                      "(timeline evicted or never seen)"},
                        )
                    else:
                        self._reply_json(200, tl)
                elif route == "/debug/dispatches":
                    self._reply_json(
                        200, server.obs.dispatches_json(qint("n", 128))
                    )
                elif route == "/debug/decisions":
                    # Decision audit log: ?kind= filters one decision
                    # class, ?request_id= joins to a request timeline.
                    self._reply_json(
                        200,
                        server.obs.decisions.json(
                            n=qint("n", 128),
                            kind=(query.get("kind") or [None])[0],
                            request_id=(
                                query.get("request_id") or [None]
                            )[0],
                        ),
                    )
                elif route == "/debug/bundle":
                    # Flight-recorder postmortem artifact (?trace=0
                    # drops the Perfetto doc for a lighter pull).
                    self._reply_json(
                        200,
                        server.bundle_json(trace=qint("trace", 1) > 0),
                    )
                elif route == "/debug/kv":
                    # Full (depth-capped, node-bounded) chain-digest
                    # walk — reads only the lock-guarded KvDigest, so
                    # handler threads never touch the confined store.
                    # ?since=V answers the INCREMENTAL form (journaled
                    # digest events past version V) for the router's
                    # global radix index sync.
                    depth = qint("depth", 0)
                    since = qint("since", -1)
                    self._reply_json(
                        200,
                        server.batcher.kv_debug_json(
                            depth=depth if depth > 0 else None,
                            max_nodes=qint("n", 2048),
                            since=since if since >= 0 else None,
                        ),
                    )
                elif route == "/debug/trace":
                    window_ms = None
                    if "window_s" in query:
                        try:
                            window_ms = (
                                float(query["window_s"][0]) * 1000.0
                            )
                        except ValueError:
                            self._reply_json(
                                400, {"error": "bad window_s"}
                            )
                            return
                    self._reply_json(
                        200, server.obs.trace_json(window_ms)
                    )
                elif route == "/debug/profile/summary":
                    self._reply_json(
                        *server._profile_summary(query)
                    )
                else:
                    self._reply_json(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in (
                    "/generate", "/chat", "/debug/profiler"
                ):
                    self._reply_json(404, {"error": "not found"})
                    return
                # End-to-end request id: honor the client's
                # X-Request-Id (so a failure is traceable from THEIR
                # logs), otherwise mint one; echoed in every reply from
                # here on — including the refusals below.
                ext_id = (
                    self.headers.get("X-Request-Id") or ""
                ).strip()[:128] or uuid.uuid4().hex[:16]
                # Every refusal below carries the id as a header too —
                # proxies correlate on headers, not 4xx/5xx bodies.
                rid_hdr = {"X-Request-Id": ext_id}
                is_debug = self.path == "/debug/profiler"
                if not is_debug and (
                    server._draining.is_set() or server._closed.is_set()
                ):
                    # Drain mode / shutdown: refuse BEFORE reading the
                    # body, with Retry-After so well-behaved clients back
                    # off until a replacement instance is routable.
                    self._reply_json(
                        503,
                        {"error": (
                            "server draining; retry later"
                            if server._draining.is_set()
                            and not server._closed.is_set()
                            else "server shutting down"
                        ), "request_id": ext_id},
                        headers={
                            "Retry-After": str(server._retry_after_s()),
                            **rid_hdr,
                        },
                    )
                    return
                # Body-size cap: the client-supplied Content-Length used
                # to be trusted unboundedly — a hostile length could pin
                # max_queue * max_body bytes of handler-thread memory.
                # Oversized or missing lengths are refused before any
                # read.
                cl = self.headers.get("Content-Length")
                if cl is None:
                    self._reply_json(
                        413, {"error": "Content-Length required",
                              "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                try:
                    n = int(cl)
                    if n < 0:
                        raise ValueError(cl)
                except ValueError:
                    self._reply_json(
                        400, {"error": f"bad Content-Length: {cl!r}",
                              "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                if n > server.max_body_bytes:
                    self._reply_json(
                        413,
                        {"error": (
                            f"request body too large ({n} bytes > "
                            f"{server.max_body_bytes} allowed)"
                        ), "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply_json(
                        400, {"error": f"bad request: {e}",
                              "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                if not isinstance(payload, dict):
                    # A JSON list/string/number parses fine but every
                    # consumer downstream calls payload.get — refuse
                    # here, not via an AttributeError traceback that
                    # closes the socket with no HTTP response.
                    self._reply_json(
                        400, {"error": "request body must be a JSON "
                                       "object", "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                if is_debug:
                    self._reply_json(*server._handle_profiler(payload))
                    return
                # Priority class (overload.py): optional "priority"
                # field, strictly validated — junk is the client's
                # defect (400), not a silent default that would let a
                # typo'd "interactiv" jump the batch queue.
                priority = payload.get("priority", "interactive")
                if priority not in PRIORITIES and priority != CANARY:
                    # CANARY is the router's reserved probe class:
                    # accepted (it rides the interactive queue) but
                    # excluded from SLO/goodput/ladder accounting.
                    self._reply_json(
                        400,
                        {"error": (
                            f'"priority" must be one of '
                            f'{list(PRIORITIES)}, got {priority!r}'
                        ), "request_id": ext_id},
                        headers=rid_hdr,
                    )
                    return
                # timeout_s parses BEFORE admission: the deadline-aware
                # refusal needs it, and a malformed value must 400, not
                # feed the cost model garbage.  NaN would make every
                # deadline comparison False and silently disable the
                # bound; inf is equally useless.
                timeout_s = payload.get("timeout_s")
                t = None
                if timeout_s is not None:
                    try:
                        t = float(timeout_s)
                        if not math.isfinite(t):
                            raise ValueError(timeout_s)
                    except (TypeError, ValueError):
                        self._reply_json(
                            400,
                            {"error": "timeout_s must be a finite number",
                             "request_id": ext_id},
                            headers=rid_hdr,
                        )
                        return
                # Admission control (overload.py): the queue-depth
                # backstop (each blocked POST holds an OS thread for
                # the full generation, so an unbounded inbox is an
                # unbounded thread/memory leak under flood), the
                # brownout ladder's batch-class gate, and the
                # cost-based deadline proof.  Every refusal is a 503
                # with a load-derived Retry-After.
                # audit: racy-read(admission-bound estimate: _active
                # is mutated by the loop thread; an off-by-a-few depth
                # only shifts when the 503 overload refusal fires)
                depth = (
                    server._inbox.qsize() + len(server._active)
                    + server.overload.queued_total()
                )
                cost = server._cost_estimate(payload)
                refusal = server.overload.admit(priority, cost, t, depth)
                if refusal is not None:
                    self._reply_json(
                        503,
                        {"error": refusal.reason, "request_id": ext_id},
                        headers={
                            "Retry-After": str(refusal.retry_after_s),
                            **rid_hdr,
                        },
                    )
                    return
                now = time.monotonic()
                pending = _Pending(
                    payload=payload, stream=bool(payload.get("stream")),
                    chat=self.path == "/chat",
                    want_lp=bool(payload.get("logprobs")),
                    ext_id=ext_id,
                    priority=priority, cost_tokens=cost,
                    # TTFT counts from POST arrival: with per-class
                    # queues a request can wait pre-admission far
                    # longer than the old always-drained inbox, and
                    # the client's clock started here.
                    received_at=now, submitted_at=now,
                    route=(
                        self.headers.get("X-Routed-By") or ""
                    ).strip()[:64] or None,
                )
                if t is not None:
                    pending.deadline = now + t
                server._inbox.put(pending)
                if pending.stream:
                    self._stream_reply(pending)
                else:
                    self._blocking_reply(pending)

            def _client_gone(self) -> bool:
                # Readable-EOF probe: a closed client socket selects
                # readable and MSG_PEEK returns b"".  Without this, a
                # client that disconnects while its request is QUEUED or
                # mid-generation (no tokens flowing to a blocking caller,
                # so no write ever fails) would keep its slot, blocks,
                # and decode work until natural completion.
                # Known trade-off: a client that half-closes
                # (shutdown(SHUT_WR)) after POSTing and then waits to
                # read is indistinguishable from a vanished one at this
                # layer and gets cancelled; HTTP/1.1 clients that
                # half-close are rare and widely treated as aborts
                # (nginx/gunicorn behave the same way).
                try:
                    r, _, _ = select.select([self.connection], [], [], 0)
                    if not r:
                        return False
                    return (
                        self.connection.recv(1, socket.MSG_PEEK) == b""
                    )
                except (OSError, ValueError):
                    return True

            def _blocking_reply(self, pending: "_Pending"):
                # Poll _closed so a request enqueued just as the loop dies
                # (put racing the final drain) still unblocks.
                while not pending.done.wait(timeout=1.0):
                    if server._closed.is_set() and not pending.done.is_set():
                        pending.fail("server shutting down", 503)
                        break
                    if self._client_gone():
                        pending.disconnected = True
                        return  # the loop reaps the request
                rid_hdr = {"X-Request-Id": pending.ext_id}
                if pending.timed_out:
                    body: Dict[str, Any] = {
                        "error": "generation timed out",
                        "request_id": pending.ext_id,
                        "tokens": pending.tokens,
                    }
                    if pending.want_lp:
                        # Partial results keep their logprobs — the
                        # streaming timeout final line already does.
                        body["logprobs"] = pending.lps
                    self._reply_json(504, body, headers=rid_hdr)
                    return
                if pending.error is not None:
                    if pending.retry_after_s is not None:
                        # Shed under overload: the 503 carries the
                        # load-derived Retry-After like every other
                        # refusal path.
                        rid_hdr = {
                            "Retry-After": str(pending.retry_after_s),
                            **rid_hdr,
                        }
                    self._reply_json(
                        pending.error_code,
                        {"error": pending.error,
                         "request_id": pending.ext_id},
                        headers=rid_hdr,
                    )
                    return
                out: Dict[str, Any] = {
                    "request_id": pending.ext_id,
                    "tokens": pending.tokens,
                }
                if pending.truncated:
                    out["truncated"] = True
                if pending.want_lp:
                    out["logprobs"] = pending.lps
                if server.tokenizer is not None:
                    out["text"] = server.tokenizer.decode(
                        server._visible(pending.tokens, pending)
                    )
                self._reply_json(200, out, headers=rid_hdr)

            def _stream_reply(self, pending: "_Pending"):
                """NDJSON token stream; body is close-delimited (no
                Content-Length).  Response headers are DEFERRED until
                the first event: a stream request that terminates
                before emitting any token (shed under overload, queued
                past its deadline, server drain) gets a REAL HTTP
                error status — 503s with the load-derived Retry-After
                — instead of a 200 stream whose only line is an error
                (load balancers and retry layers act on status codes,
                not NDJSON bodies).  A failed socket write marks the
                request disconnected; the loop cancels it at the next
                step."""
                started = False

                def start_stream() -> None:
                    nonlocal started
                    if started:
                        return
                    started = True
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson"
                    )
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.send_header("X-Request-Id", pending.ext_id)
                    self.end_headers()

                def emit(obj: Dict[str, Any]) -> bool:
                    try:
                        start_stream()
                        self.wfile.write(json.dumps(obj).encode() + b"\n")
                        self.wfile.flush()
                        return True
                    except OSError:
                        pending.disconnected = True
                        return False

                while True:
                    try:
                        ev = pending.chunks.get(timeout=1.0)
                    except queue.Empty:
                        if server._closed.is_set():
                            pending.fail("server shutting down", 503)
                            ev = _DONE
                        elif self._client_gone():
                            pending.disconnected = True
                            return  # the loop reaps the request
                        else:
                            continue
                    if ev is _DONE:
                        break
                    tok, lp = ev
                    # Every stream event carries the end-to-end id, so a
                    # line-oriented log pipeline can attribute a
                    # mid-stream failure without joining on the socket.
                    line: Dict[str, Any] = {
                        "token": tok, "request_id": pending.ext_id,
                    }
                    if lp is not None:
                        line["logprob"] = lp
                    if server.tokenizer is not None:
                        line["text"] = server.tokenizer.decode(
                            server._visible([tok], pending)
                        )
                    if not emit(line):
                        return  # client gone; the loop reaps the request
                if not started and not pending.tokens and (
                    pending.error is not None or pending.timed_out
                ):
                    # Terminal before any token flowed: reply with the
                    # real status (the stream never started, so the
                    # status line is still ours to send).
                    code = (
                        504 if pending.timed_out else pending.error_code
                    )
                    headers = {"X-Request-Id": pending.ext_id}
                    if pending.retry_after_s is not None:
                        headers["Retry-After"] = str(
                            pending.retry_after_s
                        )
                    self._reply_json(
                        code,
                        {"error": (
                            pending.error or "generation timed out"
                        ), "request_id": pending.ext_id},
                        headers=headers,
                    )
                    return
                final: Dict[str, Any] = {
                    "done": True,
                    "request_id": pending.ext_id,
                    "tokens": pending.tokens,
                }
                if pending.truncated:
                    final["truncated"] = True
                if pending.want_lp:
                    final["logprobs"] = pending.lps
                if pending.timed_out:
                    final["timeout"] = True
                if pending.error is not None:
                    final["error"] = pending.error
                emit(final)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, name="llm-http", daemon=True
        )

    # -- lifecycle ----------------------------------------------------------

    @property
    def obs(self) -> Observability:
        """The shared observability sink (rides the batcher so it
        survives quarantine/recovery rebuilds — same lifetime rule as
        the fault injector)."""
        return self.batcher.obs

    def _log(self, event: str, message: str = "", **fields) -> None:
        # self.logger is never None (the ctor substitutes a quiet
        # ring-only logger), so every event reaches the bundle tail.
        self.logger.log(event, message, **fields)

    def _slo_finalize(self, p: "_Pending", completed: bool) -> None:
        """Score one request against the configured SLOs, exactly once,
        at its terminal transition (finish / fail / timeout).  Client
        disconnects are NOT scored — the latency a vanished client
        would have observed is unattributable, and counting aborts as
        misses would let a flaky client poison the attainment gauges."""
        if p.slo_accounted:
            return
        p.slo_accounted = True
        if p.priority == CANARY:
            # Reserved probe class (overload.CANARY): a canary is the
            # ROUTER measuring this replica, never workload — scoring
            # it would let the probe distort the attainment gauges
            # and (worse) feed the brownout ladder its own probes.
            return
        self.obs.slo_account(
            p.ttft_ms, p.itl_max_ms, len(p.tokens), completed=completed
        )
        # Per-class window for the brownout ladder (overload.py) —
        # the same pass/fail math as slo_account (an unset dimension
        # always passes); the ladder reads the interactive window.
        o = self.obs
        ttft_ok = completed and (
            o.slo_ttft_ms is None
            or (p.ttft_ms is not None and p.ttft_ms <= o.slo_ttft_ms)
        )
        itl_ok = completed and (
            o.slo_itl_ms is None
            or p.itl_max_ms is None or p.itl_max_ms <= o.slo_itl_ms
        )
        self.overload.note_slo(
            p.priority, ttft_ok, itl_ok, completed and ttft_ok and itl_ok
        )

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "LLMServer":
        # audit: unguarded(happens-before: the loop/watchdog threads
        # start below, after this write)
        self._heartbeat = time.monotonic()
        self._loop_thread.start()
        if self._watchdog_thread is not None:
            self._watchdog_thread.start()
        self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        self._loop_thread.join(timeout=30)
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=10)

    def call_on_loop(self, fn, timeout_s: float = 30.0):
        """Run ``fn(batcher)`` on the serving-loop thread (the
        batcher's single owner) and return its result — the control
        path the router's cache-aware handoff scheduler uses to drive
        ``export_prefix`` / ``import_prefix`` without violating thread
        confinement.  Blocks the CALLING thread up to ``timeout_s``;
        past it the call is cancelled (never runs if the loop had not
        picked it up; a call already mid-run completes and its result
        drops) and :class:`TimeoutError` raises — so a wedged or
        heavily loaded loop bounds the scheduler instead of hanging
        it.  Raises ``TimeoutError`` immediately when the loop is not
        running (stopped / crashed / never started)."""
        if self._closed.is_set() or not self._loop_thread.is_alive():
            raise TimeoutError("serving loop is not running")
        call = _ControlCall(fn)
        self._control.put(call)
        if not call.done.wait(timeout_s):
            call.cancelled.set()
            raise TimeoutError(
                f"control call did not complete within {timeout_s}s"
            )
        if call.error is not None:
            raise call.error
        return call.result

    def _drain_control(self) -> None:
        """Execute queued control calls (loop thread only).  Errors
        are CAPTURED into the call — a failed handoff export must
        never take down the device-owning thread."""
        while True:
            try:
                call = self._control.get_nowait()
            except queue.Empty:
                return
            if call.cancelled.is_set():
                continue
            try:
                call.result = call.fn(self.batcher)
            except BaseException as e:
                call.error = e
            call.done.set()

    def begin_drain(self, timeout_s: Optional[float] = None) -> None:
        """Flip the server into drain mode (the SIGTERM/SIGINT path):
        in-flight requests run to completion, new POSTs get 503 +
        Retry-After, and the serving loop exits once idle — or once
        ``timeout_s`` (default ``drain_timeout_s``) elapses, at which
        point stragglers are failed with 503.  Idempotent: the first
        call pins the deadline.  HTTP listeners stay up through the
        drain (clients must be able to read their streams and /healthz
        must report the drain); call ``stop()`` after ``wait_drained``
        to close the sockets."""
        if self._draining.is_set():
            return
        t = self.drain_timeout_s if timeout_s is None else float(timeout_s)
        self._drain_deadline = time.monotonic() + max(0.0, t)
        self._draining.set()
        self.obs.decisions.record("drain", timeout_s=round(t, 3))

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until the serving loop has exited (drain complete or
        hard stop); returns False on timeout."""
        return self._closed.wait(timeout)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def wait_idle(
        self, timeout_s: float = 30.0, poll_s: float = 0.05,
    ) -> bool:
        """Fleet-controller drain hook: block until the serving loop is
        idle (no admitted work) WITHOUT tearing it down — unlike
        ``begin_drain``, the loop stays alive afterwards so control
        calls (the session-migration ``export_prefix`` path) still run.
        The controller stops routing to this replica first, then waits
        here for stragglers to finish; returns False on timeout (the
        drain aborts and the replica resumes).  Each probe runs on the
        loop thread between steps, so a True result is an exact
        no-admitted-work snapshot, not a racy guess."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            try:
                if self.call_on_loop(
                    lambda b: not b.pending(), timeout_s=timeout_s,
                ):
                    return True
            except TimeoutError:
                return False
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def shutdown_for_restart(self, grace_s: float = 5.0) -> bool:
        """Rollout restart hook: bounded drain + full stop in one call.
        The controller swaps a freshly built replacement into the
        router FIRST (sessions already migrated off), then retires this
        instance — any straggler past ``grace_s`` fails with 503 rather
        than wedging the rung.  Returns True when the loop exited
        within the grace window."""
        self.begin_drain(timeout_s=grace_s)
        ok = self.wait_drained(grace_s + 10.0)
        self.stop()
        return ok

    def _retry_after_s(self) -> int:
        """Retry-After value for drain-mode 503s: the remaining drain
        budget, rounded up — after that a replacement instance should be
        routable."""
        dl = self._drain_deadline
        if dl is None:
            return max(1, int(math.ceil(self.drain_timeout_s)))
        return max(1, int(math.ceil(dl - time.monotonic())))

    @staticmethod
    def _cost_estimate(payload: Dict[str, Any]) -> int:
        """Admission-cost estimate in prompt tokens: exact for token
        prompts, a chars/4 heuristic for text and chat dialogs (BPE
        averages ~4 chars/token on English text).  Feeds only the
        overload controller's TTFT lower bound and Retry-After — an
        estimate by design, never token accounting."""
        p = payload.get("prompt")
        if isinstance(p, (list, tuple)):
            return len(p)
        text = payload.get("text")
        if isinstance(text, str):
            return max(1, len(text) // 4)
        msgs = payload.get("messages")
        if isinstance(msgs, list):
            n = sum(
                len(m["content"]) // 4
                for m in msgs
                if isinstance(m, dict)
                and isinstance(m.get("content"), str)
            )
            # + a few framing tokens per message (role headers).
            return max(1, n + 4 * len(msgs))
        return 1

    def _apply_overload_knobs(self, entering: bool = False) -> None:
        """Apply the current brownout rung's knobs to the batcher
        (loop thread only — the batcher has a single owner).  Called
        on every ladder transition AND after every batcher rebuild: a
        rebuilt batcher starts from the base ctor's prefill budget, so
        the rung's shrink must be re-applied or a crash recovery would
        silently reset the brownout.  ``entering=True`` additionally
        fires the rung's one-shot host-tier demotion sweep (an
        operational HBM-pressure release, not a steady-state drain).
        The batch-class max_new cap is NOT applied here — it clamps at
        ``_submit`` time, so it follows the ladder dynamically."""
        kn = self.overload.knobs()
        base = int(self._base_ctor[2].get("prefill_budget", 0) or 0)
        if base > 0 and not self.batcher.spec:
            # Shrink, never zero: prefill_budget=0 would flip the
            # batcher to classic whole-prompt admission — the opposite
            # of protecting ITL.
            self.batcher.prefill_budget = max(
                1, int(base * kn.prefill_budget_scale)
            )
        if entering and kn.demote_blocks > 0:
            self.batcher.demote_idle(kn.demote_blocks)

    def __enter__(self) -> "LLMServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving loop (sole owner of the batcher) ---------------------------

    def _visible(self, tokens: List[int], p: "_Pending") -> List[int]:
        """Tokens to DECODE for a reply: /chat strips the tokenizer's stop
        ids (the eot/eos framing is protocol, not assistant text);
        /generate returns everything verbatim.  A /chat request that sent
        its own "stop_tokens" is also verbatim — the tokenizer's stop set
        is not framing for it, and a mid-stream eot the client asked to
        generate past must survive into "text"."""
        if not p.chat or p.stops_overridden:
            return list(tokens)
        stops = set(getattr(self.tokenizer, "stop_tokens", None) or ())
        return [t for t in tokens if t not in stops]

    def _submit(self, p: _Pending) -> None:
        payload = p.payload
        if p.want_lp and not getattr(self.batcher, "logprobs", False):
            raise ValueError(
                '"logprobs" needs a batcher constructed with '
                "logprobs=True (run.py: --logprobs)"
            )
        if p.chat:
            if self.chat_format is None:
                raise ValueError(
                    "/chat needs a server-side chat_format "
                    "(e.g. tokenizers.llama3.ChatFormat)"
                )
            messages = payload.get("messages")
            if not isinstance(messages, list) or not messages:
                raise ValueError(
                    'missing "messages" (non-empty list of '
                    '{"role", "content"})'
                )
            for m in messages:
                # Type-check the values too: ChatFormat calls .strip() /
                # encode() on them, and an AttributeError from a payload
                # is not in the loop's caught-error set — one malformed
                # request must never kill the device-owning thread.
                if (
                    not isinstance(m, dict)
                    or not isinstance(m.get("role"), str)
                    or not isinstance(m.get("content"), str)
                ):
                    raise ValueError(
                        'each message needs string "role" and "content"'
                    )
            tokens = self.chat_format.encode_dialog_prompt(messages)
        elif "prompt" in payload:
            tokens = [int(t) for t in payload["prompt"]]
        elif "text" in payload:
            if self.tokenizer is None:
                raise ValueError(
                    '"text" prompts need a server-side tokenizer; send '
                    'token ids as "prompt"'
                )
            tokens = self.tokenizer.encode(
                payload["text"], bos=True, eos=False
            )
        else:
            raise ValueError('missing "prompt" (token ids) or "text"')
        kwargs: Dict[str, Any] = {}
        for k in ("max_new_tokens", "top_k", "seed"):
            if payload.get(k) is not None:
                kwargs[k] = int(payload[k])
        # Brownout cap (overload.py): at brownout-1 and deeper the
        # ladder caps batch-class generation budgets so each batch
        # admission returns its slot and blocks sooner; interactive
        # budgets are never touched.
        cap = self.overload.knobs().batch_max_new_cap
        if cap > 0 and p.priority == "batch":
            kwargs["max_new_tokens"] = min(
                int(kwargs.get("max_new_tokens", _SUBMIT_DEFAULT_MAX_NEW)),
                cap,
            )
        for k in ("temperature", "top_p"):
            if payload.get(k) is not None:
                kwargs[k] = float(payload[k])
        if payload.get("stop_tokens") is not None:
            kwargs["stop_tokens"] = tuple(
                int(t) for t in payload["stop_tokens"]
            )
            p.stops_overridden = True
        elif p.chat:
            # Dialog completions stop at the tokenizer's stop set
            # (llama3: end_of_text + eot_id) unless overridden.
            stops = getattr(self.tokenizer, "stop_tokens", None)
            if stops:
                kwargs["stop_tokens"] = tuple(int(t) for t in stops)
        rid = self.batcher.submit(tokens, **kwargs)
        p.request_id = rid
        if p.priority == CANARY:
            self.canary_requests_total += 1
        # The batcher opened the timeline under a provisional r<rid>
        # key; attach the END-TO-END id so /debug/requests/<ext_id>
        # resolves (replays re-bind their fresh rid into the same
        # timeline — see _rebuild_and_replay).
        self.obs.bind(rid, p.ext_id)
        if p.route is not None:
            # Router decision onto the timeline + annotation ring —
            # /debug/requests/<id> shows which replica served it.
            self.obs.set_route(p.ext_id, p.route)
        if p.submitted_at is None:  # replays keep the original stamp
            p.submitted_at = time.monotonic()
        # Snapshot the replay state (crash recovery resubmits from it):
        # original prompt, resolved sampling kwargs, and the seed pinned
        # to its resolved value — a replayed request gets a new id, so
        # leaving the seed implicit would silently fork its chain.
        p.prompt_tokens = list(tokens)
        p.submit_kwargs = dict(kwargs)
        p.max_new = int(kwargs.get("max_new_tokens", _SUBMIT_DEFAULT_MAX_NEW))
        p.replay_seed = (
            int(kwargs["seed"]) if kwargs.get("seed") is not None
            else self.batcher.default_seed(rid)
        )
        self._active[rid] = p

    def _reap(self) -> None:
        """Cancel expired and disconnected requests (loop thread only —
        the batcher has a single owner)."""
        now = time.monotonic()
        for rid, p in list(self._active.items()):
            expired = p.deadline is not None and now >= p.deadline
            if not (expired or p.disconnected):
                continue
            # Timeouts record as FAILED (the registry counts timeouts
            # under requests_failed_total); only disconnects and
            # explicit cancels are "cancelled".
            self.batcher.cancel(
                rid,
                outcome="cancelled" if p.disconnected else "failed",
                error=None if p.disconnected else "generation timed out",
            )
            del self._active[rid]
            if p.disconnected:
                self._log(
                    "request_disconnected", request_id=p.ext_id, rid=rid
                )
                p.finish()  # nobody is reading; just release state
            elif p.stream:
                p.timed_out = True
                self._slo_finalize(p, completed=False)
                self._log(
                    "request_timeout", request_id=p.ext_id, rid=rid,
                    tokens=len(p.tokens),
                )
                p.finish()
            else:
                p.timed_out = True
                self._slo_finalize(p, completed=False)
                self._log(
                    "request_timeout", request_id=p.ext_id, rid=rid,
                    tokens=len(p.tokens),
                )
                p.fail("generation timed out", 504)

    def _reap_preadmission(self) -> None:
        """Deadline/disconnect reaping for requests still waiting in
        the overload controller's class queues — the pre-admission arm
        of ``_reap``.  These checks used to happen at inbox pop, but
        the per-class queues can hold an entry much longer (a batch
        request behind a brownout, anything behind a backlog)."""
        expired, gone = self.overload.reap(time.monotonic())
        for p in gone:
            self._log("request_disconnected", request_id=p.ext_id)
            p.finish()  # client vanished before admission
        for p in expired:
            # Expired while queued — the overload signature.  These
            # worst-latency requests MUST hit the SLO window, or
            # attainment reads healthy exactly when the server is
            # drowning; and they get a terminal timeline + failed
            # count even though no batcher rid ever existed, so
            # /debug/requests/<id> explains the 504.
            p.timed_out = True
            self._slo_finalize(p, completed=False)
            self.obs.request_rejected(
                p.ext_id,
                "generation timed out before admission "
                "(server overloaded)",
            )
            self._log(
                "request_timeout", "expired pre-admission",
                request_id=p.ext_id,
            )
            p.fail("generation timed out", 504)

    def _attribute(self, exc: BaseException) -> Optional[str]:
        """Map a dispatch exception to the degradable feature that
        caused it, or None (generic failure -> crash-recovery budget).
        Injected faults from the kernel/spec/suffix sites carry their
        site name; real device errors are recognized by Pallas/Mosaic
        markers in the text plus the batcher's last-dispatch record."""
        site = getattr(exc, "site", None)
        if site in _SITE_FEATURES:
            return _SITE_FEATURES[site]
        text = f"{type(exc).__name__}: {exc}".lower()
        if any(m in text for m in _KERNEL_ERROR_MARKERS):
            feats = getattr(self.batcher, "last_dispatch_features", ())
            # Opt-in kernels first: when a dispatch ran the splash or
            # stock kernel it ALSO exercised the custom-kernel path
            # (both feature names are in feats), and quarantining the
            # opt-in rung first keeps the fallback ladder one step at
            # a time (splash -> flash, stock-paged -> paged) instead
            # of knocking the dispatch all the way to XLA/gathered.
            # A splash-named error on a stock-kernel decode dispatch
            # still lands on stock_paged via this order — acceptable:
            # the two never share a dispatch kind.
            for f in (
                "splash_prefill", "stock_paged",
                "paged_kernel", "flash_attention",
            ):
                if f in feats:
                    return f
        return None

    def _build_batcher(self) -> ContinuousBatcher:
        """Fresh batcher from the ORIGINAL construction with every
        currently-quarantined feature swapped for its fallback.  Probing
        features count as enabled — that is what a probe rebuild is."""
        params, config, kwargs = self._base_ctor
        kw = dict(kwargs)
        # Kernel-selection rungs first: each falls back to the EXISTING
        # custom kernel (ctor kwargs override the config fields, so this
        # wins over a baked-in "splash"/"stock-paged"/"auto").
        if not self.degrade.enabled("splash_prefill"):
            kw["prefill_kernel"] = "flash"
        if not self.degrade.enabled("stock_paged"):
            kw["decode_kernel"] = "paged"
        if not self.degrade.enabled("paged_kernel"):
            kw["use_pallas_kernel"] = False
        if not self.degrade.enabled("spec_decode"):
            kw["draft_params"] = None
            kw["draft_config"] = None
        if not self.degrade.enabled("prefix_cache"):
            kw["prefix_cache"] = False
        if (
            not self.degrade.enabled("flash_attention")
            and config.attn_impl != "xla"
        ):
            config = config.replace(attn_impl="xla")
        return ContinuousBatcher(params, config, **kw)

    def _recover(self, exc: BaseException) -> bool:
        """Crash recovery: rebuild the batcher (fresh pool + host state
        from the still-held params) and resubmit every live request from
        the CPU-side snapshot each ``_Pending`` carries — original
        prompt + DELIVERED tokens as the replay prompt, remaining token
        budget, same sampling params/stops, seed pinned to its resolved
        value.  Greedy requests continue token-identically (teacher-
        forced prefix); streaming clients see only fresh continuation
        tokens, never a repeat, because the replay prompt already
        contains everything they received.

        Failures attributable to a degradable feature are budgeted by
        the QUARANTINE state machine instead of the breaker: each one
        rebuilds and replays like any recovery, but the bound on them is
        the feature's threshold/window (past it the feature falls back
        and the failures stop), not ``max_recoveries`` — so quarantine
        is reachable for ANY threshold, including thresholds above the
        breaker budget.  Once a feature is on its fallback, continuing
        crashes are unattributable and fill the breaker window normally,
        which keeps the hard-drain backstop for wrong attributions.

        Returns False when the circuit breaker trips (``max_recoveries``
        unattributable rebuilds inside ``recovery_window_s``): the
        caller re-raises and the finally-drain 503s every client
        instead of crash-looping."""
        feature = self._attribute(exc)
        if feature is not None:
            if self.degrade.record_failure(feature):
                self.quarantine_rebuilds_total += 1
                self._log(
                    "quarantine", f"{feature} quarantined: {exc!r}",
                    feature=feature,
                )
                self.obs.decisions.record(
                    "quarantine", feature=feature, error=repr(exc),
                )
            self.recoveries_total += 1
            self._log(
                "crash_recovery", repr(exc), feature=feature,
                recoveries_total=self.recoveries_total,
            )
            self.obs.decisions.record(
                "recovery", feature=feature, error=repr(exc),
                recoveries_total=self.recoveries_total,
            )
            self._rebuild_and_replay()
            return True
        now = time.monotonic()
        self._recovery_times = [
            t for t in self._recovery_times
            if now - t < self.recovery_window_s
        ]
        if len(self._recovery_times) >= self.max_recoveries:
            self.obs.decisions.record(
                "recovery_breaker_tripped", error=repr(exc),
                recoveries_in_window=len(self._recovery_times),
            )
            return False
        self._recovery_times.append(now)
        self.recoveries_total += 1
        self._log(
            "crash_recovery", repr(exc),
            recoveries_total=self.recoveries_total,
        )
        self.obs.decisions.record(
            "recovery", error=repr(exc),
            recoveries_total=self.recoveries_total,
        )
        self._rebuild_and_replay()
        return True

    def _rebuild_and_replay(self) -> None:
        """The recovery primitive shared by crash recovery, quarantine
        fallbacks, and probe re-enables: fresh batcher (base ctor +
        current feature overrides), then resubmit every live request
        from its CPU-side snapshot."""
        # Rebuild BEFORE detaching _active: if the rebuild itself dies
        # (e.g. a real OOM re-allocating the pool), the exception must
        # propagate with _active intact so the finally-drain still
        # delivers the crash reason to every in-flight client.
        new_batcher = self._build_batcher()
        old_active, self._active = self._active, {}
        self.batcher = new_batcher
        # Any un-credited step success died with the old batcher: the
        # exception that brought us here may have been its async work.
        self._pending_success = ()
        # The brownout ladder's knobs survive the rebuild: a fresh
        # batcher carries the BASE prefill budget, so re-apply the
        # rung's shrink (controller state itself is server-owned and
        # untouched by rebuilds, like the DegradeManager).
        self._apply_overload_knobs()
        bs = self.batcher.block_size
        for p in old_active.values():
            prompt = list(p.prompt_tokens) + list(p.tokens)
            remaining = p.max_new - len(p.tokens)
            # Replay headroom: prompt + delivered pads to a block
            # multiple, which can exceed the original prompt's padding
            # by up to a block — a request admitted within a block of
            # capacity can lose up to block_size-1 tokens of budget.
            # Clamp rather than reject, but SAY SO: a shortened reply
            # carries "truncated": true instead of silently posing as
            # the full fault-free completion.
            # _round_up is submit()'s own padding helper — the headroom
            # math must stay in lockstep with its admission check.
            room = self.batcher.max_len - _round_up(len(prompt), bs)
            if room < remaining:
                remaining = room
                p.truncated = True
            if remaining <= 0:
                # The client receives a (truncated) completion: a
                # TERMINAL delivery — close the timeline and score it,
                # or the finished counter and /debug disagree with the
                # 200 the client saw.
                self.obs.request_end(p.request_id, "finished")
                self._slo_finalize(p, completed=True)
                p.finish()  # deliver what the client already has
                continue
            kwargs = dict(p.submit_kwargs)
            kwargs["max_new_tokens"] = remaining
            kwargs["seed"] = p.replay_seed
            try:
                rid = self.batcher.submit(prompt, **kwargs)
            except (ValueError, TypeError) as e:
                msg = f"lost in crash recovery: {e}"
                self.obs.request_end(p.request_id, "failed", msg)
                p.fail(msg, 503)
                self._slo_finalize(p, completed=False)
                continue
            p.request_id = rid
            # Fold the replay's fresh rid (and its new queued span) into
            # the original external-id timeline, so /debug/requests/<id>
            # shows the whole story across batcher incarnations.
            self.obs.bind(rid, p.ext_id, replay=True)
            self._active[rid] = p

    def _watchdog(self) -> None:
        """Monitor thread: flag a stall when the serving loop's heartbeat
        goes stale past the deadline (the loop beats every iteration,
        idle included, so only a wedged dispatch — or a dead loop —
        stalls).  Passive by design: it flips /healthz degraded for the
        fleet's load balancer; it never touches the batcher."""
        while not self._stop.wait(self.watchdog_interval_s):
            if self._closed.is_set():
                break
            age = time.monotonic() - self._heartbeat
            if age > self.watchdog_deadline_s:
                if not self._stalled:
                    # audit: unguarded(single-writer: only the watchdog
                    # thread mutates _stalled / its counter; readers
                    # see a GIL-atomic bool/int snapshot)
                    self._stalled = True
                    # audit: unguarded(single-writer: watchdog thread
                    # only; readers snapshot a GIL-atomic int)
                    self.watchdog_stalls_total += 1
                    self._log(
                        "watchdog_stall", last_step_age_s=round(age, 3)
                    )
            else:
                # audit: unguarded(single-writer: watchdog thread only)
                self._stalled = False

    def _health(self) -> Dict[str, Any]:
        """The /healthz payload (schema in the module docstring):
        liveness + watchdog/recovery state + the full degraded state.
        ``ok`` is False (HTTP 503) when the loop is dead, stalled, or
        draining — load balancers must stop routing here in all three.
        A merely DEGRADED server (features quarantined, fallbacks
        serving) stays ``ok``: staying routable on the slow path is the
        whole point of quarantine."""
        alive = self._loop_thread.is_alive() and not self._closed.is_set()
        draining = self._draining.is_set()
        features = self.degrade.snapshot()
        remaining = None
        if draining and self._drain_deadline is not None:
            remaining = round(
                max(0.0, self._drain_deadline - time.monotonic()), 3
            )
        return {
            "ok": alive and not self._stalled and not draining,
            "stalled": self._stalled,
            "loop_alive": alive,
            "last_step_age_s": round(
                time.monotonic() - self._heartbeat, 3
            ),
            "recoveries_total": self.recoveries_total,
            "watchdog_stalls_total": self.watchdog_stalls_total,
            "draining": draining,
            "drain_remaining_s": remaining,
            "degraded": self.degrade.degraded(),
            "quarantined": list(self.degrade.quarantined()),
            "kv": {
                # audit: racy-read(point-in-time /healthz snapshot of
                # loop-owned batcher state: len()/count reads are
                # GIL-atomic, a scrape may be one step stale)
                "prefix_index": getattr(
                    self.batcher, "prefix_index", "off"
                ),
                "host_kv_blocks": getattr(
                    self.batcher, "host_kv_blocks", 0
                ),
                "host_tier_blocks": self.batcher._store.host_blocks(),
                "swap_queue_depth": len(self.batcher._restoring),
                "restored_waiting": len(self.batcher._restored_ready),
                # Compact chain-digest summary (kvcache.KvDigest, its
                # own leaf lock) piggybacked for the router's health
                # poller: versions for staleness detection, residency
                # counts, the publish/evict/demote/restore ledger —
                # bounded O(1) payload, zero new poll endpoints.
                "digest": self.batcher.kv_digest.summary(),
                "block_bytes": self.batcher.block_bytes,
                "total_blocks": self.batcher.n_blocks,
                "prefix_hit_tokens_total": (
                    self.batcher.prefix_hit_tokens_total
                ),
                "prompt_tokens_total": self.batcher.prompt_tokens_total,
            },
            "overload": self.overload.health(),
            # Scale-out serving (serve_mesh.py / router.py): the mesh
            # this replica's batcher runs on and its occupancy — what
            # the ReplicaRouter's least-loaded policy and its
            # aggregate /healthz ``replicas`` section read.
            "replica": {
                "id": self.replica_id,
                # audit: racy-read(point-in-time /healthz snapshot of
                # loop-owned batcher occupancy; len()/sum reads are
                # GIL-atomic, a scrape may be one step stale)
                # The sharding actually ACTIVE: meshes outside the
                # placement envelope report 1/1 + placed=False, so a
                # fleet scrape sees the degraded (unplaced) state
                # instead of the mesh the batcher was merely handed.
                "serve_mesh": smesh.mesh_shape(
                    getattr(self.batcher, "mesh", None)
                    if getattr(self.batcher, "_mesh_placed", False)
                    else None
                ),
                "serve_mesh_placed": bool(
                    getattr(self.batcher, "_mesh_placed", False)
                ),
                "active_slots": sum(
                    s is not None for s in self.batcher.slots.values()
                ),
                "n_slots": self.batcher.n_slots,
                # Per-replica ITL degradation signal for the router's
                # health sentinel (None until two non-canary tokens
                # have been delivered).
                "itl_ms_ewma": (
                    round(self.itl_ms_ewma, 3)
                    if self.itl_ms_ewma is not None else None
                ),
                "queued": (
                    self._inbox.qsize() + len(self._active)
                    + self.overload.queued_total()
                ),
                "kv_handoff_blocks": (
                    getattr(self.batcher, "kv_export_blocks_total", 0)
                    + getattr(self.batcher, "kv_import_blocks_total", 0)
                ),
            },
            "features": features,
        }

    def _handle_profiler(self, payload: Dict[str, Any]):
        """POST /debug/profiler — an on-demand ``jax.profiler`` session
        (the ``utils/profiling.trace`` context manager unrolled into two
        HTTP calls so it can bracket LIVE traffic):
        ``{"action": "start", "log_dir": DIR}`` begins an xplane trace,
        ``{"action": "stop"}`` ends it.  The resulting trace (view with
        TensorBoard's profile plugin / XProf) is the device-side
        complement of the host-side ``/debug/trace`` window.  Returns
        ``(status_code, body)`` for the handler's ``_reply_json``."""
        action = payload.get("action")
        if action == "start":
            log_dir = payload.get("log_dir")
            if not isinstance(log_dir, str) or not log_dir:
                return 400, {"error": 'start needs a "log_dir" string'}
            # Serialized: two concurrent starts racing the None check
            # would both reach jax.profiler (handler threads).
            with self._profiler_lock:
                if self._profiler_dir is not None:
                    return 409, {"error": (
                        f"profiler already tracing into "
                        f"{self._profiler_dir!r}; stop it first"
                    )}
                try:
                    import jax

                    jax.profiler.start_trace(log_dir)
                except Exception as e:  # surface, never crash the server
                    return 500, {"error": f"profiler start failed: {e}"}
                self._profiler_dir = log_dir
            self.obs.annotate("profiler_start", log_dir=log_dir)
            self._log("profiler_start", log_dir=log_dir)
            return 200, {"ok": True, "log_dir": log_dir}
        if action == "stop":
            with self._profiler_lock:
                if self._profiler_dir is None:
                    return 409, {"error": "no profiler session active"}
                log_dir = self._profiler_dir
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception as e:
                    # _profiler_dir is NOT cleared on failure: jax's
                    # session may still be live, and clearing would
                    # make both retry-stop (409) and restart (500)
                    # dead ends — unrecoverable without a process
                    # restart.  Keeping it lets the client retry stop.
                    return 500, {"error": f"profiler stop failed: {e}"}
                self._profiler_dir = None
                self._profiler_last_dir = log_dir
            self.obs.annotate("profiler_stop", log_dir=log_dir)
            self._log("profiler_stop", log_dir=log_dir)
            return 200, {"ok": True, "log_dir": log_dir}
        return 400, {"error": 'action must be "start" or "stop"'}

    def _profile_summary(self, query: Dict[str, List[str]]):
        """GET /debug/profile/summary[?log_dir=DIR] — parse the most
        recently completed profiler session's xplane capture into
        per-program device/host-ms attribution
        (``utils.profiling.summarize_xplane``).  Pure file parsing on
        the handler thread: zero device work, and the serving loop is
        never touched.  Returns ``(status_code, body)``."""
        log_dir = (query.get("log_dir") or [None])[0]
        with self._profiler_lock:
            active = self._profiler_dir
            if log_dir is None:
                log_dir = self._profiler_last_dir
        if log_dir is None:
            return 404, {"error": (
                "no completed profiler session; bracket traffic with "
                'POST /debug/profiler {"action": "start"/"stop"} '
                "first, or pass ?log_dir="
            )}
        if active is not None and log_dir == active:
            return 409, {"error": (
                f"profiler session into {log_dir!r} still active; "
                "stop it before summarizing"
            )}
        try:
            from .utils.profiling import summarize_xplane

            summary = summarize_xplane(log_dir)
        except ImportError as e:
            return 501, {"error": f"xplane protos unavailable: {e}"}
        except FileNotFoundError as e:
            return 404, {"error": str(e)}
        except Exception as e:  # surface a parse failure, never crash
            return 500, {"error": f"xplane parse failed: {e}"}
        summary["log_dir"] = log_dir
        return 200, summary

    def _loop(self) -> None:
        # The finally-drain guarantees no client blocks forever: whether
        # the loop exits via stop() or an unexpected device/runtime error,
        # every in-flight and queued request gets its done event set.
        reason, code = "server shutting down", 503
        try:
            while not self._stop.is_set():
                self._heartbeat = time.monotonic()
                # Flight recorder: one compact metric snapshot per
                # flight_interval_s (host-side dict building only) —
                # the /debug/bundle trend ring.
                if (
                    self.flight_interval_s > 0
                    and self._heartbeat - self._last_flight_t
                    >= self.flight_interval_s
                ):
                    self._last_flight_t = self._heartbeat
                    self.obs.record_metrics_snapshot(
                        self._flight_snapshot()
                    )
                # Control path: scheduled batcher work (handoff
                # export/import) runs HERE, between steps, on the
                # batcher's owning thread.
                self._drain_control()
                if self._draining.is_set():
                    # Drain mode: finish in-flight work, then exit
                    # cleanly; past the deadline fail the stragglers
                    # (the finally-drain delivers the 503s).
                    idle = (
                        not self._active
                        and self._inbox.empty()
                        and self.overload.queued_total() == 0
                        and not self.batcher.pending()
                    )
                    if idle:
                        break
                    if (
                        self._drain_deadline is not None
                        and time.monotonic() >= self._drain_deadline
                    ):
                        reason = (
                            "drain timeout: server shutting down before "
                            "this request finished"
                        )
                        break
                # Quarantined features whose cooldown expired get ONE
                # probe re-trial: rebuild with the feature re-enabled
                # (live requests replay, exactly as in crash recovery).
                # Success on the next exercising dispatch restores it;
                # failure re-quarantines via the normal recovery path.
                # Not while draining — a probe rebuild would discard the
                # very device state the drain is trying to finish.
                due = (
                    [] if self._draining.is_set()
                    else self.degrade.due_probes()
                )
                if due:
                    for f in due:
                        self.degrade.start_probe(f)
                    self.probe_rebuilds_total += 1
                    self._log("probe_rebuild", features=",".join(due))
                    self.obs.decisions.record(
                        "probe", features=",".join(due)
                    )
                    self._rebuild_and_replay()
                # Drain the inbox into the controller's per-class
                # queues (strict interactive-first ordering lives
                # there); block briefly when fully idle so shutdown
                # and new work are both responsive.
                try:
                    block = (
                        not self.batcher.pending()
                        and self.overload.queued_total() == 0
                    )
                    while True:
                        p = self._inbox.get(block=block, timeout=0.05)
                        block = False
                        self.overload.push(p)
                except queue.Empty:
                    pass
                self._reap_preadmission()
                # Brownout ladder (overload.py): evaluate the rung,
                # apply its knobs on a transition, shed queued batch
                # entries at the top rung.
                tr = self.overload.tick()
                if tr is not None:
                    old, new = tr
                    self._log(
                        "overload_transition", f"{old} -> {new}",
                        rung=new,
                    )
                    self.obs.annotate(
                        "overload_transition", old=old, state=new
                    )
                    # Decision log: the rung move WITH the signals
                    # that drove it, so /debug/decisions explains a
                    # brownout the way it explains a route.
                    ov = self.overload.health()
                    self.obs.decisions.record(
                        "brownout", old=old, rung=new,
                        rung_index=RUNG_INDEX[new],
                        interactive_attainment=(
                            ov["interactive_attainment"]
                        ),
                        queue_wait_ms_p90=ov["queue_wait_ms_p90"],
                        queued=ov["queued"],
                    )
                    # The one-shot demotion sweep is an ESCALATION
                    # pressure release only — re-firing it on recovery
                    # steps would evict warm prefix KV exactly as
                    # traffic returns.
                    self._apply_overload_knobs(
                        entering=RUNG_INDEX[new] > RUNG_INDEX[old]
                    )
                for p in self.overload.shed_batch():
                    msg = (
                        "shed under overload (brownout rung 'shed'); "
                        "retry later"
                    )
                    p.retry_after_s = self.overload.retry_after_s()
                    self.obs.request_rejected(p.ext_id, msg)
                    self._log(
                        "request_shed", request_id=p.ext_id,
                        priority=p.priority,
                    )
                    self.obs.decisions.record(
                        "shed", request_id=p.ext_id,
                        priority=p.priority,
                        retry_after_s=p.retry_after_s,
                    )
                    # Deliberately NOT SLO-scored: a shed is the
                    # controller protecting attainment — counting it
                    # as a miss would wedge the ladder at 'shed'.
                    p.fail(msg, 503)
                # Submit interactive-first while free slots can take
                # them; the rest wait ORDERED in the controller (the
                # batcher's own queue is FIFO, so keeping it shallow
                # is what makes interactive-first stick — at most
                # ``free`` entries are committed to FIFO order ahead
                # of a later interactive arrival).
                # audit: unguarded(serving-loop thread — the batcher's
                # owner — reading through its own holder alias)
                free = sum(
                    s is None for s in self.batcher.slots.values()
                )
                # audit: unguarded(owner-thread read, as above)
                while len(self.batcher.queue) < free:
                    p = self.overload.pop()
                    if p is None:
                        break
                    if p.received_at is not None and p.priority != CANARY:
                        # Canary waits are excluded: queue-wait p90 is
                        # a brownout-ladder pressure signal, and the
                        # probes must never trigger the ladder.
                        self.overload.observe_queue_wait(
                            (time.monotonic() - p.received_at) * 1000.0
                        )
                    try:
                        self._submit(p)
                    except (ValueError, TypeError, KeyError) as e:
                        # Malformed payloads must never kill the
                        # device-owning thread.  Deliberately NOT
                        # SLO-scored: a 400 is the client's defect,
                        # and letting bad payloads drag attainment
                        # would let one misconfigured client page
                        # the on-call for a healthy server.
                        p.fail(str(e), 400)
                self._reap()
                if not self.batcher.pending():
                    continue
                try:
                    events = self.batcher.step()
                except Exception as e:
                    # A step/insert dispatch died (device error, injected
                    # fault, allocation failure).  Rebuild + replay —
                    # onto a fallback path when the failure quarantined
                    # a feature; past the retry budget, re-raise into
                    # the hard drain.
                    if self._recover(e):
                        continue
                    raise
                # Probe-success recording runs ONE STEP BEHIND: jax
                # dispatch is async, so step N's device work is only
                # proven good once step N+1's host sync (the emit scan's
                # np.asarray) returns without raising.  Crediting step N
                # immediately would flip a probing feature healthy while
                # its re-enabled kernel is still in flight — a deferred
                # device error would then land on the HEALTHY state and
                # burn crash-recovery budget instead of re-quarantining.
                for f in self._pending_success:
                    self.degrade.record_success(f)
                self._pending_success = tuple(
                    getattr(self.batcher, "last_step_features", ())
                )
                # Non-finite guard: fail just the poisoned requests (the
                # batcher already freed their slots and blocks).
                for rid, msg in self.batcher.pop_failed():
                    p = self._active.pop(rid, None)
                    if p is not None:
                        self.nonfinite_failed_total += 1
                        self._slo_finalize(p, completed=False)
                        self._log(
                            "request_failed", msg,
                            request_id=p.ext_id, rid=rid,
                        )
                        p.fail(msg, 500)
                now = time.monotonic()
                for ev in events:
                    rid, tok, done = ev[0], ev[1], ev[2]
                    lp = ev[3] if len(ev) > 3 else None
                    p = self._active.get(rid)
                    if p is None:
                        continue
                    p.tokens.append(tok)
                    # Canary probes keep their per-request stamps (the
                    # router reads its own probe latency) but never
                    # feed the shared histograms/EWMAs — a stream of
                    # tiny fast probes would skew the very latency
                    # signals they exist to watch.
                    canary = p.priority == CANARY
                    if len(p.tokens) == 1:
                        if p.submitted_at is not None:
                            ttft_ms = (now - p.submitted_at) * 1000.0
                            p.ttft_ms = ttft_ms
                            if not canary:
                                self.obs.observe_ttft(ttft_ms)
                                self.ttft_ms_ewma = (
                                    ttft_ms if self.ttft_ms_ewma is None
                                    else 0.8 * self.ttft_ms_ewma
                                    + 0.2 * ttft_ms
                                )
                    elif p.last_tok_t is not None:
                        # Tokens inside one fused chunk arrive together
                        # (gap ~0); the chunk-period gap lands on the
                        # chunk's first token.  Both are real client-
                        # observed inter-token latencies.
                        itl_ms = (now - p.last_tok_t) * 1000.0
                        if not canary:
                            self.obs.observe_itl(itl_ms)
                            self.itl_ms_ewma = (
                                itl_ms if self.itl_ms_ewma is None
                                else 0.8 * self.itl_ms_ewma
                                + 0.2 * itl_ms
                            )
                        if p.itl_max_ms is None or itl_ms > p.itl_max_ms:
                            p.itl_max_ms = itl_ms
                    p.last_tok_t = now
                    if p.want_lp and lp is not None:
                        p.lps.append(lp)
                    if p.stream:
                        p.chunks.put((tok, lp if p.want_lp else None))
                    if done:
                        del self._active[rid]
                        self._slo_finalize(p, completed=True)
                        p.finish()
        except Exception as e:  # device/runtime failure: fail loudly
            reason = f"serving loop crashed: {e!r}"
            raise
        finally:
            self._closed.set()
            for p in list(self._active.values()):
                self._slo_finalize(p, completed=False)
                p.fail(reason, code)
            self._active.clear()
            # Pre-admission entries in the controller's class queues
            # must drain too — a shed-proof client is one that never
            # hangs, whatever queue it was waiting in.
            for p in self.overload.drain_all():
                p.fail(reason, code)
            while not self._inbox.empty():
                p = self._inbox.get_nowait()
                p.fail(reason, code)
            # Pending control calls fail too (their callers' own
            # timeouts bound them anyway, but an immediate error beats
            # a silent timeout).
            while True:
                try:
                    call = self._control.get_nowait()
                except queue.Empty:
                    break
                call.error = RuntimeError(reason)
                call.done.set()

    # -- flight recorder / decision audit (GET /debug/bundle, /debug/decisions)

    def _flight_snapshot(self) -> Dict[str, Any]:
        """One compact flight-recorder metric snapshot (loop thread —
        the batcher's owner): the handful of scalars whose trend a
        postmortem actually reads, not the full exposition (the ring
        holds ~100 of these)."""
        st = self.batcher.stats()
        om = self.obs.metrics()
        return {
            "emitted_tokens_total": st["emitted_tokens_total"],
            "active_slots": st["active_slots"],
            "queued_requests": st["queued_requests"],
            "free_blocks": st["free_blocks"],
            "host_syncs_total": st["host_syncs_total"],
            "decode_dispatches_total": st["decode_dispatches_total"],
            "swap_queue_depth": st["swap_queue_depth"],
            "prefill_tokens_inflight": st["prefill_tokens_inflight"],
            "requests_finished_total": om["requests_finished_total"],
            "requests_failed_total": om["requests_failed_total"],
            "goodput_tokens_total": om["goodput_tokens_total"],
            "slo_attainment": om["slo_attainment"],
            "overload_rung": self.overload.rung,
            "queued_preadmission": self.overload.queued_total(),
            "recoveries_total": self.recoveries_total,
            "canary_requests_total": self.canary_requests_total,
            "draining": self._draining.is_set(),
        }

    def _config_snapshot(self) -> Dict[str, Any]:
        """The bundle's ``config`` section: ctor-stable server knobs +
        the batcher geometry (``ContinuousBatcher.describe``)."""
        return {
            "batcher": self.batcher.describe(),
            "replica_id": self.replica_id,
            "max_queue": self.max_queue,
            "max_body_bytes": self.max_body_bytes,
            "max_recoveries": self.max_recoveries,
            "recovery_window_s": self.recovery_window_s,
            "drain_timeout_s": self.drain_timeout_s,
            "watchdog_deadline_s": self.watchdog_deadline_s,
            "flight_interval_s": self.flight_interval_s,
            "slo_ttft_ms": self.obs.slo_ttft_ms,
            "slo_itl_ms": self.obs.slo_itl_ms,
        }

    def bundle_json(self, trace: bool = True) -> Dict[str, Any]:
        """``GET /debug/bundle[?trace=0]`` — the black-box flight
        recorder's one-shot postmortem artifact: config + current
        health/metrics + the metric-snapshot trend ring + the last-N
        control-plane decisions + the annotation (state-transition)
        ring + the structured-log tail + the request index + the
        Perfetto trace.  Pure host-side snapshot assembly on the
        handler thread; the serving loop is never touched beyond the
        same racy-read surfaces /metrics and /healthz already read."""
        obs = self.obs
        out: Dict[str, Any] = {
            "kind": "replica_bundle",
            "generated_unix_s": round(time.time(), 3),
            "replica_id": self.replica_id,
            "config": self._config_snapshot(),
            "health": self._health(),
            "metrics": self._metrics_scalars(),
            "metric_snapshots": obs.metric_snapshots_json(),
            "decisions": obs.decisions.json(n=256),
            "annotations": obs.events_json(),
            "log_tail": self.logger.tail(),
            "requests": obs.requests_json(64),
        }
        if trace:
            out["trace"] = obs.trace_json()
        return out

    # -- metrics ------------------------------------------------------------

    def _metrics_scalars(self) -> Dict[str, Any]:
        """Every scalar the /metrics exposition renders (batcher +
        degrade + obs + overload + server-level), as one dict — shared
        by ``_metrics_text`` and the /debug/bundle artifact."""
        stats = dict(self.batcher.stats())
        stats.update(self.degrade.stats())
        stats.update(self.obs.metrics())
        stats.update(self.overload.stats())
        stats.update({
            # Server-level fault tolerance (batcher counters above carry
            # the injection-site totals when an injector is attached).
            "server_recoveries_total": self.recoveries_total,
            "watchdog_stalls_total": self.watchdog_stalls_total,
            "watchdog_stalled": int(self._stalled),
            "watchdog_last_step_age_seconds": round(
                time.monotonic() - self._heartbeat, 3
            ),
            # Degradation / drain / non-finite-guard state.
            "quarantine_rebuilds_total": self.quarantine_rebuilds_total,
            "probe_rebuilds_total": self.probe_rebuilds_total,
            "nonfinite_requests_failed_total": self.nonfinite_failed_total,
            "draining": int(self._draining.is_set()),
            "ttft_ms_ewma": (
                round(self.ttft_ms_ewma, 3)
                if self.ttft_ms_ewma is not None else 0.0
            ),
            "itl_ms_ewma": (
                round(self.itl_ms_ewma, 3)
                if self.itl_ms_ewma is not None else 0.0
            ),
            # Control-plane observability: synthetic canary probes
            # served (the reserved class the router sends).
            "canary_requests_total": self.canary_requests_total,
            # Scale-out serving: which replica this is (-1 standalone);
            # the serve_mesh_* shape gauges ride batcher.stats().
            "replica_id": (
                self.replica_id if self.replica_id is not None else -1
            ),
        })
        return stats

    def _metrics_text(self) -> str:
        stats = self._metrics_scalars()
        lines = []
        for k, v in stats.items():
            name = f"llm_{k}"
            meta = metric_meta(k)
            if meta is None:
                # Legacy fallback for a scalar nobody registered: the
                # old "_total names a counter" convention, with a HELP
                # line that SAYS the registration is missing — the
                # /metrics parse test (tests/test_server.py) fails on
                # it, so an unregistered metric cannot ship silently.
                kind = "gauge" if "total" not in k else "counter"
                help_text = "UNREGISTERED metric (add to obs.METRICS)"
            else:
                kind, help_text = meta
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {v}")
        # Histogram families (ttft/itl/queue-wait/prefill/swap/dispatch)
        # render their own HELP/TYPE + _bucket/_sum/_count series.
        lines.extend(self.obs.expose_histograms("llm_"))
        # Labeled families: per-kind device-time attribution gauges and
        # per-program compile counters (obs.utilization_metrics), plus
        # the live jit-cache entry count per registered serving program
        # (scrape-time reads of jax's own per-function caches — no
        # shared mutable state).  One HELP/TYPE header per family, even
        # while a family has no samples yet, so dashboards can discover
        # them before traffic.
        labeled = list(self.obs.utilization_metrics())
        for prog, n in sorted(serving_mod.jit_cache_entries().items()):
            labeled.append(("jit_cache_entries", {"program": prog}, n))
        for family in ("mxu_utilization", "hbm_utilization",
                       "host_overhead_ratio", "program_compiles_total",
                       "jit_cache_entries"):
            kind, help_text = metric_meta(family)
            lines.append(f"# HELP llm_{family} {help_text}")
            lines.append(f"# TYPE llm_{family} {kind}")
            for fam, labels, v in labeled:
                if fam != family:
                    continue
                lab = ",".join(
                    f'{k}="{val}"' for k, val in sorted(labels.items())
                )
                lines.append(f"llm_{family}{{{lab}}} {v}")
        return "\n".join(lines) + "\n"
