"""Deterministic fault injection for the serving stack.

The serving loop hangs everything off one device-owning thread: an
exception out of a jitted dispatch (``ContinuousBatcher.step`` /
``_paged_insert`` / ``_paged_suffix_insert``) or a block allocation kills
the loop.  This module makes those failure paths *testable and
rehearsable*: a seeded :class:`FaultInjector` with named injection sites
wraps the batcher's dispatch points and can raise device-style errors,
fail allocations, or add latency — at a chosen call index or with a
seeded per-call probability — so both the test suite and manual chaos
runs (``run.py --inject-faults`` / ``JLT_FAULTS``) exercise crash
recovery, the retry budget, and the step watchdog deterministically.

Sites (fired by ``ContinuousBatcher`` just before the real operation):

  ``step``           a decode/speculative step dispatch.  Chunked
                     dispatches (``decode_chunk`` / ``spec_rounds``
                     > 1) fire ONCE per fused chunk — the K decode
                     iterations or R speculative rounds inside one
                     jitted program are a single dispatch, so ``@N``
                     indices count chunks, not tokens or rounds
  ``insert``         a batched full-prompt prefill (``_paged_insert``)
  ``suffix_insert``  a prefix-cache-hit suffix prefill
  ``prefill_chunk``  a chunk dispatch CARRYING a fused prefill lane
                     (``_fused_chunk``: fused prefill-decode
                     scheduling, ``prefill_budget`` > 0) — the ``step``
                     site fires for the same dispatch first; this one
                     indexes prefill-carrying dispatches only, so
                     ``@N`` deterministically lands a fault mid-prefill
                     of an admission regardless of how many plain
                     decode chunks ran before it
  ``alloc``          a block-pool allocation (``_alloc_blocks``)
  ``kv_swap``        a host-tier swap-in begin (``_begin_restore``:
                     radix prefix index + host-DRAM block tier,
                     ``host_kv_blocks`` > 0).  UNLIKE the other error
                     sites, an injected fault here is CONTAINED by the
                     batcher: it fails only the restoring request
                     (clean per-request error via ``pop_failed`` ->
                     HTTP 500, claims released, host slabs unpinned) —
                     the server stays healthy and never burns crash-
                     recovery budget on it
  ``flash_kernel``   a dispatch whose prefill runs the Pallas flash
                     kernel (fired by the batcher per dispatch, AND by
                     ``ops.flash_attention`` at trace time when a hook
                     is installed — the batcher fire precedes the trace
                     fire, and cached executables re-fire only the
                     batcher-side site)
  ``paged_kernel``   a decode step on the Pallas paged-attention kernel
                     path (same batcher-then-trace fire order)
  ``spec_decode``    a speculative draft+verify dispatch — one round
                     classically, one fused R-round chunk under
                     ``spec_rounds`` > 1 (also fired by
                     ``spec_decode.generate_speculative`` at trace time
                     when a hook is installed)

The three kernel/spec sites carry their site name on the raised
exception (``InjectedFault.site``), which is what lets the server's
degradation layer (``degrade.py``) attribute the failure to a feature
and quarantine it onto its fallback path instead of burning the crash-
recovery budget.

Spec grammar (comma-separated, used by the CLI flag and ``JLT_FAULTS``)::

    site@N:kind[=value]     fire when the site's call counter == N
    site~P:kind[=value]     fire each call with probability P (seeded)

kinds: ``error`` (raise :class:`InjectedFault`, a device-style runtime
error), ``oom`` (raise :class:`InjectedOOM`, an allocation failure),
``delay=SECONDS`` (sleep, then proceed — the watchdog's test lever), and
``nan`` (arm a non-finite poison: the next guarded dispatch reports its
first active row's logits as non-finite — the test lever for the
serving layer's non-finite guard; no exception is raised).

Examples::

    step@5:error                 kill the 6th decode dispatch
    insert@0:error,alloc@3:oom   first prefill + 4th allocation
    step~0.01:error              1% of steps, deterministic per seed
    step@2:delay=1.5             stall one step by 1.5 s
    paged_kernel@0:error         kill the first kernel-path decode step
    stock_paged_kernel@0:error   kill the first stock-kernel decode step
                                 (quarantine falls back to the custom
                                 paged kernel, not to XLA)
    step@3:nan                   poison one row's logits on step 3
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Dict, List, Optional, Sequence, Union

SITES = (
    "step", "insert", "suffix_insert", "prefill_chunk", "alloc",
    # Kernel sites fire once per dispatch that runs the named kernel
    # family.  ``flash_kernel`` covers the CUSTOM flash kernel
    # (ops/flash_attention.py) on insert/chunked-prefill dispatches;
    # ``paged_kernel`` covers the CUSTOM block-table decode kernel
    # (ops/paged_attention.py).  The two new ops/kernels.py entries get
    # their own sites below so a fault (or a real Mosaic error)
    # attributes to the kernel actually selected: ``splash_kernel``
    # (upstream splash-mha serving splash-eligible insert chunks;
    # flash_kernel still fires on those dispatches for the non-eligible
    # remainder) and ``stock_paged_kernel`` (upstream Pallas
    # paged-attention serving T=1 non-int8 decode steps; paged_kernel
    # still fires for the fused/verify halves it keeps).
    "kv_swap", "flash_kernel", "paged_kernel", "splash_kernel",
    "stock_paged_kernel", "spec_decode",
    # Router-side site (router.ReplicaRouter.forward): an injected
    # fault here simulates the chosen replica dying at dispatch time —
    # the router marks it unhealthy and re-routes the request to a
    # surviving replica (CONTAINED: requests that have not streamed a
    # byte re-route losslessly; in-flight requests on a genuinely
    # crashed replica replay through that replica's own crash-recovery
    # path).
    "router_replica",
    # Controller-side sites (router.FleetController).  ``session_migrate``
    # fires once per live session at the start of its drain migration —
    # an injected fault aborts THAT session's move only: the source copy
    # is untouched (export never demotes before destination residency is
    # proven), the session keeps serving from the source, and the drain
    # reports the failure instead of dropping anyone.  ``scale_event``
    # fires at the start of each scale-up / scale-down / rollout-rung
    # action — an injected fault aborts the whole action cleanly (fleet
    # membership unchanged, decision record explains the abort).
    "session_migrate",
    "scale_event",
)
KINDS = ("error", "oom", "delay", "nan")


class InjectedFault(RuntimeError):
    """A deliberately injected device-style failure (INTERNAL).

    ``site`` names the injection site that raised — the degradation
    layer's attribution key (real device errors carry no site and are
    attributed from the batcher's last-dispatch record instead)."""

    def __init__(self, message: str, site: Optional[str] = None):
        super().__init__(message)
        self.site = site


class InjectedOOM(InjectedFault):
    """A deliberately injected allocation failure (RESOURCE_EXHAUSTED)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injection rule: fire ``kind`` at ``site`` when the site's call
    counter equals ``at``, or (``at`` is None) with probability ``p`` per
    call drawn from the injector's seeded RNG."""

    site: str
    kind: str
    at: Optional[int] = None
    p: float = 0.0
    delay_s: float = 0.0

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; have {SITES}"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; have {KINDS}"
            )
        if self.at is None and not (0.0 < self.p <= 1.0):
            raise ValueError(
                "a FaultSpec needs an index (site@N) or a probability "
                "in (0, 1] (site~P)"
            )

    @classmethod
    def parse(cls, text: str) -> List["FaultSpec"]:
        """Parse the comma-separated CLI/env grammar (module docstring)."""
        specs: List[FaultSpec] = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            head, sep, kind = part.partition(":")
            if not sep:
                raise ValueError(
                    f"bad fault spec {part!r}: expected site[@N|~P]:kind"
                )
            kind, _, value = kind.partition("=")
            kind = kind.strip()
            at: Optional[int] = None
            p = 0.0
            if "@" in head:
                site, _, idx = head.partition("@")
                at = int(idx)
            elif "~" in head:
                site, _, prob = head.partition("~")
                p = float(prob)
            else:
                site, at = head, 0
            delay_s = 0.0
            if kind == "delay":
                if not value:
                    raise ValueError(
                        f"bad fault spec {part!r}: delay needs =SECONDS"
                    )
                delay_s = float(value)
            elif value:
                raise ValueError(
                    f"bad fault spec {part!r}: {kind} takes no =value"
                )
            specs.append(cls(
                site=site.strip(), kind=kind, at=at, p=p, delay_s=delay_s
            ))
        return specs


# ---------------------------------------------------------------------------
# Trace-time hook registry
#
# The kernel/spec modules (ops.flash_attention, ops.paged_attention,
# ops.kernels — splash_kernel / stock_paged_kernel — and spec_decode)
# call ``fire_trace(<site>)`` at their entry points' TRACE
# time — the moment a Mosaic compile failure would surface on real
# hardware.  One registry arms or clears every site at once
# (run.py --inject-faults installs ``injector.fire`` here and clears it
# on exit); cached executables do not re-trace, so per-dispatch
# injection is the batcher-side site of the same name.  faults.py
# imports nothing from the package, so the kernel modules can import
# this without cycles.
# ---------------------------------------------------------------------------

_trace_hook = None


def install_trace_hook(hook) -> None:
    """Install (or clear, with None) the trace-time fault hook — called
    as ``hook(site)`` from the kernel/spec module entry points."""
    global _trace_hook
    _trace_hook = hook


def fire_trace(site: str) -> None:
    """Hook point for the kernel/spec modules (no-op when unarmed)."""
    if _trace_hook is not None:
        _trace_hook(site)


class FaultInjector:
    """Seeded, counting fault injector shared by a batcher's sites.

    ``fire(site)`` increments the site's call counter, checks every spec
    for that site, and either returns (no match), sleeps (``delay``), or
    raises (``error``/``oom``).  Counters survive a batcher rebuild (the
    recovery path hands the same injector to the fresh batcher), so
    ``step@N`` indexes the N-th dispatch of the *process*, not of one
    batcher incarnation — which is what makes "kill step 5, recover,
    don't kill step 6" expressible.
    """

    def __init__(
        self,
        specs: Union[str, Sequence[FaultSpec], None] = None,
        seed: int = 0,
    ):
        if isinstance(specs, str):
            specs = FaultSpec.parse(specs)
        self.specs: List[FaultSpec] = list(specs or [])
        self._rng = random.Random(seed)
        self.calls: Dict[str, int] = {s: 0 for s in SITES}
        self.injected: Dict[str, int] = {s: 0 for s in SITES}
        self.injected_total = 0
        self.delays_total = 0
        self.nans_armed_total = 0
        self._nan_armed = False
        # Observability sink (obs.Observability.annotate — the batcher
        # wires it when it adopts the injector): every injection /
        # armed poison / delay lands as an instant event in the serving
        # trace, so a chaos drill's fault is explainable next to the
        # dispatch spans it killed.
        self.trace_sink = None

    def _trace(self, site: str, kind: str, call: int) -> None:
        if self.trace_sink is not None:
            self.trace_sink(
                "fault_injected", site=site, kind=kind, call=call
            )

    def fire(self, site: str) -> None:
        """Hook point: called by the batcher just before the real op."""
        n = self.calls.get(site, 0)
        self.calls[site] = n + 1
        for spec in self.specs:
            if spec.site != site:
                continue
            if spec.at is not None:
                hit = spec.at == n
            else:
                hit = self._rng.random() < spec.p
            if not hit:
                continue
            if spec.kind == "delay":
                self.delays_total += 1
                self._trace(site, "delay", n)
                time.sleep(spec.delay_s)
                continue
            if spec.kind == "nan":
                # Arm a non-finite poison instead of raising: the next
                # guarded dispatch (ContinuousBatcher consumes via
                # ``take_nan``) reports its first active row's logits as
                # non-finite — exercising the serving non-finite guard
                # end-to-end without needing the model to emit NaN.
                self.nans_armed_total += 1
                self._nan_armed = True
                self._trace(site, "nan", n)
                continue
            self.injected[site] = self.injected.get(site, 0) + 1
            self.injected_total += 1
            self._trace(site, spec.kind, n)
            if spec.kind == "oom":
                raise InjectedOOM(
                    f"RESOURCE_EXHAUSTED: injected allocation failure "
                    f"({site} call #{n})", site=site,
                )
            raise InjectedFault(
                f"INTERNAL: injected device error ({site} call #{n})",
                site=site,
            )

    def take_nan(self) -> bool:
        """Consume an armed ``nan`` poison (one dispatch at most)."""
        armed, self._nan_armed = self._nan_armed, False
        return armed

    def stats(self) -> Dict[str, float]:
        """Counters for the HTTP /metrics endpoint."""
        out: Dict[str, float] = {
            "faults_injected_total": self.injected_total,
            "fault_delays_total": self.delays_total,
            "fault_nans_armed_total": self.nans_armed_total,
        }
        for site in SITES:
            out[f"faults_injected_{site}_total"] = self.injected.get(
                site, 0
            )
        return out
