"""Overload control: deadline-aware admission, priority shedding, and
an SLO-driven brownout ladder.

The serving stack's only overload defense used to be a static FIFO
depth count (``LLMServer.max_queue`` -> bare 503): a 32k-token prompt
and a 16-token ping cost the same admission slot, and nothing reacted
when the SLO attainment gauges (obs.py) cratered under load.  This
module is the controller half of ROADMAP item 5 — the sensors (TTFT /
ITL / queue-wait histograms, windowed attainment, goodput) landed in
PR 7; this reads them and turns the knobs the stack already exposes.

Three pieces, one :class:`OverloadController` (owned by ``LLMServer``,
surviving batcher rebuilds the way ``DegradeManager`` does):

  * **Deadline- and cost-aware admission with priority classes.**
    POST payloads carry an optional ``"priority"`` ("interactive" |
    "batch"; junk is a 400).  The controller keeps per-class queues
    with strict interactive-first ordering (FIFO within a class), and
    admission is cost-based: EWMAs of observed prefill/decode
    throughput — fed from the dispatch records the obs ring already
    captures, zero new device work — convert prompt length + queue
    backlog into a conservative TTFT estimate (queueing + own prefill
    alone, a LOWER bound on the real TTFT), and a request whose
    ``timeout_s`` deadline provably cannot be met even by that lower
    bound is refused immediately with 503 + a load-derived
    ``Retry-After`` instead of queuing to die in the reaper.  With no
    throughput evidence yet (cold server) everything is admitted — a
    refusal must be provable, never guessed.

  * **Brownout ladder** — deliberately distinct from ``degrade.py``'s
    failure-driven quarantine: that reacts to *crashes*, this reacts
    to *load*.  A hysteresis state machine::

        normal -> elevated -> brownout-1 -> brownout-2 -> shed

    driven by the windowed interactive-class SLO attainment and recent
    queue-wait samples.  Escalation requires the pressure to persist
    for ``dwell_s``; recovery steps DOWN one rung at a time after
    ``cooldown_s`` of calm (attainment back above the — higher —
    ``exit_attainment`` bar, or no recent traffic), the
    quarantine->probing pattern applied to load.  Each rung turns
    knobs the stack already has (the server applies them; the
    controller, like ``DegradeManager``, is pure bookkeeping and
    never touches the batcher):

      ==========  ======================================================
      rung        action (cumulative down the ladder)
      ==========  ======================================================
      normal      baseline knobs
      elevated    shrink ``prefill_budget`` to half (protect ITL:
                  smaller prefill slices per decode chunk)
      brownout-1  + cap batch-class ``max_new_tokens``; proactively
                  ``demote_idle()`` the KV host tier to free HBM
      brownout-2  + refuse NEW batch-class admissions (503 +
                  Retry-After); prefill budget to a quarter
      shed        + shed already-QUEUED batch-class entries (clean 503
                  + Retry-After — never a hang); interactive keeps
                  serving
      ==========  ======================================================

    Every transition is a structured-log line, an obs annotation, and
    a ``/metrics`` gauge + ``/healthz`` section (wired in server.py).

  * **Open-loop load harness** (:func:`poisson_schedule`,
    :func:`open_loop_flood`, :func:`summarize_flood`).  A Poisson-
    arrival generator that fires requests at their scheduled times
    REGARDLESS of completions (open-loop — the arrival process does
    not slow down when the server does, which is exactly what makes
    overload visible; a closed-loop client self-throttles and hides
    it).  ``bench.py`` sweeps it over request rate for the
    ``serving_goodput_vs_rate`` record; ``tests/test_overload.py``
    uses it for the flood drill (every refused/shed request gets a
    well-formed 503 + Retry-After, zero hung clients).

Thread-safety: handler threads call ``admit()`` while the serving loop
pushes/pops/ticks, so every method takes the one internal ``_lock``
(registered with the lock-discipline checker,
``analysis/lockcheck.py``).  Shed/deadline refusals are deliberate
load decisions and are NOT SLO-scored — counting them as latency
misses would wedge the ladder at its top rung (the misses it sheds to
avoid would keep it escalated forever).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

PRIORITIES = ("interactive", "batch")

# Reserved request class for the router's synthetic canary probes
# (router.py).  NOT a member of PRIORITIES on purpose: canaries ride
# the interactive queue for ordering (``_priority_of`` maps unknown
# classes there), but the server excludes the class from SLO
# attainment, goodput, the latency histograms/EWMAs and the brownout
# ladder's signal windows — a fleet whose only traffic is its own
# probes must read healthy and must never brown itself out.
CANARY = "canary"

# Ladder rungs, mildest first.  RUNG_INDEX is the /metrics gauge value.
RUNGS = ("normal", "elevated", "brownout-1", "brownout-2", "shed")
RUNG_INDEX = {name: i for i, name in enumerate(RUNGS)}


@dataclasses.dataclass(frozen=True)
class Refusal:
    """An admission refusal (always HTTP 503 — the request may succeed
    on retry or elsewhere; 4xx is reserved for defective payloads)."""

    reason: str
    retry_after_s: int
    kind: str  # "backlog" | "deadline" | "class"


@dataclasses.dataclass(frozen=True)
class RungKnobs:
    """The knob settings one ladder rung asks the server to apply.
    ``demote_blocks`` fires once on ENTERING the rung (an operational
    sweep, not a steady-state drain)."""

    prefill_budget_scale: float
    batch_max_new_cap: int      # 0 = uncapped
    admit_batch: bool           # False: new batch POSTs refused
    demote_blocks: int
    shed_batch: bool            # True: queued batch entries are shed


class OverloadController:
    """Load-driven admission + brownout state machine (module docstring).

    Queue entries are duck-typed: anything with ``priority``,
    ``cost_tokens``, ``deadline`` (absolute monotonic or None) and
    ``disconnected`` attributes (the server's ``_Pending``; tests use
    stubs).  ``clock`` is injectable so ladder transitions are
    unit-testable without sleeping.
    """

    def __init__(
        self,
        enabled: bool = True,
        max_queue: int = 256,
        enter_attainment: float = 0.85,
        exit_attainment: float = 0.95,
        queue_wait_ms: Optional[float] = None,
        slo_ttft_ms: Optional[float] = None,
        dwell_s: float = 2.0,
        cooldown_s: float = 10.0,
        signal_window_s: float = 10.0,
        min_signal_samples: int = 4,
        batch_max_new: int = 64,
        demote_blocks: int = 32,
        ewma_alpha: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 < enter_attainment <= exit_attainment <= 1.0:
            raise ValueError(
                "need 0 < enter_attainment <= exit_attainment <= 1 "
                f"(hysteresis), got {enter_attainment}/{exit_attainment}"
            )
        self.enabled = bool(enabled)
        self.max_queue = int(max_queue)
        self.enter_attainment = float(enter_attainment)
        self.exit_attainment = float(exit_attainment)
        # Queue-wait pressure bar: explicit, or derived from the TTFT
        # SLO (a wait already 2x the whole TTFT budget is pressure by
        # definition), else a 2 s default.
        if queue_wait_ms is None:
            queue_wait_ms = 2.0 * slo_ttft_ms if slo_ttft_ms else 2000.0
        self.queue_wait_ms = float(queue_wait_ms)
        self.dwell_s = float(dwell_s)
        self.cooldown_s = float(cooldown_s)
        self.signal_window_s = float(signal_window_s)
        self.min_signal_samples = int(min_signal_samples)
        self.ewma_alpha = float(ewma_alpha)
        self._clock = clock
        # Rung -> knobs (module-docstring table).  batch_max_new halves
        # per rung past brownout-1; floors at 1 so a tiny cap still
        # yields a reply instead of a zero-token 200.
        cap = max(1, int(batch_max_new))
        demote = max(0, int(demote_blocks))
        self._ladder: Dict[str, RungKnobs] = {
            "normal": RungKnobs(1.0, 0, True, 0, False),
            "elevated": RungKnobs(0.5, 0, True, 0, False),
            "brownout-1": RungKnobs(0.5, cap, True, demote, False),
            "brownout-2": RungKnobs(0.25, max(1, cap // 2), False,
                                    demote, False),
            "shed": RungKnobs(0.25, max(1, cap // 4), False, demote,
                              True),
        }
        self._lock = threading.Lock()
        # Per-class FIFO queues (strict interactive-first pop) and the
        # backlog token sums the TTFT estimator reads.
        self._queues: Dict[str, Deque[Any]] = {
            p: deque() for p in PRIORITIES
        }
        self._queued_tokens: Dict[str, int] = {p: 0 for p in PRIORITIES}
        # Tokens of requests ADMITTED but not yet drained from the
        # server inbox into the class queues (admit() increments,
        # push() releases).  Without this, a burst landing during one
        # long dispatch would be invisible to the deadline estimator —
        # every request would see a near-empty backlog and then die in
        # the reaper, the exact outcome the refusal exists to prevent.
        self._inflight_tokens: Dict[str, int] = {
            p: 0 for p in PRIORITIES
        }
        # Throughput EWMAs (tokens/s), fed from obs dispatch records
        # (on_dispatch); None until the first sample — no evidence, no
        # deadline refusals.
        self._prefill_tps: Optional[float] = None
        self._decode_tps: Optional[float] = None
        # Ladder state + timers.
        self._rung = 0
        self._rung_since = clock()
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        # Signal windows: per-class (t, ttft_ok, itl_ok, ok) SLO scores
        # and recent queue-wait samples (t, ms).  Only entries younger
        # than signal_window_s count — a flood's misses age out, which
        # is what lets the ladder step back down.
        self._slo_windows: Dict[str, Deque[Tuple[float, bool, bool, bool]]] = {
            p: deque(maxlen=256) for p in PRIORITIES
        }
        self._wait_window: Deque[Tuple[float, float]] = deque(maxlen=256)
        # Counters / gauges for /metrics and /healthz.
        self.transitions_total = 0
        self.sheds_total = 0
        self.refused_backlog_total = 0
        self.refused_deadline_total = 0
        self.refused_batch_total = 0
        self.ttft_estimate_last_ms = 0.0

    # -- sensors ------------------------------------------------------------

    def on_dispatch(self, rec: Dict[str, Any]) -> None:
        """Feed one obs dispatch record (obs.Observability calls this
        outside its own lock).  Prefill throughput comes from any
        dispatch that advanced prompt tokens (fused chunks, classic
        inserts, suffix inserts); decode throughput from the chunk
        kinds, approximated as k iterations x occupancy rows per
        dispatch wall — coarse, but it only feeds Retry-After and the
        conservative TTFT lower bound, not anything token-exact."""
        wall_s = float(rec.get("wall_ms", 0.0)) / 1000.0
        if wall_s <= 0.0:
            return
        pf_tokens = int(rec.get("prefill_tokens", 0))
        kind = rec.get("kind")
        a = self.ewma_alpha
        with self._lock:
            if pf_tokens > 0:
                sample = pf_tokens / wall_s
                self._prefill_tps = (
                    sample if self._prefill_tps is None
                    else (1 - a) * self._prefill_tps + a * sample
                )
            if kind in ("decode", "fused", "spec"):
                toks = int(rec.get("k", 1)) * max(
                    1, int(rec.get("occupancy", 1))
                )
                sample = toks / wall_s
                self._decode_tps = (
                    sample if self._decode_tps is None
                    else (1 - a) * self._decode_tps + a * sample
                )

    def note_slo(self, priority: str, ttft_ok: bool, itl_ok: bool,
                 ok: bool) -> None:
        """One finished request's SLO score (the server's
        ``_slo_finalize`` feeds this next to ``obs.slo_account``).
        The ladder reads the INTERACTIVE window — the protected class;
        the batch window only feeds the per-class attainment gauges."""
        if priority not in PRIORITIES:
            priority = "interactive"
        with self._lock:
            self._slo_windows[priority].append(
                (self._clock(), ttft_ok, itl_ok, ok)
            )

    def observe_queue_wait(self, ms: float) -> None:
        """One request's POST-arrival -> batcher-submit wait."""
        with self._lock:
            self._wait_window.append((self._clock(), float(ms)))

    # -- queues -------------------------------------------------------------

    def _priority_of(self, entry: Any) -> str:
        """Queue an entry classifies into.  With the controller
        DISABLED everything lands in one queue in arrival order — a
        genuinely plain FIFO, so ``priority_classes=off`` (and the
        bench harness's static A/B arm) really is the pre-ladder
        behavior, not interactive-first scheduling in disguise."""
        if not self.enabled:
            return "interactive"
        p = getattr(entry, "priority", "interactive")
        return p if p in PRIORITIES else "interactive"

    @staticmethod
    def _cost_of(entry: Any) -> int:
        return max(0, int(getattr(entry, "cost_tokens", 0)))

    def push(self, entry: Any) -> None:
        with self._lock:
            p = self._priority_of(entry)
            cost = self._cost_of(entry)
            self._queues[p].append(entry)
            self._queued_tokens[p] += cost
            # Release the admit-time in-flight reservation (floored:
            # test stubs and direct pushes never went through admit).
            self._inflight_tokens[p] = max(
                0, self._inflight_tokens[p] - cost
            )

    def pop(self) -> Optional[Any]:
        """Next entry, strict interactive-first (FIFO within a class)."""
        with self._lock:
            for p in PRIORITIES:
                if self._queues[p]:
                    entry = self._queues[p].popleft()
                    self._queued_tokens[p] -= self._cost_of(entry)
                    return entry
            return None

    def queued_total(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def reap(self, now: Optional[float] = None
             ) -> Tuple[List[Any], List[Any]]:
        """Remove and return (expired, disconnected) queued entries —
        the pre-admission arm of the server's reaper (deadline and
        client-gone checks used to happen at inbox pop; entries can
        now wait in the class queues much longer)."""
        now = self._clock() if now is None else now
        expired: List[Any] = []
        gone: List[Any] = []
        with self._lock:
            for p, q in self._queues.items():
                keep: Deque[Any] = deque()
                for e in q:
                    if getattr(e, "disconnected", False):
                        gone.append(e)
                    elif (
                        getattr(e, "deadline", None) is not None
                        and now >= e.deadline
                    ):
                        expired.append(e)
                    else:
                        keep.append(e)
                        continue
                    self._queued_tokens[p] -= self._cost_of(e)
                self._queues[p] = keep
        return expired, gone

    def shed_batch(self) -> List[Any]:
        """At the ``shed`` rung: drain and return every queued
        batch-class entry (the server 503s each — clean, never a
        hang).  Empty at every other rung."""
        with self._lock:
            if not self._knobs_locked().shed_batch:
                return []
            out = list(self._queues["batch"])
            self._queues["batch"].clear()
            self._queued_tokens["batch"] = 0
            self.sheds_total += len(out)
            return out

    def drain_all(self) -> List[Any]:
        """Remove and return everything queued (server shutdown — the
        finally-drain must fail these, never strand a client)."""
        with self._lock:
            out: List[Any] = []
            for p in PRIORITIES:
                out.extend(self._queues[p])
                self._queues[p].clear()
                self._queued_tokens[p] = 0
            return out

    # -- admission ----------------------------------------------------------

    def admit(
        self,
        priority: str,
        cost_tokens: int,
        timeout_s: Optional[float],
        depth: int,
    ) -> Optional[Refusal]:
        """Admission check, called on HTTP handler threads BEFORE the
        request enqueues.  Returns None (admit) or a :class:`Refusal`.

        Order matters: the backlog bound is the hard backstop (handler
        threads and memory are finite regardless of class), then the
        ladder's class gate, then the deadline proof.  The TTFT
        estimate is a LOWER bound — backlog-ahead + own prefill at the
        observed EWMA rate, ignoring decode interference and slot
        waits — so a refusal is conservative: if even the lower bound
        misses the deadline, queuing could only add a reaper 504."""
        if priority not in PRIORITIES:  # the server validates; stubs
            priority = "interactive"    # and direct callers may not
        if depth >= self.max_queue:
            with self._lock:
                self.refused_backlog_total += 1
                retry = self._retry_after_locked()
            return Refusal(
                "server overloaded; retry later", retry, "backlog"
            )
        if not self.enabled:
            return None
        with self._lock:
            knobs = self._knobs_locked()
            if priority == "batch" and not knobs.admit_batch:
                self.refused_batch_total += 1
                return Refusal(
                    f"batch-class admissions suspended "
                    f"(overload rung {RUNGS[self._rung]}); retry later",
                    self._retry_after_locked(), "class",
                )
            if timeout_s is not None and self._prefill_tps:
                # Backlog ahead = class queues PLUS admitted requests
                # still in transit through the server inbox (the
                # in-flight reservation below) — a burst arriving
                # during one long dispatch must see its own footprint.
                ahead = (
                    self._queued_tokens["interactive"]
                    + self._inflight_tokens["interactive"]
                )
                if priority == "batch":
                    ahead += (
                        self._queued_tokens["batch"]
                        + self._inflight_tokens["batch"]
                    )
                est_s = (ahead + max(0, int(cost_tokens))) / self._prefill_tps
                self.ttft_estimate_last_ms = est_s * 1000.0
                if est_s > float(timeout_s):
                    self.refused_deadline_total += 1
                    return Refusal(
                        f"deadline unmeetable: estimated time to first "
                        f"token {est_s:.2f}s exceeds timeout_s "
                        f"{float(timeout_s):.2f}s at current load; "
                        f"retry later",
                        self._retry_after_locked(), "deadline",
                    )
            # Admitted: reserve the cost until the serving loop drains
            # the entry from the inbox into a class queue (push()).
            self._inflight_tokens[priority] += max(0, int(cost_tokens))
        return None

    def _retry_after_locked(self) -> int:
        """Load-derived Retry-After (seconds, >= 1, capped at 60):
        the time the observed prefill throughput needs to drain the
        current backlog — the queue drain rate, not a constant.  With
        no throughput evidence yet, scale coarsely with queue depth."""
        backlog = sum(self._queued_tokens.values()) + sum(
            self._inflight_tokens.values()
        )
        if self._prefill_tps:
            est = backlog / self._prefill_tps
        else:
            est = sum(len(q) for q in self._queues.values()) / 8.0
        return max(1, min(60, int(est) + 1))

    def retry_after_s(self) -> int:
        with self._lock:
            return self._retry_after_locked()

    # -- brownout ladder ----------------------------------------------------

    def _recent_locked(self, window: Sequence[Tuple], now: float) -> List[Tuple]:
        return [e for e in window if now - e[0] <= self.signal_window_s]

    def _signals_locked(self, now: float) -> Tuple[Optional[float], Optional[float]]:
        """(interactive attainment, queue-wait p90) over the recent
        window; None where there are too few samples to mean anything."""
        scores = self._recent_locked(self._slo_windows["interactive"], now)
        att = None
        if len(scores) >= self.min_signal_samples:
            att = sum(1 for e in scores if e[3]) / len(scores)
        waits = [w for _, w in self._recent_locked(self._wait_window, now)]
        p90 = None
        if len(waits) >= self.min_signal_samples:
            waits.sort()
            p90 = waits[min(len(waits) - 1, int(0.9 * len(waits)))]
        return att, p90

    def tick(self, now: Optional[float] = None
             ) -> Optional[Tuple[str, str]]:
        """Evaluate the ladder; returns ``(old_rung, new_rung)`` on a
        transition, else None.  Called by the serving loop every
        iteration (pure bookkeeping, no device work).

        Pressure: recent interactive attainment below
        ``enter_attainment``, or recent queue-wait p90 above
        ``queue_wait_ms``.  Escalation needs pressure to persist for
        ``dwell_s``.  Calm: no pressure AND attainment at/above
        ``exit_attainment`` (or no recent traffic — an idle server
        must walk back to normal); de-escalation needs calm for
        ``cooldown_s``.  One rung per transition in both directions,
        and the timers re-arm after each — no skipping straight to
        shed on one bad window, no snap-back flapping."""
        now = self._clock() if now is None else now
        if not self.enabled:
            return None
        with self._lock:
            att, wait_p90 = self._signals_locked(now)
            pressure = (
                (att is not None and att < self.enter_attainment)
                or (wait_p90 is not None and wait_p90 > self.queue_wait_ms)
            )
            calm = not pressure and (
                att is None or att >= self.exit_attainment
            )
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                if (
                    self._rung < len(RUNGS) - 1
                    and now - self._pressure_since >= self.dwell_s
                ):
                    old = RUNGS[self._rung]
                    self._rung += 1
                    self._rung_since = now
                    # Restart the dwell at the transition: sustained
                    # pressure climbs one rung per dwell_s, never two
                    # rungs in one tick.
                    self._pressure_since = now
                    self.transitions_total += 1
                    return old, RUNGS[self._rung]
            elif calm:
                self._pressure_since = None
                if self._calm_since is None:
                    self._calm_since = now
                if (
                    self._rung > 0
                    and now - self._calm_since >= self.cooldown_s
                ):
                    old = RUNGS[self._rung]
                    self._rung -= 1
                    self._rung_since = now
                    # Restart the cooldown at the transition: recovery
                    # steps one rung per cooldown_s of sustained calm.
                    self._calm_since = now
                    self.transitions_total += 1
                    return old, RUNGS[self._rung]
            else:
                # Hysteresis band: attainment between enter and exit —
                # neither escalate nor recover; both timers re-arm.
                self._pressure_since = None
                self._calm_since = None
        return None

    # audit: locked(every caller holds self._lock)
    def _knobs_locked(self) -> RungKnobs:
        return self._ladder[RUNGS[self._rung]]

    def knobs(self) -> RungKnobs:
        with self._lock:
            return self._knobs_locked()

    @property
    def rung(self) -> str:
        with self._lock:
            return RUNGS[self._rung]

    def force_rung(self, name: str) -> None:
        """Pin the ladder to a rung (tests/drills only — the ladder
        normally only moves through ``tick``)."""
        with self._lock:
            self._rung = RUNG_INDEX[name]
            self._rung_since = self._clock()
            self._pressure_since = None
            self._calm_since = None

    # -- exposition ---------------------------------------------------------

    def _attainment_locked(self, priority: str, now: float) -> float:
        scores = self._recent_locked(self._slo_windows[priority], now)
        if not scores:
            return 1.0
        return sum(1 for e in scores if e[3]) / len(scores)

    def stats(self) -> Dict[str, float]:
        """Scalar gauges/counters for /metrics (names registered in
        obs.METRICS)."""
        now = self._clock()
        with self._lock:
            knobs = self._knobs_locked()
            return {
                "overload_rung": self._rung,
                "overload_transitions_total": self.transitions_total,
                "overload_sheds_total": self.sheds_total,
                "overload_refused_backlog_total":
                    self.refused_backlog_total,
                "overload_refused_deadline_total":
                    self.refused_deadline_total,
                "overload_refused_batch_total": self.refused_batch_total,
                "queued_interactive": len(self._queues["interactive"]),
                "queued_batch": len(self._queues["batch"]),
                "prefill_tokens_per_s_ewma": round(
                    self._prefill_tps or 0.0, 2
                ),
                "decode_tokens_per_s_ewma": round(
                    self._decode_tps or 0.0, 2
                ),
                "overload_ttft_estimate_ms": round(
                    self.ttft_estimate_last_ms, 1
                ),
                "overload_batch_max_new_cap": knobs.batch_max_new_cap,
                "slo_interactive_attainment": round(
                    self._attainment_locked("interactive", now), 4
                ),
                "slo_batch_attainment": round(
                    self._attainment_locked("batch", now), 4
                ),
            }

    def health(self) -> Dict[str, Any]:
        """The /healthz ``overload`` section."""
        now = self._clock()
        with self._lock:
            _, wait_p90 = self._signals_locked(now)
            return {
                "enabled": self.enabled,
                "rung": RUNGS[self._rung],
                "rung_since_s": round(now - self._rung_since, 3),
                "queued": {
                    p: len(q) for p, q in self._queues.items()
                },
                "queued_tokens": dict(self._queued_tokens),
                "transitions_total": self.transitions_total,
                "sheds_total": self.sheds_total,
                "refused": {
                    "backlog": self.refused_backlog_total,
                    "deadline": self.refused_deadline_total,
                    "batch": self.refused_batch_total,
                },
                "prefill_tokens_per_s_ewma": round(
                    self._prefill_tps or 0.0, 2
                ),
                "interactive_attainment": round(
                    self._attainment_locked("interactive", now), 4
                ),
                # Recent queue-wait p90 (the ladder's second pressure
                # signal; None with too few recent samples) — the
                # router's health sentinel reads it off the scrape.
                "queue_wait_ms_p90": (
                    round(wait_p90, 3) if wait_p90 is not None else None
                ),
            }


# ---------------------------------------------------------------------------
# Open-loop load harness
# ---------------------------------------------------------------------------

def poisson_schedule(rate_hz: float, duration_s: float,
                     seed: int = 0) -> List[float]:
    """Arrival offsets (seconds) of a Poisson process at ``rate_hz``
    over ``duration_s`` — exponential inter-arrival gaps from a seeded
    PRNG, so a sweep is reproducible.  Open-loop by construction: the
    schedule exists before the first request fires and never reacts to
    the server."""
    import random

    if rate_hz <= 0.0:
        return []
    rng = random.Random(seed)
    out: List[float] = []
    t = rng.expovariate(rate_hz)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_hz)
    return out


def _fire_one(address: str, payload: Dict[str, Any], rec: Dict[str, Any],
              timeout_s: float) -> None:
    """One open-loop request (its own thread): POST streaming, record
    client-observed TTFT / worst ITL / token count / status / whether
    a refusal carried Retry-After.  ``rec["hung"]`` stays True until a
    terminal outcome is recorded — the flood drill's zero-hung-clients
    assertion reads it."""
    req = urllib.request.Request(
        address + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            first = last = None
            itl_max = 0.0
            ntok = 0
            timed_out = False
            stream_error = None
            for line in r:
                now = time.monotonic()
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "token" in obj:
                    if first is None:
                        first = now
                    elif last is not None:
                        itl_max = max(itl_max, (now - last) * 1000.0)
                    last = now
                    ntok += 1
                if obj.get("done"):
                    if obj.get("timeout"):
                        timed_out = True
                    # A mid-stream failure rides a 200 stream (the
                    # headers were sent with the first token) and
                    # surfaces only in the final line — it must not
                    # score as a served request.
                    if obj.get("error"):
                        stream_error = obj["error"]
            if timed_out:
                status = 504
            elif stream_error is not None:
                status = 500
            else:
                status = 200
            rec.update(
                status=status,
                error=stream_error,
                ttft_ms=(
                    (first - t0) * 1000.0 if first is not None else None
                ),
                itl_max_ms=itl_max if ntok > 1 else None,
                tokens=ntok, hung=False,
            )
    except urllib.error.HTTPError as e:
        rec.update(
            status=e.code,
            retry_after=e.headers.get("Retry-After"),
            hung=False,
        )
        e.read()
    except Exception as e:  # connection reset, socket timeout, ...
        rec.update(status=-1, error=repr(e), hung=False)


def open_loop_flood(
    address: str,
    arrivals: Sequence[float],
    payload_fn: Callable[[int], Dict[str, Any]],
    timeout_s: float = 60.0,
    join_timeout_s: float = 120.0,
) -> List[Dict[str, Any]]:
    """Fire ``payload_fn(i)`` at each arrival offset against a live
    server, one thread per request (open-loop: arrivals never wait for
    completions), and return one record per request.  A record whose
    ``hung`` is still True after the join timeout is a genuinely hung
    client — the failure mode the overload controller exists to make
    impossible."""
    records: List[Dict[str, Any]] = []
    threads: List[threading.Thread] = []
    t0 = time.monotonic()
    for i, at in enumerate(arrivals):
        payload = payload_fn(i)
        rec: Dict[str, Any] = {
            "i": i, "at_s": at,
            "priority": payload.get("priority", "interactive"),
            "status": None, "ttft_ms": None, "itl_max_ms": None,
            "tokens": 0, "retry_after": None, "hung": True,
        }
        records.append(rec)
        delay = at - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(
            target=_fire_one, args=(address, payload, rec, timeout_s),
            daemon=True,
        )
        th.start()
        threads.append(th)
    deadline = time.monotonic() + join_timeout_s
    for th in threads:
        th.join(timeout=max(0.0, deadline - time.monotonic()))
    return records


def summarize_flood(
    records: Sequence[Dict[str, Any]],
    slo_ttft_ms: Optional[float] = None,
    slo_itl_ms: Optional[float] = None,
    duration_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Per-class summary of an open-loop flood: served/refused/hung
    counts, TTFT percentiles, and SLO attainment over SERVED requests
    (refusals are the controller doing its job, not latency misses),
    plus goodput (tokens from served requests that met every
    configured deadline, per second of flood)."""
    def pct(vals: List[float], q: float) -> Optional[float]:
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 1)

    out: Dict[str, Any] = {"offered": len(records)}
    goodput_tokens = 0
    for cls in PRIORITIES:
        rs = [r for r in records if r["priority"] == cls]
        served = [r for r in rs if r["status"] == 200]
        ttfts = [r["ttft_ms"] for r in served if r["ttft_ms"] is not None]
        ok = []
        for r in served:
            ttft_ok = slo_ttft_ms is None or (
                r["ttft_ms"] is not None and r["ttft_ms"] <= slo_ttft_ms
            )
            itl_ok = slo_itl_ms is None or (
                r["itl_max_ms"] is None or r["itl_max_ms"] <= slo_itl_ms
            )
            ok.append(ttft_ok and itl_ok)
            if ttft_ok and itl_ok:
                goodput_tokens += r["tokens"]
        refused = [r for r in rs if r["status"] == 503]
        out[cls] = {
            "offered": len(rs),
            "served": len(served),
            "refused_503": len(refused),
            "refused_with_retry_after": sum(
                1 for r in refused if r.get("retry_after")
            ),
            "timeout_504": sum(1 for r in rs if r["status"] == 504),
            "errors": sum(
                1 for r in rs if r["status"] not in (200, 503, 504)
            ),
            "hung": sum(1 for r in rs if r["hung"]),
            "ttft_ms_p50": pct(ttfts, 0.50),
            "ttft_ms_p99": pct(ttfts, 0.99),
            "slo_attainment": (
                round(sum(ok) / len(ok), 4) if ok else None
            ),
        }
    out["hung_total"] = sum(1 for r in records if r["hung"])
    if duration_s:
        out["goodput_tokens_per_s"] = round(
            goodput_tokens / duration_s, 2
        )
    return out
