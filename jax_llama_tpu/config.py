"""Model configuration for the TPU-native LLaMA framework.

Plain frozen dataclass — no HuggingFace ``PretrainedConfig`` baggage.  Covers
the capability surface of the reference config (``/root/reference/jax_llama/
config.py:26-116``: vocab/hidden/layers/heads/GQA/rope_theta/max-seq/eps/
tying) plus the SwiGLU intermediate-size derivation rule the reference keeps
in its converter (``/root/reference/jax_llama/convert_weights.py:36-39``),
which belongs with the config.

TPU-first additions: explicit ``dtype``/``param_dtype`` policy (bf16 compute,
fp32 islands for norm/softmax/logits), ``scan_layers`` (lax.scan over a
stacked layer pytree instead of a Python-unrolled stack, keeping 80-layer
compile times flat), ``remat`` policy, and ``attn_impl`` selecting the XLA
reference attention or the Pallas flash kernel.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp


def swiglu_hidden_size(
    dim: int,
    multiple_of: int = 256,
    ffn_dim_multiplier: Optional[float] = None,
) -> int:
    """Meta's SwiGLU FFN sizing rule.

    Start from 4*dim, take 2/3 of it (SwiGLU has 3 matrices instead of 2),
    optionally scale (Llama-3 uses 1.3), and round up to ``multiple_of``.
    """
    hidden = int(2 * (4 * dim) / 3)
    if ffn_dim_multiplier is not None:
        hidden = int(ffn_dim_multiplier * hidden)
    return multiple_of * math.ceil(hidden / multiple_of)


@dataclasses.dataclass(frozen=True)
class LLaMAConfig:
    """Architecture + numerics configuration for a LLaMA-family model."""

    vocab_size: int = 32000
    dim: int = 4096                       # hidden size
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None      # None -> n_heads (no GQA)
    intermediate_size: Optional[int] = None  # None -> swiglu_hidden_size(...)
    multiple_of: int = 256
    ffn_dim_multiplier: Optional[float] = None
    max_seq_len: int = 2048
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    use_scaled_rope: bool = False         # Llama-3.1 context-extension RoPE
    tie_word_embeddings: bool = False

    # --- training regularization (reference config.py:85-87 capability).
    # Applied only when a dropout_rng is passed to forward/train_step;
    # inference paths stay deterministic regardless.
    resid_pdrop: float = 0.0              # after attention out and MLP out
    embd_pdrop: float = 0.0               # on token embeddings
    attn_pdrop: float = 0.0               # on attention probabilities
                                          #   (xla attention path only)

    # --- numerics / execution policy (TPU-first) ---
    dtype: str = "bfloat16"               # activation/compute dtype
    param_dtype: str = "float32"          # parameter storage dtype
    scan_layers: bool = True              # lax.scan over stacked layers
    scan_unroll: int = 1                  # lax.scan unroll factor (layers
                                          # per scan iteration; lets XLA
                                          # pipeline DMAs across layers)
    remat: bool = False                   # jax.checkpoint each block
    remat_policy: str = "dots"            # "dots": save matmul outputs,
                                          #   recompute elementwise only
                                          #   (+13% train step vs "full"
                                          #   on chip at 1B/bf16/S=2048);
                                          # "full": recompute everything
                                          #   (minimum memory)
    attn_impl: str = "xla"                # "xla" | "flash" (Pallas) | "ring"
                                          #   (seq-parallel ring attention) |
                                          #   "auto" (flash for prefill /
                                          #   long blocks, xla append-free
                                          #   path for decode steps)
    pp_microbatches: Optional[int] = None # GPipe microbatch count when the
                                          #   mesh has stage > 1 (None -> S)
    attn_softmax_dtype: str = "float32"   # fp32 softmax island
    logits_dtype: str = "float32"         # fp32 logits island
    kv_cache_dtype: str = "auto"          # "auto" (= activation dtype) |
                                          #   "int8" (per-slot-per-head
                                          #   scales; halves cache HBM
                                          #   traffic/memory; xla path)

    # --- attention kernel selection (ops/kernels.py registry).  These
    # name WHICH Pallas kernel serves each role when the role's path is
    # active at all (attn_impl / use_pallas_kernel still gate the
    # paths themselves).  "auto" is resolved ONCE at serving-batcher
    # construction (ctor-stable — no per-dispatch cache-key churn); a
    # config that still says "auto" at forward() time runs the custom
    # defaults.  Fallback ladders: splash -> flash -> xla;
    # stock-paged -> paged -> gathered.
    prefill_kernel: str = "flash"         # "flash" (custom Pallas) |
                                          #   "splash" (upstream splash-mha
                                          #   on the insert path; per-chunk
                                          #   shape eligibility falls back
                                          #   to flash) | "auto"
    decode_kernel: str = "paged"          # "paged" (custom block-table
                                          #   kernel) | "stock-paged"
                                          #   (upstream Pallas kernel, T=1
                                          #   non-int8 dispatches) | "auto"

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        assert self.dim % self.n_heads == 0
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        return swiglu_hidden_size(self.dim, self.multiple_of, self.ffn_dim_multiplier)

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def weight_dtype(self):
        return jnp.dtype(self.param_dtype)

    def replace(self, **kw) -> "LLaMAConfig":
        return dataclasses.replace(self, **kw)

    def validate(self) -> None:
        assert self.dim % self.n_heads == 0, "n_heads must divide dim"
        assert self.n_heads % self.kv_heads == 0, (
            "n_heads must be a multiple of n_kv_heads (GQA group size)"
        )
        if self.attn_impl not in ("xla", "flash", "ring", "auto"):
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        if self.remat_policy not in ("dots", "full"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                "expected 'dots' or 'full'"
            )
        for name in ("resid_pdrop", "embd_pdrop", "attn_pdrop"):
            p = getattr(self, name)
            if not 0.0 <= p < 1.0:
                raise ValueError(f"{name}={p} must be in [0, 1)")
        if self.kv_cache_dtype not in ("auto", "int8"):
            # A typo here would silently fall back to the full-precision
            # cache; fail instead.
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r}; "
                "expected 'auto' or 'int8'"
            )
        # Same silent-fallback hazard as kv_cache_dtype: a typo'd kernel
        # name would never match the dispatch predicates and quietly run
        # the default kernel forever.
        if self.prefill_kernel not in ("flash", "splash", "auto"):
            raise ValueError(
                f"unknown prefill_kernel {self.prefill_kernel!r}; "
                "expected 'flash', 'splash', or 'auto'"
            )
        if self.decode_kernel not in ("paged", "stock-paged", "auto"):
            raise ValueError(
                f"unknown decode_kernel {self.decode_kernel!r}; "
                "expected 'paged', 'stock-paged', or 'auto'"
            )


# ---------------------------------------------------------------------------
# Presets.  Sizes follow the published Meta architectures; these are
# architecture constants, not tuned values.
# ---------------------------------------------------------------------------

def tiny(**kw) -> LLaMAConfig:
    """Tiny config for unit tests (mirrors the reference's test config scale:
    /root/reference/jax_test.py:28-41)."""
    base = dict(
        vocab_size=256, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64, rope_theta=10000.0,
        rms_norm_eps=1e-5, dtype="float32", param_dtype="float32",
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama2_7b(**kw) -> LLaMAConfig:
    base = dict(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=None,
        multiple_of=256, max_seq_len=4096, rope_theta=10000.0,
        rms_norm_eps=1e-5,
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama2_13b(**kw) -> LLaMAConfig:
    base = dict(
        vocab_size=32000, dim=5120, n_layers=40, n_heads=40, n_kv_heads=None,
        multiple_of=256, max_seq_len=4096, rope_theta=10000.0,
        rms_norm_eps=1e-5,
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama2_70b(**kw) -> LLaMAConfig:
    base = dict(
        vocab_size=32000, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        multiple_of=4096, ffn_dim_multiplier=1.3, max_seq_len=4096,
        rope_theta=10000.0, rms_norm_eps=1e-5,
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama3_8b(**kw) -> LLaMAConfig:
    base = dict(
        vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        multiple_of=1024, ffn_dim_multiplier=1.3, max_seq_len=8192,
        rope_theta=500000.0, rms_norm_eps=1e-5,
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama3_70b(**kw) -> LLaMAConfig:
    base = dict(
        vocab_size=128256, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        multiple_of=4096, ffn_dim_multiplier=1.3, max_seq_len=8192,
        rope_theta=500000.0, rms_norm_eps=1e-5,
    )
    base.update(kw)
    return LLaMAConfig(**base)


def llama3_1_8b(**kw) -> LLaMAConfig:
    base = dict(use_scaled_rope=True, max_seq_len=131072)
    base.update(kw)
    return llama3_8b(**base)


def llama3_1_70b(**kw) -> LLaMAConfig:
    base = dict(use_scaled_rope=True, max_seq_len=131072)
    base.update(kw)
    return llama3_70b(**base)


PRESETS = {
    "tiny": tiny,
    "llama2-7b": llama2_7b,
    "llama2-13b": llama2_13b,
    "llama2-70b": llama2_70b,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "llama3.1-8b": llama3_1_8b,
    "llama3.1-70b": llama3_1_70b,
}


def get_config(name: str, **kw) -> LLaMAConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown config preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name](**kw)
