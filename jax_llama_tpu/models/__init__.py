from .llama import (
    KVCache,
    forward,
    init_cache,
    init_params,
    param_count,
)

__all__ = ["KVCache", "forward", "init_cache", "init_params", "param_count"]
