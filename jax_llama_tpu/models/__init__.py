from .llama import (
    AuxOutput,
    KVCache,
    forward,
    fuse_params,
    fuse_qkv,
    init_cache,
    init_params,
    param_count,
    split_qkv,
)

__all__ = [
    "AuxOutput", "KVCache", "forward", "fuse_params", "fuse_qkv",
    "init_cache", "init_params", "param_count", "split_qkv",
]
