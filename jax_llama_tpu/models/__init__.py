from .llama import (
    KVCache,
    forward,
    fuse_params,
    fuse_qkv,
    init_cache,
    init_params,
    param_count,
    split_qkv,
)

__all__ = [
    "KVCache", "forward", "fuse_params", "fuse_qkv", "init_cache",
    "init_params", "param_count", "split_qkv",
]
