"""LLaMA model — pure-functional JAX, TPU-first.

Capability parity with the reference Flax model (``/root/reference/jax_llama/
model.py``): token embedding, pre-norm residual blocks (GQA attention with
RoPE + SwiGLU MLP), final RMSNorm, tied-or-untied LM head, fixed-size KV
cache for autoregressive decode.

Architectural departures (deliberate, TPU-first):
  * **No module framework, no HF shell.**  Params are a plain pytree of
    arrays; the forward pass is a function.  This keeps the decode engine a
    clean ``lax.while_loop`` over explicit state (the reference routes its
    cache through Flax mutable collections and HF's generation mixin,
    model.py:402-546).
  * **Stacked layer params + ``lax.scan``** instead of the reference's
    Python-unrolled block list (model.py:579-592): compile time is O(1) in
    depth — 80-layer Llama-3-70B traces as fast as the 4-layer test config.
  * **No materialized [1,1,S,S] causal mask** (reference model.py:154).
    Masking derives from per-slot absolute positions stored alongside the
    cache, which also subsumes the reference's left-pad handling
    (generation.py:55-60): pad slots carry position -1 and are never
    attended.
  * fp32 islands: RMSNorm statistics, RoPE rotation, softmax, and logits run
    in float32; matmuls run in the activation dtype (bf16 on TPU) with fp32
    MXU accumulation.

Param tree layout (all layers stacked on a leading L axis):

    {"embed":  {"embedding": [V, D]},
     "layers": {"attn_norm": [L, D],
                "qkv": [L, KVH, G+2, D, hd],   # G = H // KVH (GQA group)
                "o": [L, H, hd, D],
                "mlp_norm": [L, D],
                "gate_up": [L, 2, D, F], "down": [L, F, D]},
     "final_norm": [D],
     "lm_head": [D, V]}            # absent when tie_word_embeddings

The q/k/v projections are stored FUSED as one weight (and gate/up as
another): decode is HBM-bandwidth-bound, and one [D, KVH*(G+2)*hd]
matmul streams the same bytes as three separate ones but pays one
fusion's fixed cost instead of three and keeps the DMA pipeline in a
single long burst (xplane-measured: the three separate projections ran
at ~80% of the bandwidth roofline vs ~90%+ for the large MLP matmuls —
the reference also runs them separately,
``/root/reference/jax_llama/model.py:210-214``).  Slot layout along
the G+2 axis of ``qkv``: [q_0..q_{G-1}, k, v] per KV head, so the
merged query-head order is h = kvh*G + g — identical to the GQA packing
contract the flash/paged kernels already use, and tensor-parallelism
shards the KVH axis exactly like the separate layout did.

Axis ORDER within the fused weights is chosen for the layer scan, not
for reading aloud: ``qkv`` stores [KVH, G+2, D, hd] and ``gate_up``
[2, D, F] — the contracted D axis SECOND-from-last — because that is
the operand layout XLA:TPU assigns the decode matmuls.  With D leading
(the r3 layout) each ``lax.scan`` iteration's dynamic-slice of the
stacked weight relayouted into the matmul's layout: an xplane-profiled
~175us/step of pure weight-copy traffic (two kLoop relayout fusions per
layer step); with matching axis order the slice is a free view
(A/B-measured on chip, see ROADMAP).  ``fuse_params`` migrates both the
separate-q/k/v layout and the r3 D-first fused layout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import LLaMAConfig
from ..ops.attention import attention_bias, dropout as _dropout, sdpa, sdpa_cached
from ..ops.flash_attention import flash_attention, flash_attention_quantized
from ..ops.norm import rms_norm
from ..ops.quant import QuantizedTensor as _QuantizedTensor
from ..ops.quant import matmul as _quant_matmul
from ..ops.rope import apply_rope, rope_table
from ..parallel.mesh import constrain, current_mesh

Params = Dict[str, Any]


def qeinsum(
    x: jnp.ndarray,
    w: Any,
    eq: str,
    dtype: Optional[jnp.dtype] = None,
    preferred_element_type: Optional[jnp.dtype] = None,
) -> jnp.ndarray:
    """Projection einsum that transparently handles int8 weights.

    QuantizedTensor weights route through ``ops.quant.matmul`` (the
    int8 dequant-fused contraction); plain arrays run the einsum HERE
    so the xplane source attribution lands on this file.  Before this
    split, bench.py's ``step_breakdown_us`` charged every bf16/fp32
    projection matmul to ``quant.py`` (the thin wrapper's frame), which
    made the breakdown's largest bucket unreadable — "quant.py
    2,572 µs/step" was the plain weight stream, not quantization work.
    Now ``quant.py`` in a trace means actual int8 dequant math.
    """
    dtype = dtype or x.dtype
    if isinstance(w, _QuantizedTensor):
        return _quant_matmul(x, w, eq, dtype, preferred_element_type)
    y = jnp.einsum(
        eq, x, w.astype(dtype),
        preferred_element_type=preferred_element_type,
    )
    return y if preferred_element_type else y.astype(dtype)


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["k", "v", "pos", "index", "k_scale", "v_scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class KVCache:
    """Fixed-capacity per-layer KV cache with per-slot absolute positions.

    k, v:  [L, B, S_max, KVH, head_dim] — activation dtype, or int8 when
           the cache is quantized (config.kv_cache_dtype == "int8").
    pos:   [B, S_max] int32 — absolute position written into each slot;
           -1 marks an invalid (padding / unwritten) slot.
    index: int32 — next write offset: scalar (lockstep decode) or [B]
           vector (per-row offsets, continuous batching; xla path only).
    k_scale, v_scale: [L, B, S_max, KVH] fp32 per-slot-per-head dequant
           scales (int8 cache only; None otherwise).  Scales are constant
           along head_dim, so dequantization commutes with the attention
           contractions — sdpa_cached folds them into scores/weights and
           the int8 payload is never materialized at full precision.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray
    index: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def per_row_index(self) -> bool:
        """True when ``index`` is a [B] vector — each batch row writes at
        its own offset (continuous batching).  Scalar = classic lockstep
        decode.  Vector indices require the xla attention path."""
        return self.index.ndim == 1


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["k", "v", "pos", "table", "fill", "k_scale", "v_scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class PagedKVCache:
    """Paged (block-table) KV cache for continuous-batching decode.

    The serving pool's own layout, consumed directly by ``paged_forward``
    via the Pallas paged-attention kernel (``ops.paged_attention``) — the
    kernel's index maps chase ``table``, so no gathered contiguous view
    is ever materialized.

    k, v:  [L, KVH, NB, BLK, head_dim] — KV-head-major so one
           (head, block) tile is a clean (BLK, head_dim) VMEM page;
           int8 when the pool is quantized.
    pos:   [NB, BLK] int32 absolute position per slot; -1 invalid.
    table: [B, MB] int32 physical block ids in sequence order; NB marks
           an unused entry.
    fill:  [B] int32 per-row next write offset in tokens (the host
           advances it after each step, like the gathered-view path).
    k_scale, v_scale: [L, KVH, NB, BLK] fp32 per-slot-per-head dequant
           scales (int8 pool only; None otherwise) — folded in-kernel.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray
    table: jnp.ndarray
    fill: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def n_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def paged_write_indices(
    table: jnp.ndarray,      # [B, MB] physical block ids (sentinel = NB)
    fill: jnp.ndarray,       # [B] per-row write offset (tokens)
    active: jnp.ndarray,     # [B] bool
    T: int,
    n_blocks: int,
    block_size: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Physical (block, offset) pairs for landing T new per-row entries.

    THE paged write-back contract, shared by ``paged_forward`` and
    ``serving._scatter_back`` so the two paths cannot drift: row b's
    token j goes to block ``table[b, (fill[b]+j) // BLK]`` at offset
    ``(fill[b]+j) % BLK``; inactive rows and columns past the row's
    reserved capacity resolve to the sentinel block id ``n_blocks``
    (callers scatter with ``mode="drop"``).

    Returns (blk [B, T], off [B, T], cols [B, T]) int32 — ``cols`` is
    the clamped per-row view column each (blk, off) pair corresponds to,
    so callers that read values out of a virtually-contiguous view use
    the same clamping as the slot derivation.
    """
    MB = table.shape[1]
    cols = fill[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    safe = jnp.minimum(cols, MB * block_size - 1)
    blk = jnp.take_along_axis(table, safe // block_size, axis=1)
    blk = jnp.where(
        active[:, None] & (cols < MB * block_size), blk, n_blocks
    )
    return blk, safe % block_size, safe


def _remat(fn, config: LLaMAConfig):
    """Per-block rematerialization with the configured recompute policy.

    "dots" keeps matmul outputs (no batch-dim contractions = the QKV /
    attention / MLP projections) and recomputes only elementwise work in
    the backward pass — measured +13% train-step throughput over full
    recompute on chip (1B bf16, B=4 x S=2048, flash VJP) at a modest
    activation-memory cost; "full" recomputes everything (the reference's
    flag, `/root/reference/jax_llama/model.py:556-558`, maps to flax's
    equivalent full-remat transform — which nothing there exercises).
    """
    if config.remat_policy == "dots":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    return jax.checkpoint(fn)


# Above this many (row, token) pairs paged_pool_write switches from the
# unrolled dynamic_update_slice chain to the batched scatter — see its
# docstring for the measured crossover.
_POOL_WRITE_UNROLL_MAX = 256

# attn_impl="auto" resolves to the Pallas flash kernel only for blocks
# LONGER than this many tokens (decode-sized steps stay on the
# append-free xla path, where flash's one-row grid loses).  Exported
# because serving keeps HOST mirrors of the resolution — the classic
# batched-prefill flash gate and the fused prefill chunk's
# (serving._Prefill.flash) fault-site / quarantine attribution — which
# must never drift from what forward() actually runs.
FLASH_MIN_SEQ = 8


def _constrain_heads(x: Optional[jnp.ndarray], axis: int):
    """Pin one array's (KV-)head axis to ``tensor`` when the active
    mesh's tensor size divides it; no-op otherwise (no mesh, head
    count not divisible, tensor == 1).  Left unconstrained, GSPMD's
    propagation is free to resolve conflicts by REPLICATING cached KV
    operands — a full-pool/full-view all-gather inside every decode
    iteration, which the comms-budget contracts (analysis/comms.py)
    treat as a hard finding."""
    mesh = current_mesh()
    if mesh is None or x is None:
        return x
    tp = int(mesh.shape.get("tensor", 1))
    if tp <= 1 or x.shape[axis] % tp:
        return x
    names: list = [None] * x.ndim
    names[axis] = "tensor"
    return constrain(x, *names)


def _constrain_pool_plane(plane: jnp.ndarray) -> jnp.ndarray:
    """Pin a paged-pool KV plane ``[L, KVH, NB, BLK(, d)]`` to the
    serving placement's KV-head-over-``tensor`` sharding.  No-op
    without an active mesh, for 2-dim pos planes, and when ``tensor``
    does not divide the head axis (off-envelope meshes keep legacy
    propagation).  See :func:`_constrain_heads` for why."""
    if plane.ndim < 4:
        return plane
    return _constrain_heads(plane, 1)


def paged_pool_write(
    plane: jnp.ndarray,
    upd: jnp.ndarray,
    blk: jnp.ndarray,
    off: jnp.ndarray,
) -> jnp.ndarray:
    """Land per-(row, token) pool updates via an unrolled chain of
    ``dynamic_update_slice`` ops instead of one batched scatter.

    Why not ``plane.at[:, :, blk, off].set(upd, mode="drop")``: XLA:TPU's
    scatter emitter assigns the [L, KVH, NB, BLK, d] operand a KVH-minor
    layout (the scattered [L, KVH, d] slabs become contiguous), and since
    the rest of the program — the Pallas paged-attention kernel included —
    wants the default layout, every decode step materialized FOUR
    full-pool layout copies (in + back, k and v): ~3.2 ms/step on the
    bench pool, dwarfing the attention kernel itself (xplane-measured,
    r4).  B*T unrolled dynamic_update_slices keep the pool in its default
    layout, update in place on the donated buffer, and move only the
    ~tens of KB actually being written.

    Drop semantics: ``paged_write_indices`` marks dead (row, token) pairs
    with the sentinel block id NB, which a scatter would drop but
    ``dynamic_update_slice`` silently CLAMPS.  Each update therefore
    re-reads the (clamped) target slab and selects it back for dead
    pairs — ``dynamic_slice`` clamps identically, so the dead write is an
    exact in-place no-op.

    Slot-count bound: the chain is B*T sequential ops — op count, trace
    and compile time all grow linearly, so past ``_POOL_WRITE_UNROLL_MAX``
    total (row, token) pairs this falls back to the batched scatter and
    eats its layout copies.  Measured on chip (bench pool, [16, 8, 64,
    128, 128] bf16, xplane device time): chain 0.86/1.11/1.97 ms at
    B*T = 8/64/256 vs scatter flat ~2.5 ms — crossover ~B*T = 360; the
    threshold sits below it because per-plane trace size (5 planes when
    int8) is the binding cost before device time is.

    plane: [L, KVH, NB, BLK, d] payload, [L, KVH, NB, BLK] scale, or
      [NB, BLK] position plane — the (NB, BLK) axes sit at (-3, -2),
      (-2, -1) and (0, 1) respectively, derived from ndim.
    upd: matching [L, KVH, B, T, d] / [L, KVH, B, T] / [B, T].
    blk, off: [B, T] int32 physical coordinates (sentinel NB = drop).
    """
    B, T = blk.shape
    plane = _constrain_pool_plane(plane)
    # The update slabs carry the same [L, KVH, ...] head axis: pin them
    # too, or their (replicated) sharding drags the slab re-reads — and
    # with them the whole plane — replicated through the `where`.
    upd = _constrain_pool_plane(upd)
    if B * T > _POOL_WRITE_UNROLL_MAX:
        # Batched scatter: mode="drop" discards the sentinel NB pairs,
        # matching the chain's contract exactly.
        if plane.ndim == 5 or plane.ndim == 4:
            return _constrain_pool_plane(plane.at[:, :, blk, off].set(
                upd.astype(plane.dtype), mode="drop"
            ))
        return plane.at[blk, off].set(upd.astype(plane.dtype), mode="drop")
    if plane.ndim == 5:
        L, KVH, NB, BLK, d = plane.shape
        nb_ax, slab = 2, (L, KVH, 1, 1, d)
        pick = lambda b, t: upd[:, :, b, t][:, :, None, None, :]
    elif plane.ndim == 4:
        L, KVH, NB, BLK = plane.shape
        nb_ax, slab = 2, (L, KVH, 1, 1)
        pick = lambda b, t: upd[:, :, b, t][:, :, None, None]
    else:
        NB, BLK = plane.shape
        nb_ax, slab = 0, (1, 1)
        pick = lambda b, t: upd[b, t][None, None]
    live = blk < NB  # off is always in range (contract above)
    zero = jnp.int32(0)
    for b in range(B):
        for t in range(T):
            start = (
                (zero,) * nb_ax + (blk[b, t], off[b, t])
                + (zero,) * (plane.ndim - nb_ax - 2)
            )
            cur = lax.dynamic_slice(plane, start, slab)
            u = jnp.where(live[b, t], pick(b, t).astype(plane.dtype), cur)
            plane = _constrain_pool_plane(
                lax.dynamic_update_slice(plane, u, start)
            )
    return plane


def lm_head_logits(
    params: Params, x: jnp.ndarray, config: LLaMAConfig, normed: bool = False
) -> jnp.ndarray:
    """Final RMSNorm + (tied or untied) LM head — the one logits path
    every forward variant shares.  x: [B, T, D] -> [B, T, V] in
    config.logits_dtype (fp32 island, reference model.py:732-736).
    ``normed=True`` means x is already the post-final-norm hidden state
    (callers that also emit it as an aux output norm exactly once)."""
    if not normed:
        x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    if config.tie_word_embeddings:
        kernel = params["embed"]["embedding"].T
    else:
        kernel = params["lm_head"]
    logits = qeinsum(
        x, kernel, "btd,dv->btv", config.activation_dtype,
        preferred_element_type=jnp.dtype(config.logits_dtype),
    ).astype(config.logits_dtype)
    return constrain(logits, "data", "seq", "tensor")


def quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the trailing head_dim: x [..., hd] ->
    (int8 [..., hd], fp32 scale [...]).

    Every int8-KV path quantizes INCREMENTALLY with this function — only
    the step's newly appended projections ([L, B, T, KVH, hd]; T=1 in
    decode) ever pass through it, with their per-slot-per-head scales
    cached alongside the int8 payload (KVCache.k_scale / BlockPool
    scale planes).  The stored pool is never round-tripped through
    re-quantization: attention folds the cached scales at the
    scores/probability level (sdpa_cached, flash/paged kernels) so the
    int8 bytes stream from HBM untouched.  The single fp32 cast below is
    shared by the amax and the rounding (one materialization, not two).
    """
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127).astype(
        jnp.int8
    )
    return q, scale


def init_cache(
    config: LLaMAConfig,
    batch: int,
    max_len: Optional[int] = None,
    dtype: Optional[jnp.dtype] = None,
) -> KVCache:
    """Allocate an empty cache (parity: reference ``init_cache``,
    model.py:459-476 — but as a plain pytree, not a Flax collection)."""
    config.validate()
    max_len = max_len or config.max_seq_len
    int8_kv = config.kv_cache_dtype == "int8" and dtype is None
    dtype = jnp.int8 if int8_kv else (dtype or config.activation_dtype)
    shape = (config.n_layers, batch, max_len, config.kv_heads, config.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype=dtype),
        v=jnp.zeros(shape, dtype=dtype),
        pos=jnp.full((batch, max_len), -1, dtype=jnp.int32),
        index=jnp.zeros((), dtype=jnp.int32),
        k_scale=jnp.zeros(shape[:-1], jnp.float32) if int8_kv else None,
        v_scale=jnp.zeros(shape[:-1], jnp.float32) if int8_kv else None,
    )


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, config: LLaMAConfig) -> Params:
    """Random init matching standard LLaMA scaling (normal, 0.02 std for
    embeddings; Lecun-style fan-in scaling for projections)."""
    config.validate()
    D, H, KVH, hd, F, V, L = (
        config.dim, config.n_heads, config.kv_heads, config.head_dim,
        config.ffn_dim, config.vocab_size, config.n_layers,
    )
    wd = config.weight_dtype
    keys = jax.random.split(rng, 10)

    def dense(key, shape, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(wd)

    def stacked(key, shape, fan_in):
        return dense(key, (L,) + shape, fan_in)

    G = H // KVH
    params: Params = {
        "embed": {
            "embedding": (
                jax.random.normal(keys[0], (V, D), dtype=jnp.float32) * 0.02
            ).astype(wd)
        },
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype=wd),
            "qkv": stacked(keys[1], (KVH, G + 2, D, hd), D),
            "o": stacked(keys[4], (H, hd, D), D),
            "mlp_norm": jnp.ones((L, D), dtype=wd),
            "gate_up": stacked(keys[5], (2, D, F), D),
            "down": stacked(keys[7], (F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype=wd),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(keys[8], (D, V), D)
    return params


def rope_permute(w: jnp.ndarray, inverse: bool = False) -> jnp.ndarray:
    """Permute a projection weight's trailing head_dim axis between Meta's
    interleaved RoPE feature order and the runtime half-split order
    (``ops.rope`` module docstring): forward maps Meta feature 2i -> i and
    2i+1 -> i + hd/2, so ``apply_rope``'s contiguous-half rotation equals
    the reference's interleaved complex rotation exactly.  Works on any
    array whose LAST axis is head_dim (numpy or jax)."""
    *lead, hd = w.shape
    if inverse:
        # [.., hd] viewed [.., 2, hd/2] -> swap -> [.., hd/2, 2] -> flat
        return w.reshape(*lead, 2, hd // 2).swapaxes(-1, -2).reshape(w.shape)
    return w.reshape(*lead, hd // 2, 2).swapaxes(-1, -2).reshape(w.shape)


def fuse_qkv(
    q: jnp.ndarray,  # [L, D, H, hd] (or [D, H, hd]), Meta feature order
    k: jnp.ndarray,  # [L, D, KVH, hd]
    v: jnp.ndarray,  # [L, D, KVH, hd]
) -> jnp.ndarray:
    """Pack separate q/k/v projection weights (Meta interleaved-RoPE
    feature order) into the fused [..., KVH, G+2, D, hd] runtime layout:
    slots [q_0..q_{G-1}, k, v] per KV head (query head order h = kvh*G +
    g, the kernels' GQA contract), with q/k head_dim features permuted to
    the half-split RoPE order (``rope_permute``; v is not rotated and
    keeps Meta order).  D sits second-from-last (see module docstring:
    the scan-slice layout contract)."""
    *lead, D, H, hd = q.shape
    KVH = k.shape[-2]
    G = H // KVH
    qg = jnp.moveaxis(
        rope_permute(q).reshape(*lead, D, KVH, G, hd), -4, -2
    )  # [..., KVH, G, D, hd]
    kk = jnp.swapaxes(rope_permute(k), -3, -2)[..., :, None, :, :]
    vv = jnp.swapaxes(v, -3, -2)[..., :, None, :, :]
    return jnp.concatenate([qg, kk, vv], axis=-3)


def split_qkv(
    qkv: jnp.ndarray,  # [..., KVH, G+2, D, hd]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Inverse of ``fuse_qkv``: (q [..., D, H, hd], k, v [..., D, KVH, hd])
    in Meta interleaved-RoPE feature order."""
    *lead, KVH, g2, D, hd = qkv.shape
    G = g2 - 2
    q = jnp.moveaxis(qkv[..., :G, :, :], -2, -4).reshape(
        *lead, D, KVH * G, hd
    )
    return (
        rope_permute(q, inverse=True),
        rope_permute(jnp.swapaxes(qkv[..., G, :, :], -3, -2), inverse=True),
        jnp.swapaxes(qkv[..., G + 1, :, :], -3, -2),
    )


def permute_d_axis(lp: Dict[str, Any], to_d_first: bool) -> Dict[str, Any]:
    """THE current-layout <-> r3 D-first axis contract, in one place
    (qkv: D between -2 and -4; gate_up: D between -2 and -3) — used by
    ``fuse_params`` and the checkpoint restore-time migration.
    QuantizedTensor leaves permute payload AND scale together (the scale
    keeps size-1 contracted dims in the same axis positions, so the
    transform is exact for int8 trees too)."""
    from ..ops.quant import QuantizedTensor

    def mv(x, src, dst):
        if isinstance(x, QuantizedTensor):
            return QuantizedTensor(
                q=jnp.moveaxis(x.q, src, dst),
                scale=jnp.moveaxis(x.scale, src, dst),
            )
        return jnp.moveaxis(x, src, dst)

    lp = dict(lp)
    if to_d_first:
        lp["qkv"] = mv(lp["qkv"], -2, -4)
        lp["gate_up"] = mv(lp["gate_up"], -2, -3)
    else:
        lp["qkv"] = mv(lp["qkv"], -4, -2)
        lp["gate_up"] = mv(lp["gate_up"], -3, -2)
    return lp


def fuse_params(params: Params) -> Params:
    """Migrate an old-layout param tree to the current fused layout:
    either separate q/k/v + gate/up (rounds 1-2 Orbax checkpoints) or the
    r3 D-first fused layout (qkv [L, D, KVH, G+2, hd], gate_up
    [L, D, 2, F]).  No-op when already current.  Quantized trees must be
    re-quantized from the full-precision source instead (scales do not
    concatenate)."""
    lp = dict(params["layers"])
    if "qkv" in lp:
        d_model = lp["attn_norm"].shape[-1]
        if (lp["qkv"].shape[-4] == d_model
                and lp["gate_up"].shape[-3] == d_model):
            # r3 D-first fused layout: move D to second-from-last.
            # (D == KVH cannot alias: KVH is a head count, D the model dim.)
            out = dict(params)
            out["layers"] = permute_d_axis(lp, to_d_first=False)
            return out
        return params
    lp["qkv"] = fuse_qkv(lp.pop("q"), lp.pop("k"), lp.pop("v"))
    lp["gate_up"] = jnp.stack([lp.pop("gate"), lp.pop("up")], axis=-3)
    out = dict(params)
    out["layers"] = lp
    return out


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def _rope_tables(head_dim: int, max_positions: int, theta: float, scaled: bool):
    return rope_table(head_dim, max_positions, theta, use_scaled_rope=scaled)


def _block(
    x: jnp.ndarray,
    lp: Dict[str, jnp.ndarray],
    cache_k: Optional[jnp.ndarray],
    cache_v: Optional[jnp.ndarray],
    cache_k_scale: Optional[jnp.ndarray] = None,
    cache_v_scale: Optional[jnp.ndarray] = None,
    dropout_rng: Optional[jax.Array] = None,
    *,
    config: LLaMAConfig,
    positions: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    slot_pos: jnp.ndarray,
    cache_index: Optional[jnp.ndarray],
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    bias_new: Optional[jnp.ndarray] = None,
    impl: str = "xla",
    paged_pos: Optional[jnp.ndarray] = None,
    paged_table: Optional[jnp.ndarray] = None,
    paged_qpos: Optional[jnp.ndarray] = None,
    paged_pools: Optional[Tuple[jnp.ndarray, ...]] = None,
    paged_layer: Optional[jnp.ndarray] = None,
    ring_new_pos: Optional[jnp.ndarray] = None,
    chunk_offset: Optional[int] = None,
    output_attentions: bool = False,
) -> Tuple[jnp.ndarray, ...]:
    """One pre-norm transformer block. x: [B, T, D].  ``impl`` is the
    RESOLVED attention implementation (forward maps "auto" to "flash" or
    "xla" per call based on T).

    Returns (x, cache_k, cache_v, cache_k_scale, cache_v_scale), plus a
    trailing [B, H, T, S] post-softmax probability array when
    ``output_attentions`` (xla path only — the flash/ring/paged kernels
    never materialize the weights; forward routes accordingly).  On the
    xla cached path cache_k/v are just this step's new projections (the
    caller writes them once, outside the layer scan) and the scales pass
    through untouched; on the flash cached path they are the fully
    updated per-layer cache (+ updated scales when int8)."""
    B, T, D = x.shape
    adt = x.dtype
    if output_attentions and impl != "xla":
        raise NotImplementedError(
            f"output_attentions requires the xla attention path "
            f"(got impl={impl!r}); forward() forces it when asked"
        )
    attn_weights = None

    # --- attention ---
    h = rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
    # One fused QKV matmul (see module docstring): [B,T,KVH,G+2,hd],
    # slots [q_0..q_{G-1}, k, v] per KV head.  Sharded over KVH on
    # "tensor", so the slice/reshape below are shard-local.
    G = config.n_heads // config.kv_heads
    qkv = qeinsum(h, lp["qkv"], "btd,cgdk->btcgk", adt)
    qkv = constrain(qkv, "data", "seq", "tensor", None, None)
    q = qkv[..., :G, :].reshape(B, T, config.n_heads, config.head_dim)
    k = qkv[..., G, :]
    v = qkv[..., G + 1, :]
    q = constrain(q, "data", "seq", "tensor", None)
    k = constrain(k, "data", "seq", "tensor", None)
    v = constrain(v, "data", "seq", "tensor", None)

    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)

    softmax_dtype = jnp.dtype(config.attn_softmax_dtype)
    if cache_k is not None and impl == "ring_decode":
        # Seq-sharded cached decode: the cache never moves (each seq
        # shard reduces its own slots; one pmax + two psums combine) and
        # stays immutable through the layer scan — same append-free
        # contract as the xla path below.  ``slot_pos`` here is the
        # PRE-step cache positions; the step's own tokens merge at the
        # softmax level inside ring_decode via ``ring_new_pos``.
        from ..parallel.ring import ring_decode

        if cache_k_scale is not None:
            # int8 seq-sharded cache: payload + scales stay int8/fp32 in
            # HBM, sharded along S; scales fold per shard inside the body.
            attn = ring_decode(
                q, cache_k, cache_v, slot_pos, k, v, positions,
                ring_new_pos, softmax_dtype=softmax_dtype,
                k_scale=cache_k_scale, v_scale=cache_v_scale,
            )
        else:
            attn = ring_decode(
                q, cache_k.astype(adt), cache_v.astype(adt), slot_pos,
                k, v, positions, ring_new_pos, softmax_dtype=softmax_dtype,
            )
        cache_k, cache_v = k, v
    elif cache_k is not None and impl == "xla":
        # Append-free decode: the cache stays immutable through the layer
        # scan; sdpa_cached softmaxes jointly over (cache slots, new
        # tokens) at the scores level, and the caller applies ONE in-place
        # dynamic-update-slice per step after the scan.  Mutating the
        # cache per layer inside scan/while forced XLA into a full-cache
        # double-buffer copy every decode step.  GQA replication stays
        # inside the attention op, after the cache (parity with reference
        # model.py:269-270).  ``bias`` masks the cache (unwritten slots
        # carry pos -1), ``bias_new`` masks/causes the new tokens.
        if cache_k_scale is not None:
            attn = sdpa_cached(
                q, cache_k, cache_v, k, v, bias, bias_new,
                softmax_dtype=softmax_dtype,
                k_scale=cache_k_scale, v_scale=cache_v_scale,
                return_weights=output_attentions,
            )
        else:
            attn = sdpa_cached(
                q, cache_k.astype(adt), cache_v.astype(adt), k, v,
                bias, bias_new, softmax_dtype=softmax_dtype,
                return_weights=output_attentions,
            )
        if output_attentions:
            attn, attn_weights = attn
        # ys: just this step's projections; forward writes them into the
        # cache once, outside the scan.
        cache_k, cache_v = k, v
    elif impl == "paged":
        # Paged decode: ``paged_pools`` is the FULL [L, KVH, NB, BLK, hd]
        # block pool (+ scales when int8) bound once outside the layer
        # scan, and ``paged_layer`` (the scan's loop index) selects the
        # plane inside the kernel's index maps — slicing pool[i] here
        # would materialize each layer's whole plane as the custom-call
        # operand, ~3x the kernel's own time at 16k contexts (r4,
        # xplane).  The new token's slot merges at the softmax level.
        # Pool stays immutable through the scan — paged_forward scatters
        # the ys once per step.  int8 pools fold their scales in-kernel;
        # the step's projections get quantized for the scatter but merge
        # at full precision (matching sdpa_cached's treatment of
        # same-step tokens).
        pool_k, pool_v, pool_ks, pool_vs = paged_pools
        if (
            config.decode_kernel == "stock-paged"
            and T == 1
            and pool_ks is None
        ):
            # Selected stock Pallas kernel (ops/kernels.py): T == 1
            # non-int8 dispatches only — the decode halves of
            # _chunk_scan/_fused_chunk and speculative DRAFT steps.
            # T > 1 (speculative verify) and int8 pools keep the custom
            # kernel (its native multi-token sweep / in-kernel scale
            # folding); the static predicate here makes that split a
            # trace-time decision, mirrored by serving's host-side
            # feature accounting.
            from ..ops.kernels import stock_paged_decode_attention

            attn = stock_paged_decode_attention(
                q, k, v, pool_k, pool_v, paged_table, paged_qpos,
                layer=paged_layer,
            )
        else:
            from ..ops.paged_attention import paged_decode_attention

            attn = paged_decode_attention(
                q, k, v, pool_k, pool_v, paged_pos, paged_table,
                paged_qpos, k_scale=pool_ks, v_scale=pool_vs,
                layer=paged_layer,
            )
        if pool_ks is not None:
            k, cache_k_scale = quantize_kv(k)
            v, cache_v_scale = quantize_kv(v)
        cache_k, cache_v = k, v
    elif cache_k is not None and cache_k_scale is not None:
        # int8 cache on the flash path: quantize this chunk's projections,
        # land payload + scales at [cache_index, cache_index+T), and
        # attend the whole cache with in-kernel scale folding — the int8
        # bytes stream straight from HBM, never dequantized in memory.
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        cache_k = lax.dynamic_update_slice(
            cache_k, kq, (0, cache_index, 0, 0)
        )
        cache_v = lax.dynamic_update_slice(
            cache_v, vq, (0, cache_index, 0, 0)
        )
        cache_k_scale = lax.dynamic_update_slice(
            cache_k_scale, ks, (0, cache_index, 0)
        )
        cache_v_scale = lax.dynamic_update_slice(
            cache_v_scale, vs, (0, cache_index, 0)
        )
        attn = flash_attention_quantized(
            q, cache_k, cache_v, cache_k_scale, cache_v_scale,
            positions, slot_pos,
        )
    else:
        if cache_k is not None:
            # Flash path: write the T new KV entries at
            # [cache_index, cache_index+T), then attend the full cache.
            cache_k = lax.dynamic_update_slice(
                cache_k, k.astype(cache_k.dtype), (0, cache_index, 0, 0)
            )
            cache_v = lax.dynamic_update_slice(
                cache_v, v.astype(cache_v.dtype), (0, cache_index, 0, 0)
            )
            kk, vv = cache_k.astype(adt), cache_v.astype(adt)
        else:
            kk, vv = k, v
        if impl == "ring" and cache_k is None:
            # Sequence-parallel path (training / scoring / cache-free
            # prefill): ring over the seq mesh axis.  attn_pdrop composes:
            # the mask is a position-keyed counter hash (ring.dropout_keep)
            # — invariant to chunking and ring layout by construction.
            from ..parallel.ring import ring_sdpa

            attn = ring_sdpa(
                q, kk, vv, positions, slot_pos,
                dropout_rng=(
                    jax.random.fold_in(dropout_rng, 0)
                    if dropout_rng is not None and config.attn_pdrop > 0.0
                    else None
                ),
                dropout_rate=config.attn_pdrop,
            )
        elif impl in ("flash", "ring"):
            from ..ops.kernels import splash_eligible

            if cache_k is not None and splash_eligible(
                config, batch=B, q_len=T, kv_len=kk.shape[1],
                chunk_offset=chunk_offset,
            ):
                # Selected splash prefill (ops/kernels.py): the insert
                # path's chunk offset is a static Python int (the chunk
                # loop variable), so the chunk's causal window is a pure
                # static CausalMask — splash's whole mask surface.
                # Per-chunk shape eligibility (128-multiples) falls back
                # to flash HERE, statically, chunk by chunk; the fused
                # prefill window's TRACED base can never reach this
                # branch (chunk_offset stays None there).  Dropout
                # cannot co-occur (cached forwards reject dropout_rng).
                from ..ops.kernels import splash_prefill_attention

                attn = splash_prefill_attention(
                    q, kk, vv, chunk_offset=chunk_offset
                )
            elif dropout_rng is not None and config.attn_pdrop > 0.0:
                # In-kernel probability dropout: the mask is generated
                # blockwise inside the flash forward AND rebuilt
                # bit-identically in the backward kernels — O(S·d) memory
                # stands, so attention-dropout training works at long
                # context (the xla path materializes [B, H, T, S]).
                attn = flash_attention(
                    q, kk, vv, positions, slot_pos,
                    dropout_rate=config.attn_pdrop,
                    dropout_seed=jax.random.bits(
                        jax.random.fold_in(dropout_rng, 0), (2,), "uint32"
                    ),
                )
            else:
                attn = flash_attention(q, kk, vv, positions, slot_pos)
        else:
            attn = sdpa(
                q, kk, vv, bias, softmax_dtype=softmax_dtype,
                dropout_rng=(
                    jax.random.fold_in(dropout_rng, 0)
                    if dropout_rng is not None and config.attn_pdrop > 0.0
                    else None
                ),
                dropout_rate=config.attn_pdrop,
                return_weights=output_attentions,
            )
            if output_attentions:
                attn, attn_weights = attn

    attn_out = qeinsum(attn, lp["o"], "bthk,hkd->btd", adt)
    attn_out = constrain(attn_out, "data", "seq", None)
    if dropout_rng is not None and config.resid_pdrop > 0.0:
        attn_out = _dropout(
            jax.random.fold_in(dropout_rng, 1), attn_out, config.resid_pdrop
        )
    x = x + attn_out

    # --- SwiGLU MLP (fused gate+up matmul: one weight stream, one
    # fusion — the F axis stays "tensor"-sharded like the separate
    # layout) ---
    h = rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
    gate_up = qeinsum(h, lp["gate_up"], "btd,cdf->btcf", adt)
    gate_up = constrain(gate_up, "data", "seq", None, "tensor")
    hidden = jax.nn.silu(gate_up[..., 0, :]) * gate_up[..., 1, :]
    down = qeinsum(hidden, lp["down"], "btf,fd->btd", adt)
    down = constrain(down, "data", "seq", None)
    if dropout_rng is not None and config.resid_pdrop > 0.0:
        down = _dropout(
            jax.random.fold_in(dropout_rng, 2), down, config.resid_pdrop
        )
    x = x + down
    if output_attentions:
        return x, cache_k, cache_v, cache_k_scale, cache_v_scale, attn_weights
    return x, cache_k, cache_v, cache_k_scale, cache_v_scale


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["hidden_states", "last_hidden_state", "attentions"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class AuxOutput:
    """Optional eval/interp outputs of ``forward`` — capability parity
    with the reference's ``output_hidden_states`` / ``output_attentions``
    (reference model.py:488-494) and its head-less ``FlaxLLaMAModel``
    (model.py:745).

    hidden_states: [L+1, B, T, D] (or None).  Entries 0..L-1 are each
      block's INPUT (entry 0 = the embedding output), entry L is the
      POST-final-norm hidden state — the reference's exact collection
      points (model.py:580-581 per-block, :663-666 final norm appended).
      Stacked into one array rather than a Python tuple: TPU-idiomatic
      (one transfer), and ``aux.hidden_states[i]`` reads the same way.
    last_hidden_state: [B, T, D] post-final-norm hidden state
      (== hidden_states[-1]) — what the reference's base model without
      the LM head returns.  Present whenever aux is requested, so
      ``forward(..., compute_logits=False, output_hidden_states=True)``
      IS the head-less model call.
    attentions: [L, B, H, T, S] post-softmax attention probabilities
      (or None unless ``output_attentions``).  S spans the cache slots
      then the step's new tokens on the cached path.
    """

    hidden_states: Optional[jnp.ndarray]
    last_hidden_state: jnp.ndarray
    attentions: Optional[jnp.ndarray]


def forward(
    params: Params,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    config: LLaMAConfig,
    cache: Optional[KVCache] = None,
    attn_mask: Optional[jnp.ndarray] = None,
    compute_logits: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    output_hidden_states: bool = False,
    output_attentions: bool = False,
    output_last_hidden: bool = False,
    chunk_offset: Optional[int] = None,
):
    """Run the transformer.

    Args:
      params: pytree from `init_params` / the checkpoint loader.
      tokens: [B, T] int32 token ids.
      positions: [B, T] int32 absolute positions.  Padding tokens carry -1;
        they are clamped to 0 for RoPE/query purposes and recorded as -1
        (permanently masked) in the cache.
      config: model config.
      cache: optional KVCache.  When given, the T tokens are appended at
        `cache.index` and attention runs over the whole cache; when None,
        plain causal attention over the T tokens (training / parity path).
        Callers must keep `cache.index + T <= cache.max_len`:
        `dynamic_update_slice` clamps out-of-range writes silently (the
        decode engine enforces this bound statically).
      attn_mask: optional [B, T] bool, False for padding.  Defaults to
        positions >= 0.
      compute_logits: False skips final-norm + lm_head and returns
        (None, cache) — for cache-building forwards (e.g. non-final
        prefill chunks) whose [B, T, V] fp32 logits would be thrown away.
      dropout_rng: optional PRNG key enabling dropout (training only —
        requires cache=None) at the config's embd/resid/attn_pdrop rates
        (reference capability: config.py:85-87, model.py:166-168,296-299).
        None, or all rates zero, means fully deterministic.
      output_hidden_states / output_attentions: ALSO return an
        ``AuxOutput`` (see its docstring) — the eval/interp/debug
        surface, parity with the reference's flags (model.py:488-494).
        The layer stack unrolls for the collection (compile time O(L),
        per-layer arrays are real outputs — not the hot path), and
        ``output_attentions`` forces the xla attention path (the
        flash/ring/paged kernels never materialize the [B, H, T, S]
        weights; the xla path is the one that computes them anyway).
        Not supported on paged caches (a serving path) or stage > 1
        (pipeline) meshes.
      output_last_hidden: ALSO return an ``AuxOutput`` holding ONLY
        ``last_hidden_state`` (post-final-norm [B, T, D]).  Unlike the
        collect flags above this is a hot-path surface: the scan stack
        (and the pipeline stack) runs unchanged — nothing per-layer is
        stacked — so the fused training loss uses it with
        ``compute_logits=False`` to take the head matmul chunkwise
        (``ops.loss``) instead of materializing [B, T, V] logits.
        Subsumed by the collect flags when both are set.
      chunk_offset: STATIC (Python int) absolute position of this
        call's first token, when the caller knows it at trace time —
        the serving insert path passes its chunk-loop variable.  Only
        consulted by the splash prefill kernel (ops/kernels.py), whose
        causal mask is built at trace time from this offset; None (the
        default, and every traced-position caller) keeps the custom
        flash kernel.  The cache's own ``index`` cannot serve here: it
        is a traced scalar.
    Returns:
      (logits [B, T, V] in config.logits_dtype, updated cache or None);
      logits is None when compute_logits=False.  When any output
      flag is set, a third ``AuxOutput`` element is appended:
      (logits, cache, aux).
    """
    collect = output_hidden_states or output_attentions
    if isinstance(cache, PagedKVCache):
        if dropout_rng is not None:
            raise ValueError("dropout_rng is training-only (paged decode)")
        if collect or output_last_hidden:
            raise NotImplementedError(
                "output_hidden_states/output_attentions/output_last_hidden "
                "are not supported on the paged (serving) path; use a "
                "plain KVCache or a cache-free forward"
            )
        return paged_forward(
            params, tokens, positions, config, cache,
            attn_mask=attn_mask, compute_logits=compute_logits,
        )
    B, T = tokens.shape
    adt = config.activation_dtype
    if dropout_rng is not None and not (
        config.embd_pdrop > 0.0 or config.resid_pdrop > 0.0
        or config.attn_pdrop > 0.0
    ):
        dropout_rng = None  # all rates zero: identical trace either way
    if dropout_rng is not None and cache is not None:
        raise ValueError(
            "dropout_rng is training-only; cached decode is deterministic "
            "(pass dropout_rng=None)"
        )
    if attn_mask is None:
        attn_mask = positions >= 0
    q_positions = jnp.maximum(positions, 0)

    # Size the RoPE table to cover the largest reachable position: a cache
    # longer than max_seq_len (long-context decode) would otherwise run off
    # the table and jnp.take's clipping would silently repeat the last angle.
    max_positions = max(
        2 * config.max_seq_len, cache.max_len if cache is not None else 0
    )
    cos, sin = _rope_tables(
        config.head_dim, max_positions, config.rope_theta,
        config.use_scaled_rope,
    )

    x = jnp.take(params["embed"]["embedding"], tokens, axis=0).astype(adt)
    x = constrain(x, "data", "seq", None)

    layers_rng = None
    if dropout_rng is not None:
        emb_rng, rest_rng = jax.random.split(dropout_rng)
        if config.embd_pdrop > 0.0:
            x = _dropout(emb_rng, x, config.embd_pdrop)
        if config.resid_pdrop > 0.0 or config.attn_pdrop > 0.0:
            # Embedding-only dropout needs no per-layer rng threading (and
            # therefore composes with every layer-stack execution path).
            layers_rng = rest_rng

    if config.attn_impl not in ("xla", "flash", "ring", "auto"):
        raise NotImplementedError(f"attn_impl={config.attn_impl!r}")
    # "auto": Pallas flash for prefill/long blocks (no dense [B,1,T,S] bias,
    # O(S*d) memory), append-free xla path for decode-sized steps (T small)
    # where flash's one-row grid and in-scan cache writes lose.
    impl = config.attn_impl
    if impl == "auto":
        # Per-row indices are only supported on the xla path, so "auto"
        # resolves there regardless of T in that case.  (int8 caches and
        # attention dropout run on both: the flash kernel folds dequant
        # scales — and generates dropout masks — in-kernel.)
        must_xla = cache is not None and cache.per_row_index
        impl = "flash" if T > FLASH_MIN_SEQ and not must_xla else "xla"
    if output_attentions:
        if impl == "ring":
            raise NotImplementedError(
                "output_attentions does not compose with ring "
                "(seq-sharded) attention — the chunked accumulation "
                "never materializes the weights; use "
                "attn_impl='xla'/'auto'/'flash'"
            )
        impl = "xla"  # the only path that materializes [B, H, T, S]
    bias_new = None
    ring_cached = False
    if cache is not None and impl == "ring":
        from ..parallel.mesh import current_mesh as _cm

        _m = _cm()
        if _m is not None and _m.shape.get("seq", 1) > 1:
            # Seq-sharded cached decode (ring_decode): the cache shards
            # stay put and partial softmax stats combine over `seq` —
            # context is bounded by the mesh's combined HBM, not one
            # chip's.  Long prompts should prefill in chunks
            # (GenerationConfig.prefill_chunk): the step's own-token
            # merge is O(T_chunk²).
            if cache.per_row_index:
                raise NotImplementedError(
                    "seq-sharded decode needs a lockstep (scalar) cache "
                    "index; continuous batching uses seq == 1 meshes"
                )
            ring_cached = True
            impl = "ring_decode"
    xla_cached = cache is not None and impl == "xla"

    # Slot positions / masking state are layer-independent: compute once,
    # close over them.  The dense [B,1,T,S] bias is only materialized on the
    # XLA reference path — the flash kernel recomputes masks blockwise from
    # the positions and never holds an S×S buffer.
    new_slot_pos = jnp.where(attn_mask, q_positions, -1).astype(jnp.int32)
    if cache is not None and cache.per_row_index:
        if not xla_cached:
            raise NotImplementedError(
                "per-row cache indices (continuous batching) require the "
                "xla attention path"
            )
        # Scatter the T new slot positions at each row's own offset;
        # rows whose offset would run past the cache drop the write.
        _rows = jnp.arange(B, dtype=jnp.int32)[:, None]
        _cols = cache.index[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        slot_pos = cache.pos.at[_rows, _cols].set(new_slot_pos, mode="drop")
    elif cache is not None:
        slot_pos = lax.dynamic_update_slice(
            cache.pos, new_slot_pos, (0, cache.index)
        )
    else:
        slot_pos = new_slot_pos
    if impl in ("flash", "ring", "ring_decode"):
        bias = None  # positional masks are built inside the kernels/bodies
    elif xla_cached:
        # Append-free decode (see _block): the cache bias masks the OLD
        # cache contents (unwritten slots hold pos -1), the new tokens get
        # their own within-step causal/padding bias.
        bias = attention_bias(q_positions, cache.pos, cache.pos >= 0)
        bias_new = attention_bias(q_positions, new_slot_pos, attn_mask)
    else:
        bias = attention_bias(q_positions, slot_pos, slot_pos >= 0)

    block = functools.partial(
        _block,
        config=config,
        positions=q_positions,
        bias=bias,
        # ring_decode attends the PRE-step cache (its own tokens merge at
        # the softmax level via ring_new_pos); every other cached path
        # sees the updated slot positions.
        slot_pos=cache.pos if ring_cached else slot_pos,
        cache_index=cache.index if cache is not None else None,
        cos=cos,
        sin=sin,
        bias_new=bias_new,
        impl=impl,
        ring_new_pos=new_slot_pos if ring_cached else None,
        chunk_offset=chunk_offset,
    )
    if config.remat:
        block = _remat(block, config)

    lp = params["layers"]
    from ..parallel.mesh import current_mesh

    _mesh = current_mesh()
    pp_stages = _mesh.shape.get("stage", 1) if _mesh is not None else 1
    if pp_stages > 1 and cache is not None:
        # shard_params on a stage>1 mesh stores each layer's weights only on
        # its stage group; running the plain scan over that layout would
        # silently all-gather every layer's weights per decode step.
        raise NotImplementedError(
            "decode with a KV cache is not supported on a stage > 1 mesh; "
            "generation meshes keep stage == 1 (use data/tensor axes)"
        )
    if collect and pp_stages > 1:
        raise NotImplementedError(
            "output_hidden_states/output_attentions are not supported on "
            "stage > 1 (pipeline) meshes — per-layer outputs live on "
            "their stage group; run the eval forward on a stage == 1 mesh"
        )
    if pp_stages > 1:
        # Pipeline-parallel block stack (training / scoring).  Embed, final
        # norm, and the LM head stay outside — auto-sharded, replicated
        # over the stage axis.  Decode-over-cache under pipeline
        # parallelism is not supported (the cache would need to live
        # per-stage); generation meshes keep stage == 1.
        from ..parallel.pipeline import pipeline_blocks

        if _mesh.shape.get("seq", 1) > 1:
            raise NotImplementedError(
                "stage > 1 does not compose with seq > 1 (ring attention "
                "nests a second shard_map); use stage*tensor*data/fsdp "
                "meshes for pipeline training"
            )

        # Per-layer dropout keys ride the staged tree ([L] leaves reshape
        # to [S, L/S] like the weights); each stage folds the current
        # microbatch index in, so every (layer, microbatch) pair draws an
        # independent mask — stage-1 semantics, microbatched.
        with_drop = layers_rng is not None
        stage_tree = (
            (lp, jax.random.split(layers_rng, config.n_layers))
            if with_drop else lp
        )

        def stage_fn(stage_layers, xx, pos, spos, mb_index):
            sbias = (
                None
                if impl in ("flash", "ring")
                else attention_bias(pos, spos, spos >= 0)
            )

            def one(carry, xs):
                if with_drop:
                    lp_i, key_i = xs
                    rng_i = jax.random.fold_in(key_i, mb_index)
                else:
                    lp_i, rng_i = xs, None
                y, *_ = _block(
                    carry, lp_i, None, None, None, None, rng_i,
                    config=config, positions=pos, bias=sbias,
                    slot_pos=spos, cache_index=None, cos=cos, sin=sin,
                    impl=impl,
                )
                return y, None

            if config.remat:
                one = _remat(one, config)
            y, _ = lax.scan(one, xx, stage_layers)
            return y

        x = pipeline_blocks(
            stage_fn, stage_tree, x, q_positions, slot_pos,
            mesh=_mesh,
            n_microbatches=config.pp_microbatches or pp_stages,
        )
    new_k_scale = cache.k_scale if cache is not None else None
    new_v_scale = cache.v_scale if cache is not None else None
    hs: list = []     # per-block inputs (collect only)
    attns: list = []  # per-block attention probabilities (collect only)
    # Collection runs on the UNROLLED stack: per-layer arrays are real
    # outputs, so a scan would have to carry them as ys anyway — and the
    # O(L) compile is fine for an eval/interp surface.
    if config.scan_layers and pp_stages <= 1 and not collect:
        if cache is not None and cache.quantized:
            # Scales ride the scan alongside the int8 payload.  On the
            # xla path the returned ck/cv are this step's projections and
            # the scales pass through unchanged (forward quantizes after
            # the scan); on the flash path they are the updated int8
            # cache + scales per layer.
            def scan_fn(carry, xs):
                layer_params, ck, cv, cks, cvs = xs
                # Per-layer cache slices [B, S, KVH(, hd)]: keep the
                # KV-head axis sharded through the scan's xs slicing.
                y, ck, cv, cks, cvs = block(
                    carry, layer_params,
                    _constrain_heads(ck, 2), _constrain_heads(cv, 2),
                    _constrain_heads(cks, 2), _constrain_heads(cvs, 2),
                )
                return y, (ck, cv, cks, cvs)

            x, (new_k, new_v, nks, nvs) = lax.scan(
                scan_fn, x,
                (lp, cache.k, cache.v, cache.k_scale, cache.v_scale),
                unroll=config.scan_unroll,
            )
            if not xla_cached:
                new_k_scale, new_v_scale = nks, nvs
        elif cache is not None:
            # On the xla_cached path the cache rides xs READ-ONLY and the
            # ys are just each layer's new [B,T,KVH,hd] projections —
            # rebuilding the full cache as ys would force a whole-cache
            # double-buffer copy per decode step inside scan/while.
            def scan_fn(carry, xs):
                layer_params, ck, cv = xs
                # Per-layer cache slices [B, S, KVH, hd]: keep the
                # KV-head axis sharded through the scan's xs slicing.
                y, ck, cv, _, _ = block(
                    carry, layer_params,
                    _constrain_heads(ck, 2), _constrain_heads(cv, 2),
                )
                return y, (ck, cv)

            x, (new_k, new_v) = lax.scan(
                scan_fn, x, (lp, cache.k, cache.v),
                unroll=config.scan_unroll,
            )
        elif layers_rng is not None:
            # Per-layer dropout keys ride the scan as xs alongside the
            # stacked weights.
            layer_rngs = jax.random.split(layers_rng, config.n_layers)

            def scan_fn(carry, xs):
                layer_params, rng_i = xs
                y, *_ = block(
                    carry, layer_params, None, None, None, None, rng_i
                )
                return y, None

            x, _ = lax.scan(
                scan_fn, x, (lp, layer_rngs), unroll=config.scan_unroll
            )
        else:
            def scan_fn(carry, layer_params):
                y, *_ = block(carry, layer_params, None, None)
                return y, None

            x, _ = lax.scan(scan_fn, x, lp, unroll=config.scan_unroll)
    elif pp_stages <= 1:
        unroll_rngs = (
            jax.random.split(layers_rng, config.n_layers)
            if layers_rng is not None else None
        )
        new_ks, new_vs, new_kss, new_vss = [], [], [], []
        for i in range(config.n_layers):
            layer_params = jax.tree.map(lambda a: a[i], lp)
            ck = cache.k[i] if cache is not None else None
            cv = cache.v[i] if cache is not None else None
            cks = cache.k_scale[i] if cache is not None and cache.quantized else None
            cvs = cache.v_scale[i] if cache is not None and cache.quantized else None
            if output_hidden_states:
                hs.append(x)
            x, ck, cv, cks, cvs, *aw = block(
                x, layer_params, ck, cv, cks, cvs,
                unroll_rngs[i] if unroll_rngs is not None else None,
                output_attentions=output_attentions,
            )
            if output_attentions:
                attns.append(aw[0])
            new_ks.append(ck)
            new_vs.append(cv)
            new_kss.append(cks)
            new_vss.append(cvs)
        if cache is not None:
            new_k = jnp.stack(new_ks)
            new_v = jnp.stack(new_vs)
            if cache.quantized and not xla_cached:
                new_k_scale = jnp.stack(new_kss)
                new_v_scale = jnp.stack(new_vss)
    if cache is not None and (xla_cached or ring_cached):
        # new_k/new_v hold the per-layer NEW projections [L, B, T, KVH, hd];
        # one in-place write (per array) lands them all in the cache —
        # quantizing first when the cache is int8.  Scalar index: a
        # dynamic-update-slice at the shared offset.  Per-row index
        # (continuous batching): a scatter at each row's own offset —
        # advanced indices on the contiguous (B, S) axes keep the update
        # shape [L, B, T, KVH, hd]; out-of-capacity rows drop the write.
        if cache.quantized:
            new_k, k_s = quantize_kv(new_k)
            new_v, v_s = quantize_kv(new_v)
        if cache.per_row_index:
            rows = jnp.arange(B, dtype=jnp.int32)[:, None]
            cols = (
                cache.index[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            )
            if cache.quantized:
                new_k_scale = cache.k_scale.at[:, rows, cols].set(
                    k_s, mode="drop"
                )
                new_v_scale = cache.v_scale.at[:, rows, cols].set(
                    v_s, mode="drop"
                )
            new_k = cache.k.at[:, rows, cols].set(
                new_k.astype(cache.k.dtype), mode="drop"
            )
            new_v = cache.v.at[:, rows, cols].set(
                new_v.astype(cache.v.dtype), mode="drop"
            )
        else:
            if cache.quantized:
                new_k_scale = lax.dynamic_update_slice(
                    cache.k_scale, k_s, (0, 0, cache.index, 0)
                )
                new_v_scale = lax.dynamic_update_slice(
                    cache.v_scale, v_s, (0, 0, cache.index, 0)
                )
            new_k = lax.dynamic_update_slice(
                cache.k, new_k.astype(cache.k.dtype), (0, 0, cache.index, 0, 0)
            )
            new_v = lax.dynamic_update_slice(
                cache.v, new_v.astype(cache.v.dtype), (0, 0, cache.index, 0, 0)
            )
    if ring_cached:
        # Keep the cache sharded along S over `seq` across steps (GSPMD
        # applies the tiny T-token update per shard; no gather).  S must
        # be divisible by the seq axis size.
        new_k = constrain(new_k, None, "data", "seq", "tensor", None)
        new_v = constrain(new_v, None, "data", "seq", "tensor", None)
        slot_pos = constrain(slot_pos, "data", "seq")
        if cache.quantized:
            new_k_scale = constrain(new_k_scale, None, "data", "seq", "tensor")
            new_v_scale = constrain(new_v_scale, None, "data", "seq", "tensor")

    aux = None
    with_aux = collect or output_last_hidden
    if with_aux:
        final_h = rms_norm(x, params["final_norm"], config.rms_norm_eps)
        aux = AuxOutput(
            hidden_states=(
                jnp.stack(hs + [final_h]) if output_hidden_states else None
            ),
            last_hidden_state=final_h,
            attentions=jnp.stack(attns) if output_attentions else None,
        )
    logits = (
        lm_head_logits(
            params, final_h if with_aux else x, config, normed=with_aux
        )
        if compute_logits else None
    )

    if cache is not None:
        new_cache = KVCache(
            k=new_k, v=new_v, pos=slot_pos, index=cache.index + T,
            k_scale=new_k_scale, v_scale=new_v_scale,
        )
        return (logits, new_cache, aux) if with_aux else (logits, new_cache)
    return (logits, None, aux) if with_aux else (logits, None)


def paged_forward(
    params: Params,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    config: LLaMAConfig,
    cache: PagedKVCache,
    attn_mask: Optional[jnp.ndarray] = None,
    compute_logits: bool = True,
) -> Tuple[Optional[jnp.ndarray], PagedKVCache]:
    """One decode step of T tokens per row over a paged block pool
    (continuous batching; T=1 is plain decode, T=G+1 is speculative
    verify).

    The Pallas paged-attention kernel chases ``cache.table`` inside its
    BlockSpec index maps, so each layer's pool is read ONCE per step —
    for ALL T tokens of a row — and no gathered contiguous view exists
    (the pool bytes previously moved three times per step: gather read,
    gather write, attention read).  The pool rides the layer scan
    immutably; the step's new K/V land via one scatter per array
    afterwards, mirroring the xla_cached contract.

    Contract for T > 1 (the kernel derives per-token masks from a
    sublane iota): each active row's positions are CONSECUTIVE —
    ``positions[:, t] == positions[:, 0] + t`` — and a row is active or
    inactive as a whole (``attn_mask`` constant along T).  Speculative
    rounds satisfy both by construction; a row violating either is
    folded to inactive (enforced below) rather than trusted.

    Rows with ``attn_mask`` False (or position -1) are inactive: they
    attend nothing, their logits are garbage the host ignores, and their
    scatter resolves to the sentinel block id and is dropped.
    """
    B, T = tokens.shape
    adt = config.activation_dtype
    if attn_mask is None:
        attn_mask = positions >= 0
    q_positions = jnp.maximum(positions, 0)
    NB, BLK = cache.pos.shape
    MB = cache.table.shape[1]

    max_positions = max(2 * config.max_seq_len, MB * BLK)
    cos, sin = _rope_tables(
        config.head_dim, max_positions, config.rope_theta,
        config.use_scaled_rope,
    )

    x = jnp.take(params["embed"]["embedding"], tokens, axis=0).astype(adt)
    # The kernel derives token t's mask/position from positions[:, 0] + t
    # (sublane iota) and treats a row as live or dead as a whole, so the
    # T > 1 contract above is enforced by DEFINITION rather than trust:
    # a row violating it (mixed attn_mask, non-consecutive positions) is
    # folded to inactive — attends nothing, writes nothing — instead of
    # silently corrupting the pool.  [B, T] integer ops, free next to the
    # forward; speculative rounds conform by construction.
    row_active = attn_mask[:, 0]
    if T > 1:
        uniform = jnp.all(attn_mask == attn_mask[:, :1], axis=1)
        consecutive = jnp.all(
            positions
            == positions[:, :1] + jnp.arange(T, dtype=positions.dtype),
            axis=1,
        )
        row_active = row_active & uniform & consecutive
    q_pos_row = jnp.where(row_active, positions[:, 0], -1).astype(jnp.int32)

    block = functools.partial(
        _block,
        config=config,
        positions=q_positions,
        bias=None,
        slot_pos=cache.pos,
        cache_index=None,
        cos=cos,
        sin=sin,
        impl="paged",
        paged_pos=cache.pos,
        paged_table=cache.table,
        paged_qpos=q_pos_row,
        # The FULL pool rides the scan as an invariant closure operand;
        # the kernel selects its layer plane via the scan index below
        # (slicing per layer here materialized each plane as a copy —
        # see the paged branch of _block).
        paged_pools=(cache.k, cache.v, cache.k_scale, cache.v_scale),
    )

    lp = params["layers"]
    nks = nvs = None
    layer_idx = jnp.arange(config.n_layers, dtype=jnp.int32)
    if config.scan_layers:
        def scan_fn(carry, xs):
            layer_params, li = xs
            y, ck, cv, cks, cvs = block(
                carry, layer_params, None, None, paged_layer=li
            )
            ys = (ck, cv, cks, cvs) if cache.quantized else (ck, cv)
            return y, ys

        x, ys = lax.scan(
            scan_fn, x, (lp, layer_idx), unroll=config.scan_unroll
        )
        if cache.quantized:
            new_k, new_v, nks, nvs = ys
        else:
            new_k, new_v = ys
    else:
        new_ks, new_vs, sks, svs = [], [], [], []
        for i in range(config.n_layers):
            layer_params = jax.tree.map(lambda a: a[i], lp)
            x, ck, cv, cks, cvs = block(
                x, layer_params, None, None, paged_layer=layer_idx[i]
            )
            new_ks.append(ck)
            new_vs.append(cv)
            sks.append(cks)
            svs.append(cvs)
        new_k, new_v = jnp.stack(new_ks), jnp.stack(new_vs)
        if cache.quantized:
            nks, nvs = jnp.stack(sks), jnp.stack(svs)

    logits = lm_head_logits(params, x, config) if compute_logits else None

    # Land the step's projections via the shared write-back contract
    # (paged_write_indices — same function serving's gathered-view
    # scatter uses, so the two paths cannot drift).
    active = row_active
    blk_idx, off, _ = paged_write_indices(
        cache.table, cache.fill, active, T, NB, BLK
    )  # [B, T] each
    upd_k = jnp.moveaxis(new_k, 3, 1)  # [L, B, T, KVH, hd] -> [L, KVH, B, T, hd]
    upd_v = jnp.moveaxis(new_v, 3, 1)
    new_cache = dataclasses.replace(
        cache,
        k=paged_pool_write(cache.k, upd_k, blk_idx, off),
        v=paged_pool_write(cache.v, upd_v, blk_idx, off),
        pos=paged_pool_write(
            cache.pos, jnp.where(active[:, None], positions, -1),
            blk_idx, off,
        ),
    )
    if cache.quantized:
        # ys carried each layer's new int8 payload + its scales.
        new_cache = dataclasses.replace(
            new_cache,
            k_scale=paged_pool_write(
                cache.k_scale, jnp.moveaxis(nks, 3, 1), blk_idx, off
            ),
            v_scale=paged_pool_write(
                cache.v_scale, jnp.moveaxis(nvs, 3, 1), blk_idx, off
            ),
        )
    return logits, new_cache
