"""Real-weights dress rehearsal: the full download → convert → Orbax →
generate → parity pipeline as ONE unattended command.

No real Llama weights exist in the development environment, so the
end-to-end path the reference exercises with real checkpoints
(``/root/reference/jax_test.py:427-522``: load, convert, generate, logit
parity vs Meta PyTorch) is rehearsed here three ways:

  * ``--synthetic``: builds a small but real Meta-FORMAT checkpoint
    (sharded ``consolidated.NN.pth`` + ``params.json``, Megatron
    column/row splits), then runs the exact production path: convert →
    Orbax save → sharded Orbax restore → jitted greedy generate → fp32
    logit parity vs the independent torch oracle.  Every step is the same
    code real weights will take.
  * ``--shapes-8b``: abstract (eval_shape) validation at full Llama-3-8B
    geometry — param tree shapes/bytes, partition-spec coverage on a
    virtual 8-device tensor×data mesh, and Orbax save-layout metadata —
    without materializing 16 GB.
  * ``--ckpt-dir ...``: the real thing, unattended, the moment weights
    are available:

        python -m jax_llama_tpu.rehearsal \\
            --ckpt-dir /weights/Meta-Llama-3-8B \\
            --tokenizer /weights/Meta-Llama-3-8B/tokenizer.model \\
            --out /ckpts/llama3-8b-orbax

    (Download first via ``jax-llama-download --presigned-url ...``.)
    Runs convert (fp32-exact tensor reassembly, bf16 storage) → Orbax →
    restore → two greedy completions, and — when a torch oracle is
    importable (``pip install torch``; tests/torch_oracle.py) — last-token
    logit parity in fp32 on a short prompt, reporting the max abs diff
    against the <1e-3 BASELINE target.
"""

from __future__ import annotations

import argparse
import json
import sys
import contextlib
import tempfile
import time
from pathlib import Path

def _log(msg: str) -> None:
    print(f"[rehearsal +{time.perf_counter() - _T0:7.1f}s] {msg}", flush=True)


_T0 = time.perf_counter()


def _write_synthetic_meta_checkpoint(
    tmpdir: Path, *, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    vocab=256, multiple_of=32, n_shards=2, seed=0,
):
    """A miniature checkpoint in Meta's exact on-disk format (the same
    layout ``tests/test_convert.py`` pins against the reference
    converter): torch fp32 tensors, Megatron column/row shard splits,
    ``params.json`` with the SwiGLU sizing fields."""
    import numpy as np
    import torch

    from .config import swiglu_hidden_size

    rng = np.random.RandomState(seed)
    hd = dim // n_heads
    ffn = swiglu_hidden_size(dim, multiple_of)
    full = {
        "tok_embeddings.weight": rng.randn(vocab, dim).astype(np.float32),
        "norm.weight": rng.randn(dim).astype(np.float32),
        "output.weight": rng.randn(vocab, dim).astype(np.float32),
    }
    for l in range(n_layers):
        p = f"layers.{l}."
        full[p + "attention.wq.weight"] = rng.randn(
            n_heads * hd, dim).astype(np.float32)
        full[p + "attention.wk.weight"] = rng.randn(
            n_kv_heads * hd, dim).astype(np.float32)
        full[p + "attention.wv.weight"] = rng.randn(
            n_kv_heads * hd, dim).astype(np.float32)
        full[p + "attention.wo.weight"] = rng.randn(
            dim, n_heads * hd).astype(np.float32)
        full[p + "feed_forward.w1.weight"] = rng.randn(
            ffn, dim).astype(np.float32)
        full[p + "feed_forward.w2.weight"] = rng.randn(
            dim, ffn).astype(np.float32)
        full[p + "feed_forward.w3.weight"] = rng.randn(
            ffn, dim).astype(np.float32)
        full[p + "attention_norm.weight"] = rng.randn(dim).astype(np.float32)
        full[p + "ffn_norm.weight"] = rng.randn(dim).astype(np.float32)

    col_keys = ("wq", "wk", "wv", "w1", "w3", "output")
    row_keys = ("wo", "w2", "tok_embeddings")
    for s in range(n_shards):
        shard = {}
        for key, arr in full.items():
            if any(k in key for k in col_keys):
                shard[key] = torch.from_numpy(
                    np.split(arr, n_shards, axis=0)[s].copy())
            elif any(k in key for k in row_keys):
                shard[key] = torch.from_numpy(
                    np.split(arr, n_shards, axis=1)[s].copy())
            else:
                shard[key] = torch.from_numpy(arr.copy())
        torch.save(shard, tmpdir / f"consolidated.{s:02d}.pth")
    (tmpdir / "params.json").write_text(json.dumps({
        "dim": dim, "n_layers": n_layers, "n_heads": n_heads,
        "n_kv_heads": n_kv_heads, "multiple_of": multiple_of,
        "norm_eps": 1e-5, "rope_theta": 10000.0, "vocab_size": -1,
    }))
    return vocab


def _oracle_module():
    """Import tests/torch_oracle.py when available (repo checkout or an
    installed test extra); None otherwise."""
    try:
        import torch_oracle  # repo layout: tests/ on sys.path

        return torch_oracle
    except ImportError:
        tests_dir = Path(__file__).resolve().parent.parent / "tests"
        if (tests_dir / "torch_oracle.py").exists():
            sys.path.insert(0, str(tests_dir))
            try:
                import torch_oracle

                return torch_oracle
            except ImportError:
                return None
    return None


def _pipeline(ckpt_dir: str, out_dir: str, tokenizer, vocab_size, dtype,
              max_seq_len, prompts, max_gen_len, parity_atol):
    """convert → Orbax save → restore → generate → (optional) parity.

    The shared spine of both the synthetic rehearsal and the real run.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from .convert.checkpoint import load_checkpoint, save_checkpoint
    from .convert.meta import convert_meta_checkpoint
    from .engine import GenerationConfig, generate, prompt_positions

    _log(f"converting Meta checkpoint at {ckpt_dir} (dtype={dtype})")
    params, config = convert_meta_checkpoint(
        ckpt_dir, tokenizer=tokenizer, vocab_size=vocab_size,
        max_seq_len=max_seq_len, dtype=dtype,
    )
    n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))
    _log(f"converted: {n_params / 1e6:.1f}M params, dim={config.dim} "
         f"L={config.n_layers}")

    _log(f"saving Orbax checkpoint to {out_dir}")
    save_checkpoint(out_dir, params, config)
    _log("restoring (sharded restore path)")
    restored, rconfig = load_checkpoint(out_dir)
    assert rconfig == config

    if tokenizer is not None:
        encode = lambda s: tokenizer.encode(s, bos=True, eos=False)
        decode = tokenizer.decode
    else:
        encode = lambda s: [1] + [ord(c) % (vocab_size - 2) + 2 for c in s]
        decode = lambda ids: repr(ids)

    token_lists = [encode(p) for p in prompts]
    P = max(len(t) for t in token_lists)
    toks = np.zeros((len(prompts), P), np.int32)
    pmask = np.zeros((len(prompts), P), bool)
    for i, t in enumerate(token_lists):
        toks[i, P - len(t):] = t
        pmask[i, P - len(t):] = True
    gc = GenerationConfig(
        max_new_tokens=max_gen_len, temperature=0.0, stop_tokens=()
    )
    _log(f"greedy generate: {len(prompts)} prompts, max_gen_len={max_gen_len}")
    out = np.asarray(generate(
        restored, jnp.asarray(toks), jnp.asarray(pmask),
        jax.random.PRNGKey(0), config=config, gen_config=gc,
    ))
    for i, p in enumerate(prompts):
        _log(f"  prompt {i}: {p!r} -> {decode(out[i, P:].tolist())!r}")

    oracle = _oracle_module()
    if oracle is None:
        _log("torch oracle unavailable — skipping logit parity "
             "(pip install torch and run from the repo checkout)")
        return None
    _log("fp32 logit parity vs the independent torch oracle (CPU: an 8B "
         "fp32 forward does not fit one chip's HBM)")
    from .models import forward as model_forward

    fp32_cfg = config.replace(dtype="float32")
    positions = np.asarray(prompt_positions(jnp.asarray(pmask)))
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    host_params = jax.device_get(restored)
    ctx = (
        jax.default_device(cpu) if cpu is not None
        else contextlib.nullcontext()
    )
    with ctx:
        mine = np.asarray(
            jax.jit(
                lambda p, t, q: model_forward(p, t, q, fp32_cfg)[0]
            )(host_params, jnp.asarray(toks), jnp.asarray(positions))
        )
    want = oracle.oracle_forward(host_params, toks, positions, fp32_cfg)
    diff = float(np.max(np.abs(
            mine[pmask].astype(np.float64) - want[pmask].astype(np.float64)
    )))
    _log(f"max abs logit diff (fp32, all valid positions): {diff:.2e} "
         f"(target < {parity_atol})")
    if diff >= parity_atol:
        raise SystemExit(
            f"PARITY FAILURE: {diff:.2e} >= {parity_atol}"
        )
    return diff


def rehearse_synthetic() -> None:
    """Scaled-down end-to-end rehearsal on a synthetic Meta checkpoint."""
    with tempfile.TemporaryDirectory() as td:
        tmp = Path(td)
        ck = tmp / "meta"
        ck.mkdir()
        _log("building synthetic 2-shard Meta-format checkpoint")
        vocab = _write_synthetic_meta_checkpoint(ck)
        diff = _pipeline(
            str(ck), str(tmp / "orbax"), tokenizer=None, vocab_size=vocab,
            dtype="float32", max_seq_len=128,
            prompts=["hello tpu", "paged kv"], max_gen_len=8,
            # fp32 end-to-end on the synthetic model: conversion must be
            # exact, so only accumulation-order noise remains.
            parity_atol=1e-3,
        )
        _log(f"synthetic rehearsal PASSED (parity {diff:.2e})"
             if diff is not None else "synthetic rehearsal PASSED")


def rehearse_8b_shapes() -> None:
    """Abstract full-8B validation: shapes, partition coverage, Orbax
    layout — no weight materialization."""
    import types

    import numpy as np
    import jax

    from . import get_config, init_params
    from .parallel.partition import param_partition_specs, validate_tp

    config = get_config("llama3-8b")
    _log(f"eval_shape at llama3-8b: dim={config.dim} "
         f"L={config.n_layers} H={config.n_heads}/{config.kv_heads} "
         f"V={config.vocab_size}")
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), config)
    )
    total = sum(
        int(np.prod(a.shape)) for a in jax.tree.leaves(shapes)
    )
    _log(f"param tree: {len(jax.tree.leaves(shapes))} leaves, "
         f"{total / 1e9:.2f}B params, "
         f"{total * 2 / 1e9:.1f} GB bf16")
    assert 7.9e9 < total < 8.4e9, total
    # Analytic partition coverage at tensor=4 × data=2 (no devices
    # needed): every leaf must have a spec, every sharded axis must
    # divide, and the resulting largest per-device shard must fit HBM.
    axes = {"tensor": 4, "data": 2, "fsdp": 1, "seq": 1, "stage": 1}
    validate_tp(config, types.SimpleNamespace(shape=axes))
    specs = param_partition_specs(config)
    shard_bytes = []

    def check(leaf, spec):
        shape = list(leaf.shape)
        for dim, ax in enumerate(tuple(spec)):
            if ax is None:
                continue
            for a in (ax if isinstance(ax, tuple) else (ax,)):
                if a is None:
                    continue
                assert shape[dim] % axes[a] == 0, (shape, spec)
                shape[dim] //= axes[a]
        shard_bytes.append(int(np.prod(shape)) * 2)

    jax.tree.map(check, shapes, specs)
    _log(f"partition specs cover all {len(shard_bytes)} leaves at "
         f"tensor=4 × data=2; largest per-device shard "
         f"{max(shard_bytes) / 1e6:.0f} MB bf16 (fits v5e HBM)")
    _log("8B abstract rehearsal PASSED")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--synthetic", action="store_true",
                    help="scaled-down end-to-end rehearsal (no weights "
                         "needed)")
    ap.add_argument("--shapes-8b", action="store_true",
                    help="abstract full-8B shape/partition validation")
    ap.add_argument("--ckpt-dir", default=None,
                    help="real Meta checkpoint directory (consolidated."
                         "NN.pth + params.json)")
    ap.add_argument("--tokenizer", default=None,
                    help="tokenizer.model path (llama3 tiktoken format, "
                         "or --llama2)")
    ap.add_argument("--llama2", action="store_true")
    ap.add_argument("--out", default=None,
                    help="Orbax output directory (default: "
                         "<ckpt-dir>-orbax)")
    ap.add_argument("--max-seq-len", type=int, default=2048)
    ap.add_argument("--max-gen-len", type=int, default=32)
    ap.add_argument("--parity-atol", type=float, default=1e-3)
    args = ap.parse_args()

    if args.synthetic:
        rehearse_synthetic()
    if args.shapes_8b:
        rehearse_8b_shapes()
    if args.ckpt_dir:
        if args.tokenizer is None:
            raise SystemExit("--ckpt-dir needs --tokenizer")
        if args.llama2:
            from .tokenizers.llama2 import LLaMA2Tokenizer as Tok
        else:
            from .tokenizers.llama3 import LLaMA3Tokenizer as Tok
        tok = Tok(args.tokenizer)
        out = args.out or (args.ckpt_dir.rstrip("/") + "-orbax")
        _pipeline(
            args.ckpt_dir, out, tokenizer=tok, vocab_size=None,
            dtype="bfloat16", max_seq_len=args.max_seq_len,
            prompts=[
                "I believe the meaning of life is",
                "Simply put, the theory of relativity states that",
            ],
            max_gen_len=args.max_gen_len, parity_atol=args.parity_atol,
        )
        _log("real-weights rehearsal PASSED")
    if not (args.synthetic or args.shapes_8b or args.ckpt_dir):
        ap.error("pick at least one of --synthetic / --shapes-8b / "
                 "--ckpt-dir")


if __name__ == "__main__":
    main()
