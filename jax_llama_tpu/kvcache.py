"""KV capacity subsystem: radix prefix index + host-DRAM block tier.

Two cooperating parts that multiply how many concurrent chat sessions
one chip's HBM pool can hold (ROADMAP item 3 — SGLang-style
RadixAttention prefix sharing plus a vLLM-style swapped block tier,
adapted to this repo's paged pool and fused-chunk scheduler):

  * **Radix prefix index** (:class:`RadixPrefixStore`).  Replaces the
    batcher's flat exact-chain ``Dict[bytes, block]`` with a
    block-granular radix/trie over token chains: each node is ONE full
    prompt block, keyed by the cumulative chain hash of its tokens
    (``ContinuousBatcher._chain_keys``' invariant: key_j certifies the
    whole prefix up to block j), children keyed by the next block's
    hash.  An admission claims the longest shared block prefix across
    *all* cached chains; divergent chains share their common prefix
    nodes BY CONSTRUCTION instead of superseding each other's blocks
    (the flat map's duplicate-chain churn), eviction is leaves-first
    (a dropped interior node can never strand a resident suffix), and
    per-node residency (HBM block / host slab / gone) is what the host
    tier hangs off.  Refcounts stay block-granular in the batcher
    (``_block_refs``) — the index tracks keyed-ness, LRU order and
    residency, not ownership.
  * **Host-DRAM block tier** (:class:`HostTier`).  Cold (refcount-0,
    LRU-expired) blocks evict INTO a bounded host-memory pool instead
    of being freed: eviction fetches the block's KV (plus scales on
    int8 pools, plus the draft pool's twin under speculative serving)
    to pinned host numpy, and the radix node flips HBM-resident ->
    host-resident, staying matchable.  Admission of a session whose
    prefix blocks were demoted schedules an async swap-in: the slabs
    ``jax.device_put`` into STAGING buffers (pure H2D — deliberately
    NOT on the pool's dependency chain, so decode chunks dispatched
    meanwhile never wait on PCIe), the request parks in the batcher's
    new ``restoring`` admission state, and once the transfer lands
    (``jax.Array.is_ready`` polled at step boundaries, never blocking
    while rows decode) ONE jitted scatter (:func:`adopt_into_pool`, the
    block-migration generalization of the dirty-row ``_scatter_rows``
    machinery) lands the blocks in the pool and the session admits as
    a plain prefix hit — decode rows never stall (``make perf-smoke``
    asserts 0 stall dispatches while a swap-in is in flight).

Three index modes (``run.py --prefix-index``): ``radix`` (the default
— partial-prefix sharing + host tier), ``exact`` (the legacy flat
chain map, kept as the behavioral oracle; no host tier), ``off`` (no
prefix matching or retention — the old ``prefix_cache=False``).

Every store also maintains a :class:`KvDigest` (r13 fleet cache
telemetry): an incrementally-updated, lock-guarded, cross-thread-
readable digest of the published chains — order-independent content
hash, version / loss-version counters, residency aggregates, and a
bounded per-node walk — the sensor the ``/debug/kv`` endpoint, the
``/healthz`` ``kv.digest`` summary, and the router's fleet cache view
(``/debug/kv/fleet``) read.  Digest maintenance is host bookkeeping at
mutation points the store already owns: zero added device dispatches,
zero added host syncs (``make perf-smoke`` pins it).

This module owns only HOST-side bookkeeping plus the three
device-boundary primitives (:func:`fetch_slab` demote D2H,
:func:`stage_restore` async H2D staging, :func:`adopt_into_pool`
scatter); the admission state machine lives in ``serving.py``.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import threading
import uuid
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import pow2_bucket

PREFIX_INDEX_MODES = ("radix", "exact", "off")


# ---------------------------------------------------------------------------
# Chain digest (replica radix digests — the fleet cache view's sensor)
# ---------------------------------------------------------------------------

def _entry_hash(key: bytes, tier: str) -> int:
    """Order-independent per-entry hash: XOR-accumulating these over
    the digest's (key, tier) set yields the same value for the same
    published chains regardless of publish/evict interleaving — the
    determinism the digest-correctness tests pin."""
    h = hashlib.blake2b(key + tier.encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class KvDigest:
    """Incrementally-maintained, cross-thread-readable digest of one
    prefix store's published chains.

    The store (serving-loop thread) calls the ``on_*`` hooks at every
    content mutation; HTTP handler threads read :meth:`summary` (O(1)
    aggregates — the compact form piggybacked on ``/healthz``'s ``kv``
    section for the router poller) and :meth:`nodes_json` (the bounded
    tree walk behind ``GET /debug/kv``).  All state lives under one
    leaf lock (``_lock``; registered in analysis/lockcheck.py), so the
    readers need no racy-read pragmas and the writers pay two dict ops
    per mutation — pure host bookkeeping, zero device work.

    Versioning: ``version`` bumps on every content mutation (publish /
    evict / demote / restore), so a consumer holding an older version
    knows its copy is stale; ``loss_version`` bumps only on mutations
    that can LOSE a chain's HBM residency (evict, demote, host-tier
    drop) — the signal the router's affinity policy consults before
    trusting a pinned session's cache locality.  Both reset when a
    crash-recovery/quarantine rebuild replaces the store (a rebuild
    empties the cache, so any change of version IS staleness —
    consumers compare with ``!=``, not ``>``).

    ``hash`` is an order-independent XOR set-hash over (chain key,
    residency tier): equal for equal published content, cheap to
    maintain under removals (XOR is its own inverse).

    The **event journal** (``_journal``, bounded deque) records every
    content mutation as ``(version, op, key_hex, depth, tier)`` so a
    consumer holding version V can catch up INCREMENTALLY
    (:meth:`events_since`) instead of re-walking the whole tree — the
    router-side global radix index syncs off it, paying O(changes)
    per poll instead of O(nodes).  A consumer whose V fell out of the
    bounded window (or predates a rebuild) gets ``None`` and must
    full-resync via :meth:`nodes_json`."""

    # Journal window: at ~60 B/event this bounds the journal at a few
    # hundred KB while covering thousands of mutations between health
    # polls — a poller more than JOURNAL_MAX versions behind resyncs.
    JOURNAL_MAX = 4096

    def __init__(self):
        self._lock = threading.Lock()
        # Instance identity: versions RESET on rebuild, so a consumer
        # comparing versions alone can be fooled when a rebuild's
        # replay re-advances past its synced version (version
        # aliasing across histories).  The epoch is ctor-stable and
        # unique per digest instance — a consumer that sees it change
        # must full-resync regardless of version arithmetic.
        self.epoch = uuid.uuid4().hex[:16]
        # key -> [depth, tier("hbm"|"host"), idle(bool), seq]
        self._entries: Dict[bytes, List[Any]] = {}
        self._seq = 0
        self._hash = 0
        self._hbm = 0
        self._host = 0
        self._idle = 0
        self.version = 0
        self.loss_version = 0
        self.depth_max = 0  # high-water mark, not current max
        self.publishes_total = 0
        self.evictions_total = 0
        self.demotions_total = 0
        self.restores_total = 0
        self.host_evictions_total = 0
        # (version, op, key_hex, depth, tier) content-mutation journal.
        self._journal: "deque[Tuple[int, str, str, int, str]]" = deque(
            maxlen=self.JOURNAL_MAX
        )

    def _journal_locked(self, op: str, key: bytes, depth: int,
                        tier: str) -> None:
        self._journal.append(
            (self.version, op, key.hex(), int(depth), tier)
        )

    # -- mutation hooks (store/serving-loop thread) -------------------------

    def _set_tier_locked(self, ent: List[Any], key: bytes,
                         tier: str) -> None:
        if ent[1] != tier:
            self._hash ^= _entry_hash(key, ent[1])
            self._hash ^= _entry_hash(key, tier)
            if tier == "hbm":
                self._hbm += 1
                self._host -= 1
            else:
                self._host += 1
                self._hbm -= 1
                if ent[2]:
                    ent[2] = False
                    self._idle -= 1
            ent[1] = tier
        self._seq += 1
        ent[3] = self._seq

    def on_publish(self, key: bytes, depth: int) -> None:
        """A chain block became HBM-resident under ``key`` (fresh node
        or a re-publish adopting a new copy over a demoted one)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self._seq += 1
                self._entries[key] = [int(depth), "hbm", False, self._seq]
                self._hash ^= _entry_hash(key, "hbm")
                self._hbm += 1
                self.depth_max = max(self.depth_max, int(depth))
            else:
                self._set_tier_locked(ent, key, "hbm")
            self.publishes_total += 1
            self.version += 1
            self._journal_locked("publish", key, int(depth), "hbm")

    def on_remove(self, key: bytes) -> None:
        """``key`` left the index entirely (eviction drop, non-finite
        unpublish, host-tier victim's subtree)."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                return
            self._hash ^= _entry_hash(key, ent[1])
            if ent[1] == "hbm":
                self._hbm -= 1
                if ent[2]:
                    self._idle -= 1
            else:
                self._host -= 1
            self.evictions_total += 1
            self.version += 1
            self.loss_version += 1
            self._journal_locked("remove", key, ent[0], ent[1])

    def on_demote(self, key: bytes) -> None:
        """HBM -> host-tier demotion (stays matchable, loses HBM)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            self._set_tier_locked(ent, key, "host")
            self.demotions_total += 1
            self.version += 1
            self.loss_version += 1
            self._journal_locked("demote", key, ent[0], "host")

    def on_restore(self, key: bytes) -> None:
        """Host-tier -> HBM swap-in landed."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            self._set_tier_locked(ent, key, "hbm")
            self.restores_total += 1
            self.version += 1
            self._journal_locked("restore", key, ent[0], "hbm")

    def on_host_evict(self, key: bytes) -> None:
        """The host tier's LRU dropped ``key``'s slab (the node itself
        leaves via :meth:`on_remove` when that strands its subtree)."""
        with self._lock:
            self.host_evictions_total += 1
            self.version += 1
            self.loss_version += 1
            # Journaled so every version bump has a row (exact gap
            # detection in events_since); index consumers ignore the
            # op — the node's REMOVAL, when the slab loss strands it,
            # journals separately via on_remove.
            self._journal_locked("host_evict", key, 0, "host")

    def on_idle(self, key: bytes, idle: bool) -> None:
        """Refcount-boundary flip: idle (refcount 0, evictable) vs
        claimed.  Recency (``seq``) updates; versions do not — claims
        happen every admission and would drown real staleness."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or ent[2] == idle:
                return
            ent[2] = idle
            self._idle += 1 if idle else -1
            self._seq += 1
            ent[3] = self._seq

    # -- readers (any thread) -----------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """O(1) aggregate snapshot — the bounded payload piggybacked on
        ``/healthz``'s ``kv.digest`` section (the router poller scrapes
        it for free; no new poll endpoint)."""
        with self._lock:
            return {
                "epoch": self.epoch,
                "version": self.version,
                "loss_version": self.loss_version,
                "hash": format(self._hash, "016x"),
                "nodes": len(self._entries),
                "hbm_blocks": self._hbm,
                "host_blocks": self._host,
                "idle_blocks": self._idle,
                "depth_max": self.depth_max,
                "publishes_total": self.publishes_total,
                "evictions_total": self.evictions_total,
                "demotions_total": self.demotions_total,
                "restores_total": self.restores_total,
                "host_evictions_total": self.host_evictions_total,
            }

    def events_since(
        self, since: int,
    ) -> Optional[Tuple[List[Dict[str, Any]], int]]:
        """``(events, version)``: content mutations with
        ``version > since`` (oldest first) plus the digest version they
        bring the consumer to, captured under ONE lock hold so the
        pair is never torn — the incremental-sync payload behind
        ``GET /debug/kv?since=V``.

        Returns ``None`` when the journal cannot prove completeness
        and the consumer must full-resync via :meth:`nodes_json`:
        ``since`` beyond the current version (a rebuild reset the
        digest), or the bounded journal already dropped events the
        consumer needs."""
        with self._lock:
            if since > self.version:
                return None  # rebuild reset: consumer is from the past
            if since == self.version:
                return [], self.version
            if not self._journal or self._journal[0][0] > since + 1:
                return None  # window lost events the consumer needs
            return [
                {"version": v, "op": op, "key": k, "depth": d,
                 "tier": t}
                for v, op, k, d, t in self._journal if v > since
            ], self.version

    def nodes_json(self, depth: Optional[int] = None,
                   max_nodes: int = 2048) -> Dict[str, Any]:
        """The full (bounded) tree walk behind ``GET /debug/kv``:
        per-node chain-prefix hash, depth, residency tier, refcount>0
        flag, and recency seq — depth-capped by ``depth`` and
        truncated (shallowest-first, deterministic order) past
        ``max_nodes``, so the payload stays bounded at max radix
        occupancy."""
        with self._lock:
            items = [
                (d, key.hex(), tier, idle, seq)
                for key, (d, tier, idle, seq) in self._entries.items()
                if depth is None or d <= depth
            ]
            version = self.version
        items.sort()
        truncated = max(0, len(items) - max_nodes)
        return {
            "version": version,
            "nodes": [
                {"key": k, "depth": d, "tier": tier,
                 "refcount": not idle, "seq": seq}
                for d, k, tier, idle, seq in items[:max_nodes]
            ],
            "truncated": truncated,
            "depth_cap": depth,
        }


# ---------------------------------------------------------------------------
# Match result (shared by all stores)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MatchResult:
    """Longest cached chain prefix for an admission.

    blocks:  the HBM-RESIDENT hit blocks, contiguous from the root —
             what a no-swap admission reuses (stops at the first
             non-resident node).
    path:    the full reachable node path (radix only; includes
             host-resident nodes past ``blocks``' depth).
    restore: the host-resident nodes on ``path`` needing swap-in before
             the whole path is claimable (empty = plain hit)."""

    blocks: List[int]
    path: List["RadixNode"]
    restore: List["RadixNode"]


# ---------------------------------------------------------------------------
# Host-DRAM tier
# ---------------------------------------------------------------------------

class HostTier:
    """Bounded LRU store of demoted block slabs, keyed by chain hash.

    A *slab* is the plain-numpy image of one pool block —
    ``fetch_slab``'s dict of arrays (k/v/pos, + scales on int8 pools,
    + ``d_``-prefixed draft-pool twins under speculative serving).
    Capacity is counted in BLOCKS; inserting past it evicts the
    least-recently-stored unpinned slab (pinned = mid-swap-in; its
    node's restore must not lose the bytes under it)."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._slabs: "OrderedDict[bytes, Dict[str, np.ndarray]]" = (
            OrderedDict()
        )
        self.pinned: set = set()

    def __len__(self) -> int:
        return len(self._slabs)

    def get(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        return self._slabs.get(key)

    def drop(self, key: bytes) -> None:
        self._slabs.pop(key, None)
        self.pinned.discard(key)

    def put(self, key: bytes, slab: Dict[str, np.ndarray]) -> List[bytes]:
        """Store a slab; returns the keys evicted to make room (their
        nodes lose host residency — the caller drops/strands them)."""
        self._slabs[key] = slab
        evicted: List[bytes] = []
        while len(self._slabs) > self.capacity:
            victim = next(
                (k for k in self._slabs if k not in self.pinned and
                 k != key),
                None,
            )
            if victim is None:
                break  # everything pinned: tolerate transient overflow
            del self._slabs[victim]
            evicted.append(victim)
        return evicted


# ---------------------------------------------------------------------------
# Radix index
# ---------------------------------------------------------------------------

class RadixNode:
    """One full prompt block in the radix tree.

    ``key`` is the block's CUMULATIVE chain hash (position-invariant,
    certifies the whole prefix — ``_chain_keys``), so node identity is
    chain-prefix identity and divergent chains share nodes for free.
    Residency: ``block`` (HBM) and ``host`` (demoted slab, held by the
    tier) are mutually exclusive; both ``None`` only transiently during
    teardown.  ``restoring`` marks an in-flight swap-in — unreachable
    for NEW matches (a second admission racing the swap would double-
    allocate), adopted into ``block`` when the transfer lands."""

    __slots__ = (
        "key", "parent", "children", "block", "host", "depth",
        "restoring",
    )

    def __init__(self, key: bytes, parent: Optional["RadixNode"],
                 depth: int):
        self.key = key
        self.parent = parent
        self.children: Dict[bytes, "RadixNode"] = {}
        self.block: Optional[int] = None
        self.host: Optional[Dict[str, np.ndarray]] = None
        self.depth = depth
        self.restoring = False

    @property
    def reachable(self) -> bool:
        return self.block is not None or (
            self.host is not None and not self.restoring
        )


class RadixPrefixStore:
    """The radix/trie prefix index + host tier (mode ``radix``).

    Interface contract with ``ContinuousBatcher`` (the batcher keeps
    per-block refcounts; the store keeps keyed-ness, tree structure,
    idle-LRU order and residency):

      match(keys)            longest reachable path -> MatchResult
      publish(keys, blocks)  register a freshly prefilled chain;
                             returns idle blocks to free NOW
      unpublish(blk)         non-finite-guard: drop the node AND its
                             subtree (suspect KV must never be hit);
                             returns stranded idle blocks to free
      is_keyed(blk)          retain on last-ref free?
      retain(blocks)         freed keyed blocks -> idle LRU (chain
                             order in; reversed so leaves evict first)
      on_claim(blocks)       admission claimed blocks -> leave LRU
      evictable()            idle count (capacity accounting)
      pop_evictable(demote)  reclaim one idle block, demoting its KV
                             into the host tier when there is room
      pin/unpin/complete_restore   the swap-in lifecycle
    """

    kind = "radix"
    enabled = True

    def __init__(self, host_blocks: int = 0, on_event=None):
        self.root = RadixNode(b"", None, 0)
        self._by_key: Dict[bytes, RadixNode] = {}
        self._by_block: Dict[int, RadixNode] = {}
        # refcount-0 HBM-resident keyed nodes; front = evict first.
        self._idle: "OrderedDict[bytes, RadixNode]" = OrderedDict()
        self.tier = HostTier(host_blocks) if host_blocks > 0 else None
        # Cross-thread-readable chain digest (fleet cache telemetry):
        # updated at every content mutation below, read by /debug/kv
        # and the /healthz kv section from handler threads.
        self.digest = KvDigest()
        # Optional observability sink (obs.Observability.annotate):
        # tier transitions — demotions, host-LRU drops, completed
        # restores — land as instant events in the serving trace, so a
        # /debug/trace window explains WHY a session re-prefilled cold
        # (its slab was the host tier's LRU victim) without log
        # archaeology.  Pure host bookkeeping, never on the decode hot
        # path.
        self._on_event = on_event

    def _event(self, name: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event(name, **fields)

    # -- matching / publication --------------------------------------------

    def match(self, keys: Sequence[bytes]) -> MatchResult:
        path: List[RadixNode] = []
        node = self.root
        for key in keys:
            child = node.children.get(key)
            if child is None or not child.reachable:
                break
            path.append(child)
            node = child
        blocks: List[int] = []
        for n in path:
            if n.block is None:
                break
            blocks.append(n.block)
        restore = [n for n in path if n.block is None]
        return MatchResult(blocks=blocks, path=path, restore=restore)

    def publish(self, keys: Sequence[bytes],
                blocks: Sequence[int]) -> List[int]:
        """Register a freshly prefilled full-prompt chain.  Existing
        RESIDENT nodes keep their block — the publisher's duplicate
        copy stays private/unkeyed and frees plainly with its slot
        (shared-by-construction replaces the flat map's supersede
        churn); a demoted node adopts the fresh HBM copy (newer bytes,
        host slab dropped)."""
        parent = self.root
        for key, blk in zip(keys, blocks):
            node = self._by_key.get(key)
            if node is None:
                node = RadixNode(key, parent, parent.depth + 1)
                parent.children[key] = node
                self._by_key[key] = node
                node.block = blk
                self._by_block[blk] = node
                self.digest.on_publish(key, node.depth)
            elif node.block is None and not node.restoring:
                node.block = blk
                self._by_block[blk] = node
                if node.host is not None:
                    node.host = None
                    if self.tier is not None:
                        self.tier.drop(key)
                self.digest.on_publish(key, node.depth)
            parent = node
        return []

    def unpublish(self, blk: int) -> List[int]:
        node = self._by_block.get(blk)
        if node is None or node.block != blk:
            return []
        return self._drop_subtree(node)

    def _drop_subtree(self, node: RadixNode) -> List[int]:
        """Remove ``node`` and every descendant from the index.  Idle
        (refcount-0 retained) blocks in the subtree are returned for
        the caller to free; blocks with live users merely lose their
        keying and free plainly when their slots do."""
        freed: List[int] = []
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self._by_key.pop(n.key, None)
            self.digest.on_remove(n.key)
            if n.block is not None:
                if self._by_block.get(n.block) is n:
                    del self._by_block[n.block]
                if n.key in self._idle:
                    del self._idle[n.key]
                    freed.append(n.block)
                n.block = None
            if n.host is not None:
                n.host = None
                if self.tier is not None:
                    self.tier.drop(n.key)
            n.restoring = False
        return freed

    # -- refcount-boundary hooks -------------------------------------------

    def is_keyed(self, blk: int) -> bool:
        node = self._by_block.get(blk)
        return node is not None and node.block == blk

    def retain(self, blocks: Sequence[int]) -> None:
        # Later chain blocks enter the LRU first (reversed) so chains
        # evict back-to-front — the leaves-first discipline.
        for blk in reversed(list(blocks)):
            node = self._by_block.get(blk)
            if node is not None and node.block == blk:
                self._idle[node.key] = node
                self.digest.on_idle(node.key, True)

    def on_claim(self, blocks: Sequence[int]) -> None:
        for blk in blocks:
            node = self._by_block.get(blk)
            if node is not None:
                self._idle.pop(node.key, None)
                self.digest.on_idle(node.key, False)

    # -- eviction / demotion -----------------------------------------------

    def evictable(self) -> int:
        return len(self._idle)

    def pop_evictable(
        self,
        demote: Optional[Callable[[int], Dict[str, np.ndarray]]] = None,
    ) -> Tuple[Optional[int], List[int]]:
        """Reclaim one idle keyed block for the allocator.

        With a host tier and a ``demote`` callback the block's KV is
        fetched to a host slab first and the node stays matchable
        (host-resident); otherwise the node is DROPPED — choosing an
        idle node with no reachable children when one exists, so an
        interior drop never strands a resident suffix (the flat map
        relied on insertion order for this; the tree checks).

        Returns ``(block, extra_free)``: the reclaimed block plus any
        additional idle blocks orphaned by a forced subtree drop (the
        caller returns those to the free list)."""
        if not self._idle:
            return None, []
        if self.tier is not None and demote is not None:
            key, node = next(iter(self._idle.items()))
            blk = self._demote_node(key, node, demote)
            return blk, self._host_put(key, node.host)
        # Drop path (no tier): leaves first.
        chosen = None
        for key, node in self._idle.items():
            if not any(c.reachable or c.restoring
                       for c in node.children.values()):
                chosen = node
                break
        if chosen is None:
            chosen = next(iter(self._idle.values()))
        blk = chosen.block
        self._event("kv_evict", block=blk, depth=chosen.depth)
        extra = self._drop_subtree(chosen)
        extra.remove(blk)
        return blk, extra

    def _demote_node(
        self, key: bytes, node: RadixNode,
        demote: Callable[[int], Dict[str, np.ndarray]],
    ) -> int:
        """Demote one idle HBM-resident node into a host slab (caller
        guarantees idleness and residency); returns the freed block.
        The slab lands on ``node.host`` — the caller feeds it to
        :meth:`_host_put` for tier insertion + LRU fallout."""
        blk = node.block
        slab = demote(blk)
        del self._idle[key]
        del self._by_block[blk]
        node.block = None
        node.host = slab
        self.digest.on_demote(key)
        self._event("kv_demote", block=blk, depth=node.depth)
        return blk

    def _host_put(
        self, key: bytes, slab: Dict[str, np.ndarray],
    ) -> List[int]:
        """Insert a demoted slab into the host tier; host-LRU victims
        lose their slab (and their now-unreachable subtrees drop),
        returning any idle blocks that strands for the caller to
        free."""
        extra: List[int] = []
        for ekey in self.tier.put(key, slab):
            enode = self._by_key.get(ekey)
            if enode is None:
                continue
            enode.host = None
            self.digest.on_host_evict(ekey)
            self._event("kv_host_evict", depth=enode.depth)
            if enode.block is None:
                extra.extend(self._drop_subtree(enode))
        return extra

    def demote_keys(
        self,
        keys: Sequence[bytes],
        demote: Optional[
            Callable[[int], Dict[str, np.ndarray]]
        ] = None,
    ) -> List[int]:
        """TARGETED demotion of one exported chain (the
        demote-after-export half of a cross-replica handoff): each
        key's node, if idle and HBM-resident, demotes into the host
        tier (stays matchable) — or, with no tier, DROPS when nothing
        reachable hangs below it (leaves-first; an interior node with
        a resident suffix is kept so the drop never strands it).
        Claimed (refcount>0) nodes are skipped — a live session's KV
        never moves under it.  Returns the freed HBM blocks (plus any
        host-LRU fallout) for the caller to invalidate+free.  Walks
        deepest-first so the no-tier drop path sees leaves before
        their parents."""
        freed: List[int] = []
        for key in reversed(list(keys)):
            node = self._by_key.get(key)
            if (
                node is None or node.block is None
                or key not in self._idle
            ):
                continue
            if self.tier is not None and demote is not None:
                blk = self._demote_node(key, node, demote)
                freed.append(blk)
                freed.extend(self._host_put(key, node.host))
            else:
                if any(c.reachable or c.restoring
                       for c in node.children.values()):
                    continue  # resident suffix below: keep the node
                blk = node.block
                self._event(
                    "kv_evict", block=blk, depth=node.depth
                )
                freed.extend(self._drop_subtree(node))
        return freed

    # -- swap-in lifecycle --------------------------------------------------

    def pin_restoring(self, nodes: Sequence[RadixNode]) -> None:
        for n in nodes:
            n.restoring = True
            if self.tier is not None:
                self.tier.pinned.add(n.key)

    def unpin_restoring(self, nodes: Sequence[RadixNode]) -> None:
        """Abort a swap-in (injected failure / cancel): the nodes stay
        host-resident and matchable again."""
        for n in nodes:
            n.restoring = False
            if self.tier is not None:
                self.tier.pinned.discard(n.key)

    def complete_restore(self, nodes: Sequence[RadixNode],
                         blocks: Sequence[int]) -> None:
        """The swap-in landed: nodes flip host-resident -> HBM-resident
        under their freshly scattered blocks (claimed by the admission,
        so NOT idle), slabs leave the tier."""
        for n, blk in zip(nodes, blocks):
            n.block = blk
            self._by_block[blk] = n
            n.host = None
            n.restoring = False
            if self.tier is not None:
                self.tier.drop(n.key)
            self.digest.on_restore(n.key)
        if nodes:
            self._event("kv_restore_complete", blocks=len(nodes))

    # -- observability -------------------------------------------------------

    def cached_blocks(self) -> int:
        return len(self._idle)

    def nodes_total(self) -> int:
        return len(self._by_key)

    def host_blocks(self) -> int:
        return len(self.tier) if self.tier is not None else 0

    def resident_chains(self) -> List[List[bytes]]:
        """Every maximal HBM-resident chain as its ordered key path
        (root child → deepest resident node) — the drain/migration
        enumeration surface.  A path is cut at the first non-HBM node
        (demoted or restoring): only the contiguous resident prefix can
        be exported, exactly what ``export_prefix`` would move.  Nodes
        whose chain continues resident are not emitted separately —
        their keys appear as prefixes of the longer chain."""
        chains: List[List[bytes]] = []
        stack: List[Tuple[RadixNode, List[bytes]]] = [(self.root, [])]
        while stack:
            node, path = stack.pop()
            nxt = [c for c in node.children.values() if c.block is not None]
            if not nxt and path:
                chains.append(path)
            for child in nxt:
                stack.append((child, path + [child.key]))
        return chains


# ---------------------------------------------------------------------------
# Exact (legacy) and off modes
# ---------------------------------------------------------------------------

class ExactPrefixStore:
    """The pre-radix flat chain map (mode ``exact``), kept as the
    behavioral oracle: one ``Dict[bytes, block]`` keyed by cumulative
    chain hash, duplicate publications SUPERSEDE (the old
    ``_register_chain`` churn), eviction is pure insertion-order LRU,
    and there is no host tier."""

    kind = "exact"
    enabled = True

    def __init__(self):
        self._prefix_index: Dict[bytes, int] = {}
        self._block_chain: Dict[int, bytes] = {}
        self._reusable: "OrderedDict[int, None]" = OrderedDict()
        # Flat-map digest: depth = chain index + 1 (no tree, but the
        # same versioned surface every store exposes).
        self.digest = KvDigest()

    def match(self, keys: Sequence[bytes]) -> MatchResult:
        hits: List[int] = []
        for key in keys:
            blk = self._prefix_index.get(key)
            if blk is None:
                break
            hits.append(blk)
        return MatchResult(blocks=hits, path=[], restore=[])

    def publish(self, keys: Sequence[bytes],
                blocks: Sequence[int]) -> List[int]:
        superseded: List[int] = []
        for depth, (blk, key) in enumerate(zip(blocks, keys)):
            old = self._prefix_index.get(key)
            if old is not None and old != blk:
                self._block_chain.pop(old, None)
                if old in self._reusable:
                    del self._reusable[old]
                    superseded.append(old)
                # The key now binds the freshly published (claimed)
                # block: clear any idle flag inherited from the
                # superseded one, or /debug/kv would report a live
                # session's block as evictable for its whole life.
                self.digest.on_idle(key, False)
            self._block_chain[blk] = key
            self._prefix_index[key] = blk
            self.digest.on_publish(key, depth + 1)
        return superseded

    def unpublish(self, blk: int) -> List[int]:
        key = self._block_chain.pop(blk, None)
        if key is not None and self._prefix_index.get(key) == blk:
            del self._prefix_index[key]
            self.digest.on_remove(key)
        return []

    def is_keyed(self, blk: int) -> bool:
        return blk in self._block_chain

    def retain(self, blocks: Sequence[int]) -> None:
        for blk in reversed(list(blocks)):
            self._reusable[blk] = None
            key = self._block_chain.get(blk)
            if key is not None:
                self.digest.on_idle(key, True)

    def on_claim(self, blocks: Sequence[int]) -> None:
        for blk in blocks:
            self._reusable.pop(blk, None)
            key = self._block_chain.get(blk)
            if key is not None:
                self.digest.on_idle(key, False)

    def evictable(self) -> int:
        return len(self._reusable)

    def pop_evictable(self, demote=None) -> Tuple[Optional[int], List[int]]:
        if not self._reusable:
            return None, []
        blk, _ = self._reusable.popitem(last=False)
        self.unpublish(blk)
        return blk, []

    def demote_keys(self, keys, demote=None) -> List[int]:
        """Demote-after-export is a radix/tier feature; the exact
        oracle keeps its published chains in place."""
        return []

    def pin_restoring(self, nodes) -> None:  # pragma: no cover - no tier
        raise AssertionError("exact store has no host tier")

    unpin_restoring = complete_restore = pin_restoring

    def cached_blocks(self) -> int:
        return len(self._reusable)

    def nodes_total(self) -> int:
        return len(self._prefix_index)

    def host_blocks(self) -> int:
        return 0

    def resident_chains(self) -> List[List[bytes]]:
        """Flat map: no parent links, so chains cannot be reassembled —
        each published key is emitted as its own depth-1 chain.  Because
        ``match`` looks every cumulative key up independently, importing
        these singletons on another replica reproduces the same hit
        surface; only the radix store's shared-prefix structure is
        lost (it never existed here)."""
        return [[key] for key in self._prefix_index]


class NullPrefixStore:
    """Mode ``off``: nothing matches, nothing is retained."""

    kind = "off"
    enabled = False

    def __init__(self):
        self.digest = KvDigest()  # permanently empty, version 0

    def match(self, keys) -> MatchResult:
        return MatchResult(blocks=[], path=[], restore=[])

    def publish(self, keys, blocks) -> List[int]:
        return []

    def unpublish(self, blk) -> List[int]:
        return []

    def is_keyed(self, blk) -> bool:
        return False

    def retain(self, blocks) -> None:
        pass

    def on_claim(self, blocks) -> None:
        pass

    def evictable(self) -> int:
        return 0

    def pop_evictable(self, demote=None) -> Tuple[Optional[int], List[int]]:
        return None, []

    def demote_keys(self, keys, demote=None) -> List[int]:
        return []

    def cached_blocks(self) -> int:
        return 0

    def nodes_total(self) -> int:
        return 0

    def host_blocks(self) -> int:
        return 0

    def resident_chains(self) -> List[List[bytes]]:
        return []


def make_prefix_store(mode: str, host_blocks: int = 0, on_event=None):
    """Store factory.  The host tier only attaches to the radix index
    (``exact`` is the legacy oracle, ``off`` retains nothing — in both
    a nonzero ``host_blocks`` is inert by design: the degradation
    layer's prefix-cache quarantine rebuilds with the cache off and
    must not trip a constructor error over the tier flag).
    ``on_event`` (radix only) is an observability sink for tier
    transitions — the batcher wires ``obs.Observability.annotate`` so
    demote/host-evict/restore events land in the serving trace."""
    if mode not in PREFIX_INDEX_MODES:
        raise ValueError(
            f"unknown prefix_index mode {mode!r}; have {PREFIX_INDEX_MODES}"
        )
    if mode == "radix":
        return RadixPrefixStore(host_blocks=host_blocks,
                                on_event=on_event)
    if mode == "exact":
        return ExactPrefixStore()
    return NullPrefixStore()


# ---------------------------------------------------------------------------
# Device-boundary primitives (demote fetch / staged swap-in / adoption)
# ---------------------------------------------------------------------------

# Slab array names in pool order; the draft pool's twins carry the
# ``d_`` prefix.  ``pos`` is per-block [BLK]; k/v are [L, KVH, BLK, hd];
# scales (int8 pools only) are [L, KVH, BLK].
_POOL_FIELDS = ("k", "v", "pos", "k_scale", "v_scale")


def _pool_names(pool) -> Tuple[str, ...]:
    return _POOL_FIELDS if pool.k_scale is not None else _POOL_FIELDS[:3]


def pool_block_bytes(pool) -> int:
    """Bytes of pool memory ONE block occupies (k + v + pos + scales on
    int8 pools) — the unit the router's duplicate-chain accounting
    multiplies node counts by.  Every pool array carries exactly one
    n_blocks axis, so total bytes / n_blocks is exact.  Host-side
    metadata arithmetic only (``nbytes`` never touches buffers)."""
    n_blocks = pool.pos.shape[0]
    total = sum(getattr(pool, name).nbytes for name in _pool_names(pool))
    return int(total // max(1, n_blocks))


def fetch_slab(pool, blk: int, prefix: str = "") -> Dict[str, np.ndarray]:
    """Demotion D2H: one block's KV image as plain numpy (synchronous —
    demotion happens on the admission path, where the allocator already
    owns the step boundary).  Must run BEFORE the caller invalidates
    the block's pool positions (the slab keeps the live ``pos`` row the
    future restore re-installs)."""
    out: Dict[str, np.ndarray] = {}
    for name in _pool_names(pool):
        arr = getattr(pool, name)
        sl = arr[blk] if name == "pos" else arr[:, :, blk]
        # audit: host-fetch(demotion D2H on the admission/capacity
        # path — counted in swap_out_blocks_total, never in
        # host_syncs_total, see _demote_block)
        out[prefix + name] = np.asarray(sl)
    return out


def stage_restore(
    slabs: Sequence[Dict[str, np.ndarray]],
    block_ids: Sequence[int],
    sentinel: int,
    placements: Optional[Dict[str, object]] = None,
) -> Dict[str, jax.Array]:
    """Swap-in H2D: stack the slabs along the block axis and
    ``jax.device_put`` them into STAGING buffers.  The transfer is
    async and independent of the pool arrays — decode chunks dispatched
    while it is in flight have no data dependency on it, which is what
    makes the overlap real (enqueueing the pool scatter immediately
    would chain every subsequent chunk behind the PCIe copy).
    Readiness = every staged array ``.is_ready()``.

    ``block_ids`` are the fresh HBM blocks the adoption scatter will
    land in, padded to a pow2 bucket with ``sentinel`` (out-of-range:
    the scatter drops pad rows) so the jit cache of
    :func:`adopt_into_pool` stays O(log max-restore-depth).

    ``placements`` (serving-mesh pools;
    ``parallel.serve_mesh.staging_shardings``) maps staged field names
    to Shardings so each buffer lands PRE-SHARDED with the pool's own
    layout — every tensor shard stages its KV-head slice of the slab
    and the adoption scatter stays shard-local (no cross-shard reshard
    on the adopt dispatch).  None keeps default placement."""
    n = len(slabs)
    nb = pow2_bucket(n)
    ids = np.full((nb,), sentinel, np.int32)
    ids[:n] = list(block_ids)
    placements = placements or {}
    staged: Dict[str, jax.Array] = {
        "ids": jax.device_put(ids, placements.get("ids"))
    }
    for name in slabs[0]:
        arrs = [s[name] for s in slabs]
        axis = 0 if name.endswith("pos") else 2
        stacked = np.stack(arrs, axis=axis)
        if nb > n:
            pad_shape = list(stacked.shape)
            pad_shape[axis] = nb - n
            stacked = np.concatenate(
                [stacked, np.zeros(pad_shape, stacked.dtype)], axis=axis
            )
        # audit: host-upload(slab staging H2D, deliberately OFF the
        # pool's dependency chain — the async transfer decode chunks
        # never queue behind; one per restored pool field)
        staged[name] = jax.device_put(stacked, placements.get(name))
    return staged


def restore_ready(staged: Dict[str, jax.Array]) -> bool:
    """Non-blocking readiness poll of a staged swap-in."""
    return all(a.is_ready() for a in staged.values())


@functools.partial(jax.jit, donate_argnums=(0,))
def _adopt_jit(pool_arrays: Tuple[jnp.ndarray, ...], ids: jnp.ndarray,
               staged: Tuple[jnp.ndarray, ...]):
    out = []
    for a, s in zip(pool_arrays, staged):
        if a.ndim == 2:  # pos: [NB, BLK] <- [n, BLK]
            out.append(a.at[ids].set(s.astype(a.dtype), mode="drop"))
        else:            # k/v/scales: [L, KVH, NB, ...] <- [L, KVH, n, ...]
            out.append(a.at[:, :, ids].set(s.astype(a.dtype), mode="drop"))
    return tuple(out)


def adopt_into_pool(pool, staged: Dict[str, jax.Array], prefix: str = ""):
    """ONE jitted scatter landing a completed swap-in's staged blocks in
    the pool — the block-migration generalization of serving's
    dirty-row ``_scatter_rows`` sync (pool arrays donated; sentinel pad
    rows drop).  Called only once the staging transfer is ready, so the
    dispatch is device-to-device and cheap; returns the updated pool."""
    names = _pool_names(pool)
    arrays = tuple(getattr(pool, name) for name in names)
    new = _adopt_jit(
        arrays, staged["ids"], tuple(staged[prefix + n] for n in names)
    )
    return dataclasses.replace(pool, **dict(zip(names, new)))


def adopt_lower(pool, staged: Dict[str, jax.Array], prefix: str = ""):
    """AOT lowering of the adopt scatter with the exact args
    :func:`adopt_into_pool` dispatches — the device-time attribution
    hook (obs.CostModelCache) reads FLOPs/bytes off its cost_analysis.
    Trace-time host work only: lowering never touches buffers."""
    names = _pool_names(pool)
    arrays = tuple(getattr(pool, name) for name in names)
    return _adopt_jit.lower(
        arrays, staged["ids"], tuple(staged[prefix + n] for n in names)
    )
