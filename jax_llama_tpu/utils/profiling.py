"""Tracing / profiling / throughput observability.

The reference has none of this (SURVEY.md §5: "Tracing / profiling: Absent
— only leftover debug prints", ``/root/reference/jax_llama/model.py:636``);
this module provides the TPU-native equivalents the survey prescribes:
``jax.profiler`` xplane traces viewable in TensorBoard/XProf, wall-clock
timers that block on device work, and tokens/sec/chip decode counters (the
BASELINE.json metric).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace (xplane format) into ``log_dir``.

    View with TensorBoard's profile plugin or xprof.  Wrap the steady-state
    region only — include one warm-up call outside the context so compile
    time does not dominate the trace.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class Timer:
    """Wall-clock timer that waits for in-flight device work on both edges,
    so the measured window covers exactly the enclosed computation."""

    elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        _block_on_pending()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        _block_on_pending()
        self.elapsed_s = time.perf_counter() - self._t0


def _block_on_pending() -> None:
    # effects_barrier waits for all dispatched-but-unfinished computations.
    jax.effects_barrier()


@dataclasses.dataclass
class DecodeStats:
    """Throughput accounting for one generation call.

    tokens/sec figures are per chip: divide by ``n_devices`` so multi-chip
    meshes report the BASELINE.json metric (tokens/sec/chip) directly.
    """

    batch: int
    prompt_len: int
    new_tokens: int
    prefill_s: float
    decode_s: float
    n_devices: int = 1

    @property
    def decode_tokens_per_s(self) -> float:
        return self.batch * self.new_tokens / max(self.decode_s, 1e-9)

    @property
    def decode_tokens_per_s_per_chip(self) -> float:
        return self.decode_tokens_per_s / self.n_devices

    @property
    def prefill_tokens_per_s(self) -> float:
        return self.batch * self.prompt_len / max(self.prefill_s, 1e-9)

    @property
    def per_token_latency_ms(self) -> float:
        return 1e3 * self.decode_s / max(self.new_tokens, 1)

    def summary(self) -> str:
        prefill = (
            f"prefill {self.prefill_tokens_per_s:,.0f} tok/s | "
            if self.prefill_s > 0
            else ""
        )
        return (
            f"{prefill}decode "
            f"{self.decode_tokens_per_s_per_chip:,.1f} tok/s/chip "
            f"({self.per_token_latency_ms:.2f} ms/tok, batch {self.batch})"
        )
