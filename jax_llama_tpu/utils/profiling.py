"""Tracing / profiling / throughput observability.

The reference has none of this (SURVEY.md §5: "Tracing / profiling: Absent
— only leftover debug prints", ``/root/reference/jax_llama/model.py:636``);
this module provides the TPU-native equivalents the survey prescribes:
``jax.profiler`` xplane traces viewable in TensorBoard/XProf, wall-clock
timers that block on device work, and tokens/sec/chip decode counters (the
BASELINE.json metric).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import glob
import os
import re
import shutil
import tempfile
import time
from typing import Callable, Dict, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace (xplane format) into ``log_dir``.

    View with TensorBoard's profile plugin or xprof.  Wrap the steady-state
    region only — include one warm-up call outside the context so compile
    time does not dominate the trace.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class Timer:
    """Wall-clock timer that waits for in-flight device work on both edges,
    so the measured window covers exactly the enclosed computation."""

    elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        _block_on_pending()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        _block_on_pending()
        self.elapsed_s = time.perf_counter() - self._t0


def _block_on_pending() -> None:
    # effects_barrier waits for all dispatched-but-unfinished computations.
    jax.effects_barrier()


def device_op_times(
    thunk: Callable[[], None],
    *,
    by: str = "op",
    device_substr: str = "TPU",
) -> Dict[str, int]:
    """Run ``thunk`` under a profiler trace and return device-op time in
    PICOSECONDS aggregated by HLO op name (``by="op"``) or by the source
    file XLA attributes the op to (``by="source"``).

    This is the measurement primitive behind every perf number in
    bench.py/ROADMAP.md: wall-clock timing of a single dispatch in a
    tunneled/dev environment measures the dispatch overhead, not the op
    (a 13 ms kernel reads as ~110 ms), while device-op durations from
    the xplane are stable to ~0.01% run-to-run.  Caller contract: warm
    the thunk (compile) BEFORE calling, or the trace will be dominated
    by compilation; outer ``%while`` ops are dropped so loop bodies are
    not double-counted.

    Requires the TensorFlow profiler protos (`tensorflow.tsl`); raises
    ImportError where unavailable.
    """
    assert by in ("op", "source"), by
    tmpdir = tempfile.mkdtemp(prefix="jlt_xplane_")
    try:
        # trace() stops the profiler even when thunk raises — a leaked
        # active profiler would fail every later start_trace in the
        # process, cascading one failure into many.
        with trace(tmpdir):
            thunk()
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        path = glob.glob(f"{tmpdir}/**/*.xplane.pb", recursive=True)[0]
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    plane = next(p for p in space.planes if device_substr in p.name)
    stat_names = {k: v.name for k, v in plane.stat_metadata.items()}
    op_name, op_src = {}, {}
    for k, v in plane.event_metadata.items():
        op_name[k] = v.name
        src = next(
            (
                st.str_value
                for st in v.stats
                if stat_names.get(st.metadata_id) == "source"
            ),
            "",
        )
        m = re.search(r"/(\w+\.py):", src)
        op_src[k] = m.group(1) if m else "other"
    line = next(ln for ln in plane.lines if ln.name == "XLA Ops")
    agg: Dict[str, int] = collections.Counter()
    key = op_name if by == "op" else op_src
    for e in line.events:
        if op_name[e.metadata_id].startswith("%while"):
            continue  # outer loops double-count their bodies
        agg[key[e.metadata_id]] += e.duration_ps
    return agg


# Event-name spellings that carry a jitted-program identity in an
# xplane capture: the host plane's python line traces dispatch frames
# as ``PjitFunction(<name>)``, and device planes' "XLA Modules" line
# names executables ``jit_<name>`` (sometimes with a ``.N`` or
# ``(...)`` specialization suffix).
_PJIT_RE = re.compile(r"^PjitFunction\((.+)\)$")
_JIT_MODULE_RE = re.compile(r"^jit_(.+?)(?:\.\d+)?$")


def normalize_program_name(event_name: str):
    """The serving-program name behind an xplane event name, or None
    for events that are not jitted-program roots (individual HLO ops,
    host syscalls, ...)."""
    m = _PJIT_RE.match(event_name)
    if m:
        return m.group(1)
    m = _JIT_MODULE_RE.match(event_name)
    if m:
        return m.group(1)
    return None


def summarize_xplane(log_dir: str) -> Dict[str, object]:
    """Aggregate the newest xplane capture under ``log_dir`` into
    per-jitted-program time attribution.

    Device planes (name contains TPU/GPU) attribute their "XLA
    Modules" line — executable-granular device time, the number the
    MXU-gap investigation needs; the host plane's ``PjitFunction``
    frames attribute host-side dispatch time (on a CPU-only capture
    that is the only signal, and it still answers "which program").
    Raises ImportError when the TensorFlow xplane protos are absent
    and FileNotFoundError when ``log_dir`` holds no capture — the
    /debug/profile/summary endpoint maps both to clean HTTP errors.
    """
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(f"{log_dir}/**/*.xplane.pb", recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise FileNotFoundError(
            f"no .xplane.pb capture under {log_dir!r}"
        )
    path = paths[-1]
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    device_ms: Dict[str, float] = collections.defaultdict(float)
    host_ms: Dict[str, float] = collections.defaultdict(float)
    for plane in space.planes:
        is_device = any(t in plane.name for t in ("TPU", "GPU"))
        names = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if is_device and line.name != "XLA Modules":
                continue  # per-op lines double-count their module
            for e in line.events:
                prog = normalize_program_name(
                    names.get(e.metadata_id, "")
                )
                if prog is None:
                    continue
                sink = device_ms if is_device else host_ms
                sink[prog] += e.duration_ps / 1e9
    programs = sorted(set(device_ms) | set(host_ms))
    return {
        "xplane": path,
        "programs": {
            p: {
                "device_ms": round(device_ms.get(p, 0.0), 3),
                "host_ms": round(host_ms.get(p, 0.0), 3),
            }
            for p in programs
        },
        "total_device_ms": round(sum(device_ms.values()), 3),
        "total_host_ms": round(sum(host_ms.values()), 3),
    }


@dataclasses.dataclass
class DecodeStats:
    """Throughput accounting for one generation call.

    tokens/sec figures are per chip: divide by ``n_devices`` so multi-chip
    meshes report the BASELINE.json metric (tokens/sec/chip) directly.
    """

    batch: int
    prompt_len: int
    new_tokens: int
    prefill_s: float
    decode_s: float
    n_devices: int = 1

    @property
    def decode_tokens_per_s(self) -> float:
        return self.batch * self.new_tokens / max(self.decode_s, 1e-9)

    @property
    def decode_tokens_per_s_per_chip(self) -> float:
        return self.decode_tokens_per_s / self.n_devices

    @property
    def prefill_tokens_per_s(self) -> float:
        return self.batch * self.prompt_len / max(self.prefill_s, 1e-9)

    @property
    def per_token_latency_ms(self) -> float:
        return 1e3 * self.decode_s / max(self.new_tokens, 1)

    def summary(self) -> str:
        prefill = (
            f"prefill {self.prefill_tokens_per_s:,.0f} tok/s | "
            if self.prefill_s > 0
            else ""
        )
        return (
            f"{prefill}decode "
            f"{self.decode_tokens_per_s_per_chip:,.1f} tok/s/chip "
            f"({self.per_token_latency_ms:.2f} ms/tok, batch {self.batch})"
        )
