from .profiling import DecodeStats, Timer, trace

__all__ = ["DecodeStats", "Timer", "trace"]
