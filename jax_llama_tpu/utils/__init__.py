from .profiling import DecodeStats, Timer, device_op_times, trace

__all__ = ["DecodeStats", "Timer", "device_op_times", "trace"]
