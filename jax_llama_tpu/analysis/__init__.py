"""Invariant auditor: static-analysis contracts for the serving stack.

The serving stack's load-bearing guarantees — 1 packed fetch + 0
steady-state uploads per chunk, donated pool carries, no full-pool-copy
lowerings, single-owner batcher state — were enforced only by runtime
smoke tests (``make perf-smoke``) and two hand-written HLO pins
(``tests/test_tpu_compiled.py``).  This package turns them into
machine-checked contracts, runnable on any backend in seconds:

  * :mod:`.hostsync`  — **host-boundary lint** (AST + taint): flags
    device->host syncs (``np.asarray`` on device values, ``float()`` /
    ``.item()`` on tracers, ``block_until_ready``, ``jax.device_get``),
    Python control flow on device values, and ``jnp.*`` construction
    inside host loops; every sanctioned crossing carries an
    ``# audit: host-fetch(<reason>)``-style pragma, so
    ``grep 'audit: host-fetch'`` lists the stack's entire device->host
    surface with justifications.
  * :mod:`.lowering` + :mod:`.contracts` — **lowering auditor**
    (jaxpr/StableHLO): a declarative registry where every jitted
    program the batcher dispatches declares its donated args, its
    live-output (host-fetchable) surface and byte budget, and the
    forbidden full-pool-copy equation classes; the auditor lowers each
    program at a tiny example shape and verifies donation actually
    resolves to input-output aliases.  New programs must register a
    contract — the coverage check fails on any unregistered jitted
    function in serving.py / kvcache.py.
  * :mod:`.lockcheck` — **lock-discipline checker** (AST): a guarded-
    field registry for ``Observability`` / ``DegradeManager`` /
    ``LLMServer`` (lock-guarded) and ``ContinuousBatcher`` /
    ``LLMServer`` (owner-thread-confined); unguarded touches need an
    ``# audit: racy-read(...)`` / ``locked(...)`` / ``unguarded(...)``
    pragma carrying the safety argument.
  * :mod:`.retrace` — **retrace auditor** (AST dataflow + runtime
    drill): every value entering a registered program's jit cache key
    — static args and admission-shaped dims — must flow through a
    bounded-domain constructor (``pow2_bucket``, a clamp, a bool, a
    ctor-stable attribute); each contract declares ``max_cache_keys``
    and a real-batcher admission sweep asserts
    ``serving.jit_cache_entries()`` stays within it.  Sanction with
    ``# audit: trace-domain(...)``.
  * :mod:`.comms` — **comms-budget contracts** (compiled sharded
    lowering + jaxpr): per-program collective counts/bytes against a
    declared :class:`~.contracts.CommsBudget`; a full-pool-shaped
    collective is a hard finding (the silent reshard class
    mesh-sharding-drift cannot see).
  * :mod:`.schedules` — **schedule explorer**: every ``racy-read`` /
    ``unguarded`` pragma maps to a deterministic interleaving model
    over the real classes (preemption-exploring the real readers
    line-by-line against the writers' declared critical regions under
    a virtual clock); a pragma with no passing model is a finding.
  * :mod:`.metricscheck` — **metrics-registry lint**: ``obs.METRICS``
    names must be emitted somewhere and every provider-emitted scalar
    must be registered — statically, for every configuration.

Run everything with ``python -m jax_llama_tpu.analysis`` (exit 0 =
clean) or ``make lint-invariants``; ``make check`` stacks the ruff
gate, the fast analysis tests and perf-smoke on top as the single
pre-PR gate.  Tier-1 runs the same checks via
``tests/test_analysis.py`` (``pytest -m analysis``), so a violating
change fails CI before any bench round notices.  The pragma grammar
and the how-to for registering a new program's contract live in
README.md ("Static analysis").
"""

from .common import Finding, Pragmas  # noqa: F401
from .contracts import (  # noqa: F401
    REGISTRY, CommsBudget, ProgramContract,
)
from .hostsync import AUDITED_MODULES, HostBoundaryChecker  # noqa: F401
from .lockcheck import (  # noqa: F401
    CONFINEMENTS, LOCK_GUARDS, LockDisciplineChecker, LockGuard,
    ThreadConfinement,
)
from .lowering import LoweringAuditor  # noqa: F401

from typing import List


def run_all(trace: bool = True) -> List[Finding]:
    """Run every checker over the package; [] means clean.  ``trace``
    gates the compile-heavy layers (abstract-trace lowering, comms
    budgets, the retrace jit-cache drill)."""
    from . import comms, metricscheck, retrace, schedules

    findings: List[Finding] = []
    findings.extend(HostBoundaryChecker().check_package())
    findings.extend(LockDisciplineChecker().check_package())
    findings.extend(LoweringAuditor().check_package(trace=trace))
    findings.extend(retrace.check_static())
    if trace:
        findings.extend(retrace.check_runtime())
        findings.extend(comms.check_package())
    findings.extend(schedules.check_package())
    findings.extend(metricscheck.check_package())
    return findings
